"""North-star benchmark: coproc JSON-filter transform at 64 partitions.

Measures record_batches/sec through the TPU engine (BASELINE.md config 4
shape: JSON filter + project to a fixed struct, 64 partitions, zstd output)
against a single-core host baseline that mirrors what the reference's
Node.js sidecar does per record (decode framing, JSON parse, predicate,
re-encode, re-CRC — src/js/modules/rpc/server.ts:244-266).

The engine is measured the way a broker drives it: a steady stream of ticks
with GROUP ticks fused per launch and DEPTH launches in flight
(submit_group / Ticket.result — coproc/engine.py). The spec is a v2
where-expression, so the engine runs its columnar pushdown path: the native
columnarizer ships per-field columns up, the device evaluates the predicate
tree, one bit per record comes back, and outputs are assembled, framed,
recompressed, and resealed host-side — the clock runs from first submit to
the last fully-rebuilt reply.

Secondary metrics ride in the same JSON line:
- config 1 = produce-path batch CRC validation through the measured adapter
  boundary (ops/crc_backend.py): BOTH host and device rates plus the
  backend pick() chose.
- config 2 = 16-partition LZ4 produce codec path.
- config 3 = identity transform through the engine at 16 partitions (the
  engine routes identity to its host stage — no device work exists for it),
  plus config3_payload_bridge_16p = the same identity FORCED through the
  full-row device staging path, the honest bridge-overhead number
  (comparable to BENCH_r03's config3 collapse).
- "stages" = the engine's per-stage wall/bytes breakdown for the headline
  run; "link" = a quick device-link profile (RTT + H2D MB/s), so every
  BENCH artifact carries the physics that justified the architecture.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import time

import numpy as np

P = 64  # partitions
RECORDS_PER_BATCH = 32
RECORD_JSON_PAD = 900  # ~1KB records
ROW_STRIDE = 1152
GROUP = int(os.environ.get("BENCH_GROUP", "16"))  # ticks fused per launch
DEPTH = int(os.environ.get("BENCH_DEPTH", "3"))  # launch groups in flight
# long enough that DEPTH-deep pipelining reaches steady state: with 3
# launch groups the fill+drain tunnel round trips (~2x67ms) dominate a
# ~0.27s run and understate the sustained rate by ~40%
MEASURE_TICKS = int(os.environ.get("BENCH_TICKS", "160"))
BASELINE_TICKS = int(os.environ.get("BENCH_BASELINE_TICKS", "4"))
# Host-stage pool size for the headline runs (coproc/host_pool.py). The
# workers=1 ablation rides in the same JSON so every BENCH artifact proves
# the pool-off path did not regress.
HOST_WORKERS = int(os.environ.get("BENCH_HOST_WORKERS", "4"))


def _probe_tpu(timeout_s: int = 150) -> bool:
    """Check TPU health in a subprocess (the tunnel can hang indefinitely).

    On timeout the child gets SIGTERM (graceful) and only SIGKILL as a last
    resort: a SIGKILL mid-TPU-init is known to wedge the axon tunnel for
    every later process (see .claude/skills/verify/SKILL.md).
    """
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return b"ok" in (out or b"")
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        return False
    except Exception:
        return False


def _pin_cpu():
    from redpanda_tpu.utils.platform import force_cpu_platform

    force_cpu_platform()


def _build_workload(n_partitions=P, topic="bench"):
    from redpanda_tpu.models import Record, RecordBatch, NTP
    from redpanda_tpu.coproc.engine import ProcessBatchItem, ProcessBatchRequest

    rng = np.random.default_rng(0)
    levels = ["error", "info", "warn"]
    items = []
    for p in range(n_partitions):
        recs = []
        for i in range(RECORDS_PER_BATCH):
            doc = '{"level":"%s","code":%d,"msg":"%s"}' % (
                levels[(p + i) % 3],
                i,
                "x" * (RECORD_JSON_PAD + int(rng.integers(0, 100))),
            )
            recs.append(Record(offset_delta=i, timestamp_delta=i, value=doc.encode()))
        batch = RecordBatch.build(recs, base_offset=0, first_timestamp=1_000_000)
        items.append(ProcessBatchItem(1, NTP.kafka(topic, p), [batch]))
    return ProcessBatchRequest(items)


def _spec():
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    return where(field("level") == "error") | map_project(Int("code"), Str("msg", 64))


def _run_engine_stream(engine, req, n_ticks, group, depth) -> float:
    """Steady-state record_batches/sec: GROUP ticks per launch, DEPTH
    launches in flight, replies fully rebuilt on the critical path."""
    n_groups = (n_ticks + group - 1) // group
    pending = []
    replies = []
    t0 = time.perf_counter()
    for g in range(n_groups):
        k = min(group, n_ticks - g * group)
        pending.append(engine.submit_group([req] * k))
        while len(pending) > depth:
            replies.extend(t.result() for t in pending.pop(0))
    while pending:
        replies.extend(t.result() for t in pending.pop(0))
    elapsed = time.perf_counter() - t0
    assert len(replies) == n_ticks
    assert all(len(r.items) == len(req.items) for r in replies)
    n_batches = sum(len(it.batches) for it in req.items)
    return n_ticks * n_batches / elapsed


def _fmt_stages(stats: dict) -> dict:
    """Stage keys only (the t_/n_/bytes_ prefixes stats() documents):
    probe records and numeric metadata like host_workers are reported at
    the top level instead, so the per-stage tables stay diffable across
    BENCH artifacts."""
    out = {}
    for k, v in sorted(stats.items()):
        if k.startswith(("t_", "n_", "bytes_")):
            out[k] = round(v, 4) if k.startswith("t_") else int(v)
    return out


def _harvest_mode(stats: dict) -> str:
    """Which framing path a run took (gather = zero-copy from the joined
    blob; padded = row-matrix). One helper so the detection rule can't
    drift between the headline and the ablation blocks."""
    return "gather" if stats.get("n_frame_gather", 0.0) else "padded"


def _run_engine_mode(
    req, force_mode: str | None, host_workers: int = HOST_WORKERS,
    colcache_mb: int = 0, **engine_kw,
) -> tuple[float, dict, list | None, dict]:
    """One measured engine run. force_mode None = the PRODUCT path (the
    engine's own measured device-vs-host probe picks where the predicate
    runs); "columnar_device"/"columnar_host" pin each half so every BENCH
    carries the full ablation regardless of what the probe chose.
    host_workers sizes the host-stage shard pool (1 = inline ablation).
    colcache_mb enables the device-resident column cache (the broker
    default posture) — the HEADLINE runs with it because the bench's
    steady state IS a repeat script over unchanged partitions; the
    machinery ablations run cache-off so they still measure the machinery
    they are named for. Returns (rate, stage dict, per-shard stage splits
    of the last launch, probe record)."""
    from redpanda_tpu.coproc import TpuEngine

    engine = TpuEngine(
        row_stride=ROW_STRIDE, force_mode=force_mode,
        host_workers=host_workers, device_column_cache_mb=colcache_mb,
        **engine_kw,
    )
    codes = engine.enable_coprocessors([(1, _spec().to_json(), ("bench",))])
    assert codes[0] == 0
    # warmup: compile the GROUP-sized shape and, when MEASURE_TICKS is not a
    # multiple of GROUP, the tail-group shape too (one full group followed
    # by one tail-sized group), so no XLA compile lands in the timed run.
    tail = MEASURE_TICKS % GROUP
    _run_engine_stream(engine, req, GROUP + (tail or min(GROUP, MEASURE_TICKS)), GROUP, DEPTH)
    engine.reset_stats()
    rate = _run_engine_stream(engine, req, MEASURE_TICKS, GROUP, DEPTH)
    stats = engine.stats()
    probe = {
        "columnar_backend": stats.get("columnar_backend"),
        "columnar_probe": stats.get("columnar_probe"),
        "host_pool_probe": stats.get("host_pool_probe"),
        # previous probe result when the periodic re-calibration
        # (coproc_host_pool_recal_launches) has re-measured at least once
        "host_pool_probe_prev": stats.get("host_pool_probe_prev"),
        # zero-copy harvest: which framing path the run took (the
        # projection headline mutates bytes, so it reports padded
        # honestly) and the scratch arena's reuse accounting
        "harvest_mode": _harvest_mode(stats),
        "arena": stats.get("arena"),
        # structural-index parse: the engine's measured fused-vs-staged
        # pick for this run (None = never probed: every launch was a
        # cache hit or below the probe floor) + the probe timings
        "parse_path": stats.get("parse_path"),
        "parse_probe": stats.get("parse_probe"),
        # device-resident column cache accounting (absent = cache off)
        "colcache": stats.get("colcache"),
        # fault-domain health of the run: a BENCH number produced while the
        # breaker was open (or launches fell back to host) is an artifact
        # of a degraded link, and must say so on its face
        "breaker": stats.get("breaker"),
        # per-domain decision plane: breaker split + posture at run end
        # (coproc/governor.py; the process-wide journal summary + tail ride
        # at the top level of the BENCH json, collected after all runs)
        "breakers": stats.get("breakers"),
        "governor_posture": (stats.get("governor") or {}).get("posture"),
        "fallback_rows": stats.get("n_fallback_rows", 0.0),
        "device_retries": stats.get("n_retries", 0.0),
        # multi-chip meshrunner block (absent on single-device engines)
        "mesh": stats.get("mesh"),
    }
    shards = engine.last_launch_shards
    # a live harvester pins the engine (jit executables, staged arrays)
    # for the rest of the multi-mode bench process
    engine.shutdown()
    return rate, _fmt_stages(stats), shards, probe


def _measure_aa_skew(req) -> dict:
    """A/A box-skew self-check (ROADMAP item 4's "diagnose first"): two
    IDENTICAL host-columnar passthrough rounds timed back to back before
    any measured run. Their rate difference is the box's short-horizon
    capacity noise — a cross-round BENCH delta inside this band (the
    config3_payload_bridge_16p 5682→1439 rb/s "regression" on a ±30% box)
    is weather, not a code regression, and every BENCH artifact now says
    so on its face."""
    from redpanda_tpu.coproc import TpuEngine
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import where

    spec = where(field("level") == "error")
    engine = TpuEngine(
        row_stride=ROW_STRIDE, force_mode="columnar_host", host_workers=0
    )
    codes = engine.enable_coprocessors([(1, spec.to_json(), ("bench",))])
    assert codes[0] == 0
    _run_engine_stream(engine, req, GROUP, GROUP, DEPTH)  # warmup
    rates = [
        _run_engine_stream(engine, req, 2 * GROUP, GROUP, DEPTH)
        for _ in range(2)
    ]
    engine.shutdown()
    skew = abs(rates[0] - rates[1]) / max(rates) * 100.0 if max(rates) else 0.0
    return {
        "aa_rates_rb_s": [round(r, 1) for r in rates],
        "aa_skew_pct": round(skew, 1),
    }


def run_cpu_baseline(req) -> float:
    """Single-core host engine: per-record decode + json.loads + predicate +
    rebuild + re-CRC (the work profile of the reference's JS supervisor)."""
    from redpanda_tpu.compression import is_available
    from redpanda_tpu.models import Record, RecordBatch
    from redpanda_tpu.models.record import Compression

    # same degrade-don't-fail posture as the engine's output recompressor
    # (batch_codec.build_output_batch): without the zstandard package both
    # sides of the comparison compress with gzip, keeping vs_baseline fair
    out_codec = (
        Compression.zstd if is_available(Compression.zstd) else Compression.gzip
    )

    def tick():
        n_batches = 0
        for item in req.items:
            for batch in item.batches:
                kept = []
                for rec in batch.records():
                    try:
                        doc = json.loads(rec.value)
                    except Exception:
                        continue
                    if doc.get("level") != "error":
                        continue
                    msg = str(doc.get("msg", ""))[:64].encode()
                    out_val = struct.pack("<iH", int(doc.get("code", 0)), len(msg)) + msg.ljust(64, b"\x00")
                    kept.append(out_val)
                if kept:
                    recs = [
                        Record(offset_delta=i, value=v) for i, v in enumerate(kept)
                    ]
                    out = RecordBatch.build(
                        recs,
                        base_offset=0,
                        compression=out_codec,
                        first_timestamp=batch.header.first_timestamp,
                    )
                    assert out.header.crc
                n_batches += 1
        return n_batches

    tick()  # warmup
    # best-of-N per tick: the baseline must be the host's BEST case, so a
    # noisy-slow run can't inflate vs_baseline (min-time convention)
    best = None
    for _ in range(BASELINE_TICKS):
        t0 = time.perf_counter()
        n = tick()
        rate = n / (time.perf_counter() - t0)
        best = rate if best is None else max(best, rate)
    return best


def run_config1_crc_validate() -> dict:
    """Config 1: produce-path batch CRC validation, 1KB records, through
    the measured adapter boundary (ops/crc_backend.py — the call site the
    reference hard-codes at kafka_batch_adapter.cc:93-121).

    Reports both measured rates and the backend the probe chose; the chosen
    path is what the produce handler runs, so vs_host_single_core reflects
    the DECISION, not a forced device run."""
    from redpanda_tpu.models import Record, RecordBatch
    from redpanda_tpu.ops.crc_backend import CrcBackend

    batches = [
        RecordBatch.build(
            [Record(offset_delta=i, value=bytes([i % 251]) * 1024) for i in range(1)],
            base_offset=b,
        )
        for b in range(64)
    ]
    regions = [b.crc_region() for b in batches] * 16  # 1024 batches
    backend = CrcBackend.pick(regions, reps=8)
    d = backend.decision
    chosen_rate = (
        d.device_batches_per_sec if backend.backend == "device" else d.host_batches_per_sec
    )
    return {
        "batches_per_sec": round(chosen_rate, 1),
        "vs_host_single_core": round(chosen_rate / d.host_batches_per_sec, 2),
        "host_batches_per_sec": round(d.host_batches_per_sec, 1),
        "device_batches_per_sec": round(d.device_batches_per_sec, 1),
        "chosen_backend": backend.backend,
    }


def run_config2_lz4_produce() -> dict:
    """Config 2: 16-partition produce with LZ4 — codec-registry throughput
    (wire batch -> verify CRC -> LZ4 recompress), MB/s."""
    from redpanda_tpu.compression import compress, uncompress
    from redpanda_tpu.models import Record, RecordBatch
    from redpanda_tpu.models.record import Compression

    batches = []
    rng = np.random.default_rng(1)
    for p in range(16):
        recs = [
            Record(offset_delta=i, value=rng.bytes(512) + b"x" * 512)
            for i in range(RECORDS_PER_BATCH)
        ]
        batches.append(RecordBatch.build(recs, base_offset=0))
    total_bytes = sum(len(b.payload) for b in batches)
    reps = 6
    t0 = time.perf_counter()
    for _ in range(reps):
        for b in batches:
            assert b.verify_kafka_crc()
            c = compress(b.payload, Compression.lz4)
            assert uncompress(c, Compression.lz4) == b.payload
    elapsed = time.perf_counter() - t0
    return {"mb_per_sec": round(reps * total_bytes / 1e6 / elapsed, 1)}


def run_config3_identity(engine_cls, force_mode=None, **engine_kw) -> dict:
    """Config 3: identity transform at 16 partitions.

    Default: the engine's real identity path (routed to the host stage —
    identity has no device work; coproc/column_plan.py plan_spec).
    force_mode="payload": the full-row device staging path, isolating raw
    bridge overhead (the number that collapsed to 490 rb/s in BENCH_r03).
    engine_kw rides through to the engine (the diagnosis bisect pins
    host_workers to isolate PR-5's seal-sharding suspect path)."""
    from redpanda_tpu.ops.transforms import identity

    req16 = _build_workload(16, topic="bench3")
    engine = engine_cls(row_stride=ROW_STRIDE, force_mode=force_mode, **engine_kw)
    codes = engine.enable_coprocessors([(1, identity().to_json(), ("bench3",))])
    assert codes[0] == 0
    _run_engine_stream(engine, req16, GROUP, GROUP, DEPTH)
    rate = _run_engine_stream(engine, req16, 4 * GROUP, GROUP, DEPTH)
    engine.shutdown()
    return {"record_batches_per_sec": round(rate, 1)}


def run_pulse_block() -> dict:
    """ISSUE 14: the pandapulse block every BENCH artifact carries — one
    instrumented columnar round with the flight recorder on, so the
    artifact holds the same per-stage timeline totals `rpk debug profile`
    would show for the bench's launch shape (plus the recorder/profiler
    summary). Tracer + pulse state restore after; the measured headline
    runs above stay uninstrumented."""
    from redpanda_tpu.coproc import TpuEngine
    from redpanda_tpu.observability.pulse import pulse
    from redpanda_tpu.observability.trace import tracer

    was_tracing = tracer.enabled
    was_pulse = pulse.enabled
    tracer.configure(enabled=True)
    pulse.configure(enabled=True)
    pulse.recorder.reset()
    try:
        req = _build_workload(8, topic="bench_pulse")
        engine = TpuEngine(row_stride=ROW_STRIDE)
        codes = engine.enable_coprocessors(
            [(1, _spec().to_json(), ("bench_pulse",))]
        )
        assert codes[0] == 0
        req.trace_id = tracer.new_trace_id()
        engine.submit(req).result()
        engine.shutdown()
        tl = pulse.timeline()
        global _LAST_PULSE_TIMELINE
        _LAST_PULSE_TIMELINE = tl
        return {
            "recorder": pulse.recorder.summary(),
            "stage_totals_s": {
                k: round(v, 6)
                for k, v in sorted(pulse.recorder.stage_totals().items())
            },
            "timeline_events": len(tl["traceEvents"]),
            "journal_events": tl["journal_events"],
        }
    finally:
        pulse.configure(enabled=was_pulse)
        tracer.configure(enabled=was_tracing)


# the pulse block's raw timeline, kept for --diff-against: a timeline
# baseline diffs against THIS run's timeline through tools/pulsediff.py
_LAST_PULSE_TIMELINE: dict | None = None


def run_trend_block() -> dict:
    """ISSUE 17: the pandatrend block every BENCH artifact carries — the
    metrics-history recorder sampled around one columnar round, so the
    artifact holds the same derived counter tracks `/v1/history` and
    `rpk debug trend` serve on a live broker (occupancy, shed rate,
    colcache, per-histogram p99.9) for the bench's launch shape. No
    recorder thread runs here: two explicit ``sample_once()`` calls
    bracket the round, exactly the delta one 5s window would carry."""
    from redpanda_tpu.coproc import TpuEngine
    from redpanda_tpu.observability.history import history

    history.reset()
    history.sample_once()  # anchors the delta baseline
    req = _build_workload(8, topic="bench_trend")
    engine = TpuEngine(row_stride=ROW_STRIDE)
    codes = engine.enable_coprocessors(
        [(1, _spec().to_json(), ("bench_trend",))]
    )
    assert codes[0] == 0
    engine.submit(req).result()
    engine.shutdown()
    win = history.sample_once() or {}
    snap = history.snapshot(limit=1)
    return {
        "tracks": win.get("tracks", {}),
        "counter_deltas": {
            k: v["delta"]
            for k, v in sorted(win.get("counters", {}).items())
        },
        "hist_p999_us": {
            k: v["p999"] for k, v in sorted(win.get("hists", {}).items())
        },
        "breaches_total": snap["breaches_total"],
        "recorder_running": snap["recorder_running"],
        "counter_events": len(history.counter_tracks(pid=0)),
    }


def run_config3_diagnosis(aa: dict) -> dict:
    """ISSUE 11 satellite: judge the config3_payload_bridge_16p 5682→1439
    rb/s r04→r05 move now that the A/A self-check makes regression-vs-
    weather decidable. Three back-to-back A/A-bracketed reruns of the
    EXACT bridge config give the same-code spread; a pool-off bisect
    isolates the only PR-5 machinery the payload bridge actually crosses
    (arena-backed framing + the sharded seal engagement, both pool-gated).
    The verdict is journaled into the governor DIAGNOSIS domain so the
    BENCH artifact and /v1/governor both carry it."""
    from redpanda_tpu.coproc import TpuEngine
    from redpanda_tpu.coproc import governor as gov_mod

    rates = [
        run_config3_identity(TpuEngine, force_mode="payload")[
            "record_batches_per_sec"
        ]
        for _ in range(3)
    ]
    spread_pct = (
        (max(rates) - min(rates)) / max(rates) * 100.0 if max(rates) else 0.0
    )
    # bisect: pool off = the pre-PR-3/5 inline posture (no sharded seal,
    # no pool machinery anywhere near the bridge path)
    pool_off = run_config3_identity(
        TpuEngine, force_mode="payload", host_workers=0
    )["record_batches_per_sec"]
    mid = sorted(rates)[1]
    bisect_delta_pct = (pool_off - mid) / mid * 100.0 if mid else 0.0
    r04, r05 = 5682.2, 1439.3  # the recorded artifact values under test
    drop_pct = (r04 - r05) / r04 * 100.0
    # regression-suspect only if the PR-5-path bisect shows a step that
    # could plausibly account for a drop of this magnitude: well clear of
    # the box's own noise band AND a material fraction of the drop itself.
    # A noise-level bisect delta with a tight same-code rerun spread means
    # the 4x move was box weather, not a code path.
    band = max(aa["aa_skew_pct"], spread_pct)
    verdict = (
        "regression-suspect"
        if abs(bisect_delta_pct) >= max(3.0 * band, drop_pct / 4.0)
        else "weather"
    )
    inputs = {
        "rerun_rates_rb_s": rates,
        "rerun_spread_pct": round(spread_pct, 1),
        "pool_off_rate_rb_s": pool_off,
        "pool_off_delta_pct": round(bisect_delta_pct, 1),
        "aa_skew_pct": aa["aa_skew_pct"],
        "r04_rb_s": r04,
        "r05_rb_s": r05,
        "r04_to_r05_drop_pct": round(drop_pct, 1),
    }
    gov_mod.journal_record(
        gov_mod.DIAGNOSIS,
        verdict,
        "config3_payload_bridge_16p r04->r05 (-"
        f"{drop_pct:.0f}%) judged: same-code rerun spread "
        f"{spread_pct:.1f}%, A/A band {aa['aa_skew_pct']:.1f}%, pool-off "
        f"bisect delta {bisect_delta_pct:+.1f}% (PR-5 seal/arena paths)",
        inputs,
    )
    return {"verdict": verdict, **inputs}


def run_harvest_passthrough(req) -> dict:
    """Zero-copy harvest ablation: the same 64-partition workload through a
    PURE filter (passthrough plan — output bytes are the input values, the
    shape the gather path exists for), gather on vs off. Stage keys carry
    the per-path split; the microbench harvest_path gate asserts the
    stage-time cut, this block puts both end-to-end rates on record."""
    from redpanda_tpu.coproc import TpuEngine
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import where

    spec = where(field("level") == "error")
    out = {}
    for key, gather in (("gather", True), ("padded_ablation", False)):
        engine = TpuEngine(
            row_stride=ROW_STRIDE,
            force_mode="columnar_host",
            host_workers=HOST_WORKERS,
            gather_frame=gather,
        )
        codes = engine.enable_coprocessors([(1, spec.to_json(), ("bench",))])
        assert codes[0] == 0
        _run_engine_stream(engine, req, GROUP, GROUP, DEPTH)  # warmup
        engine.reset_stats()
        rate = _run_engine_stream(engine, req, 4 * GROUP, GROUP, DEPTH)
        stats = engine.stats()
        out[key] = {
            "record_batches_per_sec": round(rate, 1),
            "harvest_mode": _harvest_mode(stats),
            "stages": _fmt_stages(stats),
            "arena": stats.get("arena"),
        }
        engine.shutdown()
    return out


def run_mesh_64p() -> dict:
    """Config-5 promotion, MEASURED (the MULTICHIP_r06 artifact): the
    64-partition JSON-filter workload through the mesh-sharded engine
    (coproc/meshrunner.py — per-device sub-launches, one SPMD predicate
    program over the partition axis) against the 1-device ablation, with
    the A/A skew band applied to the delta. Bit-parity between the two
    engines is ASSERTED on a live request here (the same contract the
    test_meshrunner matrix pins), and the governor's mesh-domain journal
    rides in the artifact so the mesh-vs-single decision is
    reconstructible.

    Caller must provide >= 2 devices on the cpu backend (``bench.py
    mesh`` spawns this in a child with the host-platform device flag;
    on real multi-chip hardware the mesh spans the actual chips)."""
    from redpanda_tpu.coproc import TpuEngine
    from redpanda_tpu.coproc import governor as gov_mod
    from redpanda_tpu.coproc.meshrunner import available_devices

    n_dev = len(available_devices("cpu"))
    if n_dev < 2:
        return {"skipped": True, "reason": f"need >= 2 devices, have {n_dev}"}
    n_dev = min(8, n_dev)
    req = _build_workload()
    aa = _measure_aa_skew(req)
    gov_mod.reset_journal()
    # mesh lane pinned (mesh_probe=False): the 1-device run IS the
    # ablation, so the config must measure the lane, not the probe's
    # verdict about it — the probe's own measured verdict is reported
    # separately by the headline bench's product path
    TpuEngine.reset_columnar_probe()
    mesh_rate, mesh_stages, _, mesh_probe = _run_engine_mode(
        req, None, colcache_mb=32,
        mesh_devices=n_dev, mesh_backend="cpu", mesh_probe=False,
    )
    TpuEngine.reset_columnar_probe()
    one_rate, one_stages, _, _ = _run_engine_mode(req, None, colcache_mb=32)
    # live bit-parity assertion between the two paths
    TpuEngine.reset_columnar_probe()
    em = TpuEngine(
        row_stride=ROW_STRIDE, host_workers=HOST_WORKERS,
        mesh_devices=n_dev, mesh_backend="cpu", mesh_probe=False,
    )
    e1 = TpuEngine(row_stride=ROW_STRIDE, host_workers=0)
    for e in (em, e1):
        assert e.enable_coprocessors([(1, _spec().to_json(), ("bench",))]) == [0]
    pm = [
        (it.script_id, [b.payload for b in it.batches])
        for it in em.process_batch(req).items
    ]
    p1 = [
        (it.script_id, [b.payload for b in it.batches])
        for it in e1.process_batch(req).items
    ]
    em.shutdown()
    e1.shutdown()
    assert pm == p1, "mesh output diverged from the single-device path"
    delta_pct = (mesh_rate - one_rate) / one_rate * 100.0 if one_rate else 0.0
    verdict = (
        "within-band"
        if abs(delta_pct) <= aa["aa_skew_pct"]
        else ("mesh-win" if delta_pct > 0 else "mesh-loss")
    )
    return {
        "measured": True,
        "dryrun": False,
        "config": "mesh_64p",
        "n_devices": n_dev,
        "mesh_rb_s": round(mesh_rate, 1),
        "ablation_1dev_rb_s": round(one_rate, 1),
        "delta_pct": round(delta_pct, 1),
        "aa_skew_pct": aa["aa_skew_pct"],
        "aa_rates_rb_s": aa["aa_rates_rb_s"],
        "verdict": verdict,
        "parity": "bit-identical (asserted live; matrix in tests/test_meshrunner.py)",
        "mesh": mesh_probe.get("mesh"),
        "stages_mesh": mesh_stages,
        "stages_1dev": one_stages,
        "governor_journal_mesh": gov_mod.journal.entries(
            domain=gov_mod.MESH
        ),
    }


def main_mesh() -> None:
    """``python bench.py mesh``: the measured multichip round (run under
    the host-platform device flag; the MULTICHIP_r06 producer)."""
    _pin_cpu()
    from redpanda_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(8)
    out = run_mesh_64p()
    # the microbench gate on the same mesh: sharded CRC+vote step at
    # 1/2/4/8 devices with the no-regression floor (see
    # tools/microbench.py bench_mesh_scaling threshold guidance)
    try:
        from tools.microbench import bench_mesh_scaling

        scaling = bench_mesh_scaling(1.0)
        floor = float(os.environ.get("BENCH_MESH_SPEEDUP_FLOOR", "0.9"))
        scaling["assert_mesh_speedup"] = {
            "threshold": floor,
            "speedup": scaling.get("mesh_speedup_best", 0.0),
            "pass": scaling.get("mesh_speedup_best", 0.0) >= floor,
        }
        out["mesh_scaling"] = scaling
    except Exception as exc:
        out["mesh_scaling_error"] = repr(exc)
    print(json.dumps(out))


def run_link_profile() -> dict:
    """Quick device-link physics: sync RTT and H2D bandwidth (the numbers
    that justify columnar pushdown; full probe in tools/link_probe.py)."""
    import jax

    tiny = np.zeros(8, np.uint8)
    np.asarray(jax.device_put(tiny))  # warm
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(jax.device_put(tiny))
    rtt_ms = (time.perf_counter() - t0) / 3 * 1e3
    arr = np.random.default_rng(0).integers(0, 255, 8 << 20, np.uint8)
    f = jax.jit(lambda x: x.astype(np.int32).sum())
    jax.block_until_ready(f(arr))  # warm + compile
    t0 = time.perf_counter()
    jax.block_until_ready(f(arr))
    h2d = 8 / (time.perf_counter() - t0)
    return {"rtt_ms": round(rtt_ms, 1), "h2d_mb_s_consumed": round(h2d, 1)}


def _bench_diff_block(against_path: str, artifact: dict) -> dict:
    """ISSUE 17 release-flow judgment on the BENCH side: diff this run
    against a prior artifact through tools/pulsediff.py. A timeline
    baseline (a saved ``rpk debug profile --perfetto`` / pulse block
    export) judges against THIS run's pulse-round timeline stage by
    stage; a BENCH/SLO baseline delegates to slodiff as before. The
    bench's own measured A/A band rides as the noise band either way."""
    from tools import pulsediff

    try:
        baseline = pulsediff._load(against_path)
        if pulsediff.is_timeline(baseline):
            tl = _LAST_PULSE_TIMELINE
            if tl is None:
                raise ValueError(
                    "no pulse timeline captured this run to diff against"
                )
            tl = dict(tl)
            tl.setdefault("aa_band_pct", artifact.get("aa_skew_pct"))
            d = pulsediff.diff_artifacts(baseline, tl, None)
        else:
            d = pulsediff.diff_artifacts(baseline, artifact, None)
        d["against"] = against_path
        return d
    except Exception as exc:  # the measured run must never sink on a diff
        return {"against": against_path, "error": repr(exc),
                "verdict": "NO_BASELINE"}


def main(diff_against: str | None = None):
    tpu_ok = _probe_tpu()
    if not tpu_ok:
        _pin_cpu()
    req = _build_workload()
    from redpanda_tpu.coproc import TpuEngine

    # A/A control FIRST: whatever the measured runs report, the artifact
    # carries the box's own same-code noise band to judge deltas against
    aa = _measure_aa_skew(req)
    TpuEngine.reset_columnar_probe()  # the headline measures its own pick
    # PRODUCT path: broker posture — column cache on (the bench's steady
    # state is a repeat script over unchanged partitions, exactly the
    # workload the cache exists for; its hit rate rides in the artifact)
    value, stages, shard_stages, probe = _run_engine_mode(
        req, None, colcache_mb=32
    )
    # cache-off ablation of the SAME product path: attributes the headline
    # delta between the parse/extract machinery and the cache
    TpuEngine.reset_columnar_probe()
    nc_rate, nc_stages, _, nc_probe = _run_engine_mode(req, None)
    TpuEngine.reset_columnar_probe()
    dev_rate, dev_stages, _, _ = _run_engine_mode(req, "columnar_device")
    host_col_rate, host_col_stages, _, _ = _run_engine_mode(req, "columnar_host")
    # pool-off ablation: the acceptance bar is "no regression when the pool
    # is off", so the same product path runs again with ONE worker (inline).
    # Reset the sticky backend probe first — the ablation engine must
    # re-measure device-vs-host itself, not inherit the headline's pick.
    TpuEngine.reset_columnar_probe()
    w1_rate, w1_stages, _, w1_probe = _run_engine_mode(req, None, host_workers=1)
    baseline = run_cpu_baseline(req)

    columnar_probe = probe["columnar_probe"]
    columnar_backend = probe["columnar_backend"]
    import jax

    extras = {}
    try:
        # mesh_64p runs in a CHILD with the host-platform device flag:
        # this process's jax backend is already initialized (possibly on
        # the 1-chip tunnel), and the virtual multi-device mesh can only
        # be requested before backend init
        try:
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            flag = "--xla_force_host_platform_device_count=8"
            if flag not in env.get("XLA_FLAGS", ""):
                env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
            child = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "mesh"],
                capture_output=True, timeout=1800, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            extras["mesh_64p"] = json.loads(
                child.stdout.decode().strip().splitlines()[-1]
            )
        except Exception as exc:
            extras["mesh_64p"] = {"skipped": True, "error": repr(exc)}
        extras["harvest_passthrough_64p"] = run_harvest_passthrough(req)
        extras["config1_crc_validate"] = run_config1_crc_validate()
        extras["config2_lz4_produce"] = run_config2_lz4_produce()
        extras["config3_identity_16p"] = run_config3_identity(TpuEngine)
        extras["config3_payload_bridge_16p"] = run_config3_identity(
            TpuEngine, force_mode="payload"
        )
        # ISSUE 11 satellite: the r04->r05 payload-bridge drop, judged
        # with A/A bracketing + a pool-off bisect; verdict journaled into
        # the governor DIAGNOSIS domain (rides the journal tail below)
        extras["config3_diagnosis"] = run_config3_diagnosis(aa)
        extras["link"] = run_link_profile()
        from redpanda_tpu.ops.lz4_device import measure_probe

        # the SURVEY §7 "measure first" item: device LZ4 block decode vs
        # host liblz4, keep-or-kill on the recorded ratio (ops/lz4_device.py)
        extras["device_lz4_probe"] = measure_probe(
            n_records=32, record_size=256, reps=1
        )
        # decision-plane record for the whole bench process: every adaptive
        # decision any of the runs made (calibrations, backend probes,
        # breaker transitions, harvest/seal modes, lz4 keep-or-kill,
        # deadline moves) is reconstructible from this block alone — the
        # same view /v1/governor serves on a live broker
        from redpanda_tpu.coproc import governor as gov_mod

        extras["governor"] = {
            "posture": probe["governor_posture"],
            "journal": gov_mod.journal.summary(),
            "journal_tail": gov_mod.journal.entries(limit=16),
        }
        # ISSUE 17: the pandatrend block — history-recorder counter tracks
        # for one columnar round, sampled FIRST so the pulse block's
        # timeline below carries them as ph:"C" lanes on the span clock
        extras["trend"] = run_trend_block()
        # ISSUE 14: the pandapulse block — flight-recorder stage totals +
        # timeline/journal event counts for one instrumented round
        extras["pulse"] = run_pulse_block()
    except Exception as exc:  # secondary metrics must never sink the bench
        extras["configs_error"] = repr(exc)

    artifact = (
            {
                "metric": "coproc_json_filter_record_batches_per_sec_64p",
                "value": round(value, 1),
                "unit": "record_batches/s",
                "vs_baseline": round(value / baseline, 2),
                "baseline_cpu_single_core": round(baseline, 1),
                "device": str(jax.devices()[0]),
                # honest fallback marker: when the axon tunnel is
                # unavailable the whole bench runs on the CPU device and
                # the number is NOT a TPU measurement
                **(
                    {}
                    if tpu_ok
                    else {"device_note": "TPU tunnel unavailable; CPU-device fallback"}
                ),
                # same-code A/A control measured before everything else:
                # deltas inside this band are box noise, not regressions
                "aa_skew_pct": aa["aa_skew_pct"],
                "aa_rates_rb_s": aa["aa_rates_rb_s"],
                "partitions": P,
                "records_per_batch": RECORDS_PER_BATCH,
                "group_ticks_per_launch": GROUP,
                "launch_depth": DEPTH,
                "engine_mode": "columnar",
                # host-stage shard pool (coproc/host_pool.py): headline pool
                # size, the per-shard stage splits of the last launch, and
                # the workers=1 inline ablation proving the pool-off path
                # holds the pre-pool rate
                "host_workers": HOST_WORKERS,
                # the engine's one-shot parallel-capacity probe: when
                # parallel_ok is false this box has no real concurrency
                # (advertised CPUs backed by ~1 core of quota) and the
                # pool self-demoted to the inline path for the headline
                "host_pool_probe": probe["host_pool_probe"],
                "host_pool_probe_prev": probe["host_pool_probe_prev"],
                # zero-copy harvest bookkeeping for the headline run (the
                # projection headline assembles new bytes, so this is
                # honestly "padded"; harvest_passthrough_64p carries the
                # gather-vs-padded ablation)
                "harvest_mode": probe["harvest_mode"],
                "arena": probe["arena"],
                # structural-index parse + device column cache (PR 11):
                # which parse ladder the engine's measured probe picked,
                # its timings, and the headline's cache hit rate
                "parse_path": probe["parse_path"],
                "parse_probe": probe["parse_probe"],
                "colcache": probe["colcache"],
                # the SAME product path with the column cache off: the
                # honest split of the headline between parse/extract
                # machinery and cache hits
                "colcache_off_ablation": {
                    "record_batches_per_sec": round(nc_rate, 1),
                    "parse_path": nc_probe["parse_path"],
                    "parse_probe": nc_probe["parse_probe"],
                    "stages": nc_stages,
                },
                "shard_stages": shard_stages,
                "host_workers1_ablation": {
                    "record_batches_per_sec": round(w1_rate, 1),
                    "stages": w1_stages,
                    # re-probed after reset_columnar_probe(): proves the
                    # ablation measured its own backend pick
                    "columnar_backend": w1_probe["columnar_backend"],
                },
                # where the predicate ran in the headline: the engine's own
                # measured probe decides (device vs numpy over the SAME
                # extracted columns) — probe timings on record
                "columnar_backend": columnar_backend,
                "columnar_probe": columnar_probe,
                "stages": stages,
                # both halves of the decision, every run: vs_host_columnar
                # is what the DEVICE contributes over the identical plan
                # with a numpy predicate; <=1.0 means the device does not
                # pay for its link on this hardware for this workload.
                "engine_device_columnar": {
                    "record_batches_per_sec": round(dev_rate, 1),
                    "stages": dev_stages,
                },
                "engine_host_columnar": {
                    "record_batches_per_sec": round(host_col_rate, 1),
                    "stages": host_col_stages,
                },
                "vs_host_columnar": round(dev_rate / host_col_rate, 2),
                **extras,
            }
    )
    if diff_against:
        artifact["diff"] = _bench_diff_block(diff_against, artifact)
    print(json.dumps(artifact))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "mesh":
        main_mesh()
    else:
        _da = None
        if "--diff-against" in sys.argv:
            _i = sys.argv.index("--diff-against")
            if _i + 1 >= len(sys.argv):
                sys.exit("--diff-against requires a path")
            _da = sys.argv[_i + 1]
        main(diff_against=_da)
