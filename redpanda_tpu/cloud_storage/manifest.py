"""Partition + topic manifests.

Parity with cloud_storage/manifest.h: the per-ntp JSON manifest lists
uploaded segments {name → base_offset, committed_offset, size, term}, and
the topic manifest records the topic config. Object naming mirrors the
reference's layout: a hash prefix spreads keys across S3 partitions
(manifest.cc uses xxhash of the path), then
``<prefix>/<ns>/<topic>/<partition>_<revision>/...``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.models.fundamental import NTP

MANIFEST_FORMAT_VERSION = 1


def _prefix(path: str) -> str:
    return f"{xxhash64(path.encode()) & 0xFFFFFFFF:08x}"


def partition_path(ntp: NTP, revision: int = 0) -> str:
    base = f"{ntp.ns}/{ntp.topic}/{ntp.partition}_{revision}"
    return f"{_prefix(base)}/{base}"


@dataclass
class SegmentMeta:
    name: str  # "<base>-<term>-v1.log"
    base_offset: int
    committed_offset: int
    size_bytes: int
    term: int


@dataclass
class PartitionManifest:
    ntp: NTP
    revision: int = 0
    segments: dict[str, SegmentMeta] = field(default_factory=dict)

    @property
    def manifest_key(self) -> str:
        return f"{partition_path(self.ntp, self.revision)}/manifest.json"

    def segment_key(self, name: str) -> str:
        return f"{partition_path(self.ntp, self.revision)}/{name}"

    def add(self, meta: SegmentMeta) -> None:
        self.segments[meta.name] = meta

    def contains(self, name: str) -> bool:
        return name in self.segments

    @property
    def last_uploaded_offset(self) -> int:
        if not self.segments:
            return -1
        return max(s.committed_offset for s in self.segments.values())

    def to_json(self) -> bytes:
        return json.dumps({
            "version": MANIFEST_FORMAT_VERSION,
            "namespace": self.ntp.ns,
            "topic": self.ntp.topic,
            "partition": self.ntp.partition,
            "revision": self.revision,
            "segments": {
                name: {
                    "base_offset": s.base_offset,
                    "committed_offset": s.committed_offset,
                    "size_bytes": s.size_bytes,
                    "term": s.term,
                }
                for name, s in sorted(self.segments.items())
            },
        }, indent=1).encode()

    @staticmethod
    def from_json(blob: bytes) -> "PartitionManifest":
        d = json.loads(blob.decode())
        m = PartitionManifest(
            NTP(d["namespace"], d["topic"], d["partition"]), d.get("revision", 0)
        )
        for name, s in d.get("segments", {}).items():
            m.segments[name] = SegmentMeta(
                name, s["base_offset"], s["committed_offset"], s["size_bytes"], s["term"]
            )
        return m


@dataclass
class TopicManifest:
    ns: str
    topic: str
    partition_count: int
    replication_factor: int
    config: dict = field(default_factory=dict)

    @property
    def manifest_key(self) -> str:
        base = f"{self.ns}/{self.topic}"
        return f"{_prefix(base)}/{base}/topic_manifest.json"

    def to_json(self) -> bytes:
        return json.dumps({
            "version": MANIFEST_FORMAT_VERSION,
            "namespace": self.ns,
            "topic": self.topic,
            "partition_count": self.partition_count,
            "replication_factor": self.replication_factor,
            "config": self.config,
        }, indent=1).encode()

    @staticmethod
    def from_json(blob: bytes) -> "TopicManifest":
        d = json.loads(blob.decode())
        return TopicManifest(
            d["namespace"], d["topic"], d["partition_count"],
            d["replication_factor"], d.get("config", {}),
        )
