"""Remote (tiered) partition reads + topic recovery from manifests.

The round-1 build could upload segments (archival/) but nothing ever read
them back. This is the read side, parity with cloud_storage/remote.h:33 +
cache_service.h + the recovery path:

- ``RemotePartition``: serves batch reads for offsets that have been
  prefix-truncated out of the local log. Segment lookups go through the
  partition manifest; segment bytes go through the local disk cache
  (CacheService) so repeated reads of cold data hit S3 once.
- ``recover_topic_from_cloud``: topic recovery on create — downloads the
  topic manifest, recreates the topic config, then replays every uploaded
  segment's batches into fresh local logs with their ORIGINAL offsets
  (assign_offsets=False), so a cluster can be rebuilt from the bucket.

Offsets here are raw log offsets: the Partition facade translates to the
Kafka domain above (cluster/offset_translator.py keeps its full gap
history precisely so evicted prefixes stay translatable).
"""

from __future__ import annotations

import logging

from redpanda_tpu.cloud_storage.cache import CacheService
from redpanda_tpu.cloud_storage.manifest import PartitionManifest, TopicManifest
from redpanda_tpu.cloud_storage.remote import Remote
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import INTERNAL_HEADER_SIZE, RecordBatch

logger = logging.getLogger("rptpu.cloud_storage")


class RemotePartition:
    def __init__(
        self,
        ntp: NTP,
        remote: Remote,
        cache: CacheService | None = None,
        revision: int = 0,
        manifest_source=None,
    ) -> None:
        """manifest_source: optional callable returning the live manifest —
        on the archiving leader the local NtpArchiver's manifest is always
        fresher than a re-download, so the scheduler shares it."""
        self.ntp = ntp
        self.remote = remote
        self.cache = cache
        self._manifest = PartitionManifest(ntp, revision)
        self._manifest_source = manifest_source
        self._synced = False

    @property
    def manifest(self) -> PartitionManifest:
        if self._manifest_source is not None:
            return self._manifest_source()
        return self._manifest

    async def sync(self, force: bool = False) -> None:
        if self._manifest_source is not None:
            return  # always live via the archiver's manifest
        if self._synced and not force:
            return
        m = await self.remote.download_partition_manifest(self._manifest)
        if m is not None:
            self._manifest = m
        self._synced = True

    # ------------------------------------------------------------ offsets
    @property
    def start_offset(self) -> int:
        return min(
            (s.base_offset for s in self.manifest.segments.values()), default=0
        )

    @property
    def last_offset(self) -> int:
        return self.manifest.last_uploaded_offset

    # ------------------------------------------------------------ reads
    async def _segment_bytes(self, name: str) -> bytes:
        key = self.manifest.segment_key(name)
        if self.cache is not None:
            data = self.cache.get(key)
            if data is not None:
                return data
        data = await self.remote.download_segment(key)
        if self.cache is not None:
            self.cache.put(key, data)
        return data

    async def read(
        self,
        start_offset: int,
        max_bytes: int = 1 << 20,
        *,
        max_offset: int | None = None,
        type_filter=None,
    ) -> list[RecordBatch]:
        """Batches overlapping [start_offset, max_offset] from uploaded
        segments, oldest first (raw log offsets)."""
        await self.sync()
        out: list[RecordBatch] = []
        taken = 0
        for meta in sorted(
            self.manifest.segments.values(), key=lambda s: s.base_offset
        ):
            if meta.committed_offset < start_offset:
                continue
            if max_offset is not None and meta.base_offset > max_offset:
                break
            blob = await self._segment_bytes(meta.name)
            at = 0
            while at + INTERNAL_HEADER_SIZE <= len(blob):
                batch, consumed = RecordBatch.decode_internal(blob, at)
                at += consumed
                if batch.last_offset < start_offset:
                    continue
                if max_offset is not None and batch.base_offset > max_offset:
                    return out
                if type_filter is not None and batch.header.type not in type_filter:
                    continue
                batch.header.term = meta.term
                out.append(batch)
                taken += batch.size_bytes
                if taken >= max_bytes:
                    return out
        return out


async def recover_topic_from_cloud(
    broker, remote: Remote, topic: str, *, cache: CacheService | None = None
) -> int:
    """Recreate a topic from its cloud manifests (create-with-recovery).

    Returns the number of partitions restored. The reference's recovery
    flow (topic manifest -> partition manifests -> segment download) is
    mirrored; batches are replayed into the local log with their original
    offsets so translators/STMs rebuild identically.
    """
    from redpanda_tpu.cluster.topic_table import TopicConfig

    tm = await remote.download_topic_manifest(TopicManifest("kafka", topic, 1, 1))
    if tm is None:
        raise FileNotFoundError(f"no topic manifest for {topic!r} in the bucket")
    remote_cfg = dict(tm.config or {})
    # the archived incarnation id locates the partition manifests; the
    # recreated topic gets a fresh revision so future uploads never collide
    old_revision = int(remote_cfg.pop("x-rp-revision", 0))
    cfg = TopicConfig(topic, tm.partition_count, tm.replication_factor, ns=tm.ns)
    for k, v in remote_cfg.items():
        cfg.apply_override(k, v)
    await broker.create_topic(cfg)
    restored = 0
    for p in range(tm.partition_count):
        ntp = NTP.kafka(topic, p)
        rp = RemotePartition(ntp, remote, cache, revision=old_revision)
        await rp.sync()
        if not rp.manifest.segments:
            continue
        part = broker.partition_manager.get(ntp)
        if part is None:
            continue
        batches = await rp.read(rp.start_offset, 1 << 40)
        if batches:
            await part.log.append(batches, assign_offsets=False)
            await part.log.flush()
            restored += 1
            logger.info(
                "recovered %s: %d batches up to offset %d",
                ntp, len(batches), batches[-1].last_offset,
            )
    return restored
