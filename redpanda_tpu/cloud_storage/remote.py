"""Retrying remote operations over the S3 client.

Parity with cloud_storage/remote.h:33: every upload/download retries with
exponential backoff inside a time budget (retry_chain_node semantics), and
manifests get typed (de)serialization.
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.cloud_storage.manifest import PartitionManifest, TopicManifest
from redpanda_tpu.http import HttpError
from redpanda_tpu.s3 import S3Client, S3Error

logger = logging.getLogger("rptpu.cloud_storage")


class Remote:
    def __init__(
        self, client: S3Client, *, retries: int = 3, backoff_s: float = 0.1
    ) -> None:
        self.client = client
        self.retries = retries
        self.backoff_s = backoff_s

    async def _with_retries(self, what: str, fn):
        delay = self.backoff_s
        for attempt in range(1, self.retries + 1):
            try:
                return await fn()
            except FileNotFoundError:
                raise
            except (S3Error, HttpError, OSError, asyncio.TimeoutError) as e:
                logger.warning("%s failed (attempt %d): %s", what, attempt, e)
                if attempt == self.retries:
                    raise
                await asyncio.sleep(delay)
                delay *= 2

    # ------------------------------------------------------------ segments
    async def upload_segment(self, key: str, data: bytes) -> None:
        await self._with_retries(
            f"upload {key}", lambda: self.client.put_object(key, data)
        )

    async def download_segment(self, key: str) -> bytes:
        return await self._with_retries(
            f"download {key}", lambda: self.client.get_object(key)
        )

    # ------------------------------------------------------------ manifests
    async def upload_manifest(self, manifest: PartitionManifest | TopicManifest) -> None:
        await self._with_retries(
            f"upload {manifest.manifest_key}",
            lambda: self.client.put_object(manifest.manifest_key, manifest.to_json()),
        )

    async def download_partition_manifest(self, manifest: PartitionManifest) -> PartitionManifest | None:
        """Fetch the remote manifest for the ntp; None when absent."""
        try:
            blob = await self._with_retries(
                f"download {manifest.manifest_key}",
                lambda: self.client.get_object(manifest.manifest_key),
            )
        except FileNotFoundError:
            return None
        return PartitionManifest.from_json(blob)

    async def download_topic_manifest(self, manifest: TopicManifest) -> TopicManifest | None:
        """Fetch the topic manifest; None when absent (recovery probe)."""
        try:
            blob = await self._with_retries(
                f"download {manifest.manifest_key}",
                lambda: self.client.get_object(manifest.manifest_key),
            )
        except FileNotFoundError:
            return None
        return TopicManifest.from_json(blob)

    async def list_prefix(self, prefix: str = "") -> list[dict]:
        return await self._with_retries(
            f"list {prefix}", lambda: self.client.list_objects(prefix)
        )
