"""Tiered-storage download/read side (src/v/cloud_storage parity)."""

from redpanda_tpu.cloud_storage.cache import CacheService
from redpanda_tpu.cloud_storage.manifest import PartitionManifest, TopicManifest
from redpanda_tpu.cloud_storage.remote import Remote

__all__ = ["CacheService", "PartitionManifest", "Remote", "TopicManifest"]
