"""Local disk cache for downloaded segments.

Parity with cloud_storage/cache_service.h: downloaded objects land under a
cache dir keyed by their object key; total size is bounded and eviction is
LRU by access time (the reference walks the dir and trims to the target
size with recursive_directory_walker).
"""

from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger("rptpu.cloud_storage.cache")


class CacheService:
    def __init__(self, cache_dir: str, max_bytes: int = 1 << 30) -> None:
        self.cache_dir = cache_dir
        self.max_bytes = max_bytes
        os.makedirs(cache_dir, exist_ok=True)
        # in-memory access ordering; seeded from mtimes on restart
        self._access: dict[str, float] = {}
        for root, _dirs, files in os.walk(cache_dir):
            for f in files:
                p = os.path.join(root, f)
                self._access[os.path.relpath(p, cache_dir)] = os.path.getmtime(p)

    def _path(self, key: str) -> str:
        """Resolve a key under cache_dir, rejecting escapes ('..' segments,
        absolute keys): the class accepts arbitrary keys, so a hostile key
        must not be able to read/write/delete outside the cache root."""
        p = os.path.realpath(os.path.join(self.cache_dir, key.lstrip("/")))
        root = os.path.realpath(self.cache_dir)
        if os.path.commonpath([p, root]) != root:
            raise ValueError(f"cache key escapes cache dir: {key!r}")
        return p

    def get(self, key: str) -> bytes | None:
        p = self._path(key)
        if not os.path.exists(p):
            return None
        self._access[key.lstrip("/")] = time.time()
        with open(p, "rb") as f:
            return f.read()

    def put(self, key: str, data: bytes) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".part"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)
        self._access[key.lstrip("/")] = time.time()
        self._maybe_evict()

    def contains(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def size_bytes(self) -> int:
        total = 0
        for rel in list(self._access):
            p = os.path.join(self.cache_dir, rel)
            try:
                total += os.path.getsize(p)
            except OSError:
                self._access.pop(rel, None)
        return total

    def _maybe_evict(self) -> None:
        total = self.size_bytes()
        if total <= self.max_bytes:
            return
        # oldest-access first until under budget
        for rel in sorted(self._access, key=self._access.get):
            p = os.path.join(self.cache_dir, rel)
            try:
                sz = os.path.getsize(p)
                os.remove(p)
                total -= sz
            except OSError:
                pass
            self._access.pop(rel, None)
            logger.debug("evicted %s from cache", rel)
            if total <= self.max_bytes:
                return
