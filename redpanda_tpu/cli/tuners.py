"""rpk tune — the checker/tunable autotune framework.

Parity with the reference's tuner suite (src/go/rpk/pkg/tuners/check.go
Check(), checked_tunable.go checkedTunable.Tune(), aio.go, clocksource.go,
hugepages, ballast; the autotune story of docs/www/autotune.md): each
tuner couples a CHECKER that reads real system state with a TUNE action
that mutates it, run as check -> (ok? skip) -> supported? -> apply ->
post-check. `--dry-run` stops after the check and reports the delta that
WOULD be applied.

All file access goes through SysFs, a root-prefixed view of /proc and
/sys — production uses root="/", tests point it at a faked tree (the
reference injects afero.Fs the same way).
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass


class Severity(enum.Enum):
    fatal = "fatal"
    warning = "warning"


@dataclass
class CheckResult:
    ok: bool
    current: str
    required: str
    err: str = ""


@dataclass
class TuneOutcome:
    """One tuner's full story for the report table."""

    name: str
    supported: bool
    reason: str = ""  # why unsupported
    checked: CheckResult | None = None
    applied: bool = False
    post_ok: bool | None = None
    error: str = ""


class SysFs:
    """Root-prefixed /proc//sys accessor (afero-style injection point)."""

    def __init__(self, root: str = "/") -> None:
        self.root = root

    def _p(self, path: str) -> str:
        return os.path.join(self.root, path.lstrip("/"))

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))

    def read(self, path: str) -> str:
        with open(self._p(path)) as f:
            return f.read().strip()

    def write(self, path: str, value: str) -> None:
        with open(self._p(path), "w") as f:
            f.write(value)


class Tuner:
    """check() reads state; apply() mutates it. Subclasses define both
    plus supported() (e.g. the knob's file exists on this kernel)."""

    name = ""
    severity = Severity.warning

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        raise NotImplementedError

    def check(self, fs: SysFs) -> CheckResult:
        raise NotImplementedError

    def apply(self, fs: SysFs) -> None:
        raise NotImplementedError

    # ------------------------------------------------- checked-tunable flow
    def run(self, fs: SysFs, dry_run: bool = False) -> TuneOutcome:
        out = TuneOutcome(self.name, supported=True)
        sup, reason = self.supported(fs)
        if not sup:
            out.supported = False
            out.reason = reason
            return out
        try:
            out.checked = self.check(fs)
        except OSError as e:
            out.error = f"check failed: {e}"
            return out
        if out.checked.ok or dry_run:
            return out
        try:
            self.apply(fs)
            out.applied = True
        except OSError as e:
            out.error = f"apply failed: {e}"
            return out
        try:
            out.post_ok = self.check(fs).ok  # checked_tunable post-check
        except OSError as e:
            out.error = f"post-check failed: {e}"
        return out


# ---------------------------------------------------------------- tuners
class AioMaxNr(Tuner):
    """fs.aio-max-nr >= 1048576 (tuners/aio.go: seastar needs AIO slots
    proportional to shard count; the reference requires >= 1048576)."""

    name = "aio_events"
    severity = Severity.fatal
    PATH = "/proc/sys/fs/aio-max-nr"
    REQUIRED = 1048576

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        if not fs.exists(self.PATH):
            return False, f"{self.PATH} missing (kernel without AIO?)"
        return True, ""

    def check(self, fs: SysFs) -> CheckResult:
        cur = int(fs.read(self.PATH))
        return CheckResult(cur >= self.REQUIRED, str(cur), f">= {self.REQUIRED}")

    def apply(self, fs: SysFs) -> None:
        fs.write(self.PATH, str(self.REQUIRED))


class Swappiness(Tuner):
    """vm.swappiness <= 1 (tuners/sys memory posture: the broker's page
    cache must not be swapped out under it)."""

    name = "swappiness"
    PATH = "/proc/sys/vm/swappiness"
    REQUIRED = 1

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        if not fs.exists(self.PATH):
            return False, f"{self.PATH} missing"
        return True, ""

    def check(self, fs: SysFs) -> CheckResult:
        cur = int(fs.read(self.PATH))
        return CheckResult(cur <= self.REQUIRED, str(cur), f"<= {self.REQUIRED}")

    def apply(self, fs: SysFs) -> None:
        fs.write(self.PATH, str(self.REQUIRED))


class Clocksource(Tuner):
    """current_clocksource == tsc (tuners/clocksource.go: non-tsc sources
    cost a vsyscall per timestamp on the hot path)."""

    name = "clocksource"
    CUR = "/sys/devices/system/clocksource/clocksource0/current_clocksource"
    AVAIL = "/sys/devices/system/clocksource/clocksource0/available_clocksource"
    REQUIRED = "tsc"

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        if not fs.exists(self.CUR):
            return False, f"{self.CUR} missing"
        if self.REQUIRED not in fs.read(self.AVAIL).split():
            return False, "tsc not in available_clocksource"
        return True, ""

    def check(self, fs: SysFs) -> CheckResult:
        cur = fs.read(self.CUR)
        return CheckResult(cur == self.REQUIRED, cur, self.REQUIRED)

    def apply(self, fs: SysFs) -> None:
        fs.write(self.CUR, self.REQUIRED)


class TransparentHugepages(Tuner):
    """THP enabled 'always' (hugepage-backed allocators drop TLB pressure;
    the reference's hugepages posture, tuners/hugepages)."""

    name = "transparent_hugepages"
    PATH = "/sys/kernel/mm/transparent_hugepage/enabled"
    REQUIRED = "always"

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        if not fs.exists(self.PATH):
            return False, f"{self.PATH} missing (THP not built in)"
        return True, ""

    def check(self, fs: SysFs) -> CheckResult:
        raw = fs.read(self.PATH)  # e.g. "always [madvise] never"
        cur = raw[raw.find("[") + 1 : raw.find("]")] if "[" in raw else raw
        return CheckResult(cur == self.REQUIRED, cur, self.REQUIRED)

    def apply(self, fs: SysFs) -> None:
        fs.write(self.PATH, self.REQUIRED)


class Nofile(Tuner):
    """RLIMIT_NOFILE soft limit >= 102400 (file_limit checkers: a broker
    holds an fd per segment + per connection). Applies to THIS process
    tree via setrlimit — the one tuner whose state is not a /proc file."""

    name = "nofile"
    REQUIRED = 102400

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        return True, ""

    def _limits(self):
        import resource

        return resource.getrlimit(resource.RLIMIT_NOFILE)

    def check(self, fs: SysFs) -> CheckResult:
        import resource

        soft, _hard = self._limits()
        if soft == resource.RLIM_INFINITY:
            return CheckResult(True, "unlimited", f">= {self.REQUIRED}")
        return CheckResult(soft >= self.REQUIRED, str(soft), f">= {self.REQUIRED}")

    def apply(self, fs: SysFs) -> None:
        import resource

        soft, hard = self._limits()
        # NEVER touch the hard limit: lowering it (e.g. from unlimited)
        # is irreversible without CAP_SYS_RESOURCE (syschecks.py posture)
        if hard == resource.RLIM_INFINITY:
            target = max(self.REQUIRED, 0 if soft == resource.RLIM_INFINITY else soft)
        else:
            target = min(max(self.REQUIRED, soft), hard)
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (target, hard))
        except ValueError as e:
            raise OSError(str(e)) from e


class BallastFile(Tuner):
    """Preallocated ballast so a disk-full incident has a deletable escape
    hatch (tuners/ballast). Size is deliberately modest by default."""

    name = "ballast_file"

    def __init__(self, path: str = "/var/lib/redpanda/ballast", size: int = 1 << 30):
        self.path = path
        self.size = size

    def supported(self, fs: SysFs) -> tuple[bool, str]:
        parent = os.path.dirname(fs._p(self.path))
        if not os.path.isdir(parent):
            return False, f"parent directory missing: {os.path.dirname(self.path)}"
        return True, ""

    def check(self, fs: SysFs) -> CheckResult:
        p = fs._p(self.path)
        cur = os.path.getsize(p) if os.path.exists(p) else 0
        return CheckResult(cur >= self.size, str(cur), f">= {self.size} bytes")

    def apply(self, fs: SysFs) -> None:
        p = fs._p(self.path)
        with open(p, "wb") as f:
            f.truncate(self.size)


def all_tuners(ballast_path: str | None = None, ballast_size: int | None = None) -> list[Tuner]:
    ballast = BallastFile(
        ballast_path or "/var/lib/redpanda/ballast",
        ballast_size if ballast_size is not None else 1 << 30,
    )
    return [
        AioMaxNr(), Swappiness(), Clocksource(), TransparentHugepages(),
        Nofile(), ballast,
    ]


def run_tuners(
    names: list[str] | None = None,
    *,
    root: str = "/",
    dry_run: bool = False,
    ballast_path: str | None = None,
    ballast_size: int | None = None,
) -> list[TuneOutcome]:
    fs = SysFs(root)
    tuners = all_tuners(ballast_path, ballast_size)
    if names:
        tuners = [t for t in tuners if t.name in set(names)]
    return [t.run(fs, dry_run=dry_run) for t in tuners]


def format_outcomes(outcomes: list[TuneOutcome], dry_run: bool) -> str:
    lines = []
    for o in outcomes:
        if not o.supported:
            lines.append(f"{o.name:<24} unsupported  ({o.reason})")
        elif o.error:
            lines.append(f"{o.name:<24} ERROR        ({o.error})")
        elif o.checked and o.checked.ok:
            lines.append(f"{o.name:<24} ok           (current: {o.checked.current})")
        elif dry_run:
            lines.append(
                f"{o.name:<24} would-tune   (current: {o.checked.current}, "
                f"required: {o.checked.required})"
            )
        elif o.applied and o.post_ok:
            lines.append(f"{o.name:<24} tuned        (was: {o.checked.current})")
        else:
            lines.append(
                f"{o.name:<24} tuned-UNVERIFIED (post-check failed; was: "
                f"{o.checked.current}, required: {o.checked.required})"
            )
    return "\n".join(lines)
