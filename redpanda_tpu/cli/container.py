"""`rpk container` — a local multi-broker cluster for development.

The reference's `rpk container` manages a throwaway local cluster in
docker (src/go/rpk/pkg/cli/cmd/container, one container per broker). On
TPU hosts the natural unit is a PROCESS, not a container: each broker is a
detached `python -m redpanda_tpu start`, the cluster state (ports, pids,
data dirs) lives in one JSON file, and teardown is signal + rm. Same
lifecycle surface: start / status / stop / purge.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

DEFAULT_DIR = os.path.join(
    os.environ.get("XDG_STATE_HOME", os.path.expanduser("~/.local/state")),
    "rptpu-container",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    # reap if it's our own child (start+stop in one process leaves a
    # zombie otherwise; detached use reparents to init, which reaps)
    try:
        done, _ = os.waitpid(pid, os.WNOHANG)
        if done == pid:
            return False
    except ChildProcessError:
        pass
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(") ", 1)[1][0] != "Z"
    except OSError:
        return False


def _admin_ready(port: int, timeout: float = 1.0) -> bool:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/status/ready", timeout=timeout
        ) as r:
            return r.status == 200
    except Exception:
        return False


class LocalCluster:
    def __init__(self, base_dir: str = DEFAULT_DIR):
        self.base_dir = base_dir
        self.state_path = os.path.join(base_dir, "state.json")

    # ------------------------------------------------------------ state
    def load(self) -> dict | None:
        try:
            with open(self.state_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _save(self, state: dict) -> None:
        os.makedirs(self.base_dir, exist_ok=True)
        with open(self.state_path, "w") as f:
            json.dump(state, f, indent=2)

    # ------------------------------------------------------------ lifecycle
    def start(self, n: int = 1, wait_s: float = 120.0, extra_sets=None) -> dict:
        if self.load() is not None:
            raise RuntimeError(
                f"cluster already exists in {self.base_dir} "
                "(rpk container stop/purge first)"
            )
        ports = [
            {"kafka": _free_port(), "rpc": _free_port(), "admin": _free_port()}
            for _ in range(n)
        ]
        seeds = ",".join(f"{i}@127.0.0.1:{p['rpc']}" for i, p in enumerate(ports))
        nodes = []
        for i, p in enumerate(ports):
            data_dir = os.path.join(self.base_dir, f"n{i}")
            os.makedirs(data_dir, exist_ok=True)
            sets = {
                "node_id": i,
                "data_directory": data_dir,
                "kafka_api_port": p["kafka"],
                "advertised_kafka_api_port": p["kafka"],
                "rpc_server_port": p["rpc"],
                "admin_api_port": p["admin"],
            }
            if n > 1:
                sets["seed_servers"] = seeds
            sets.update(extra_sets or {})
            cmd = [sys.executable, "-m", "redpanda_tpu", "start"]
            for k, v in sets.items():
                cmd += ["--set", f"{k}={v}"]
            log = open(os.path.join(data_dir, "broker.log"), "ab")
            proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True,  # survives the rpk process exiting
            )
            nodes.append({"node_id": i, "pid": proc.pid, **p, "data_dir": data_dir})
        state = {"nodes": nodes, "started_at": time.time()}
        self._save(state)
        deadline = time.monotonic() + wait_s
        pending = {nd["node_id"] for nd in nodes}
        while pending and time.monotonic() < deadline:
            for nd in nodes:
                if nd["node_id"] in pending:
                    if not _pid_alive(nd["pid"]):
                        raise RuntimeError(
                            f"node {nd['node_id']} died during startup; see "
                            f"{nd['data_dir']}/broker.log"
                        )
                    if _admin_ready(nd["admin"]):
                        pending.discard(nd["node_id"])
            time.sleep(0.3)
        if pending:
            raise TimeoutError(f"nodes not ready after {wait_s}s: {sorted(pending)}")
        return state

    def status(self) -> list[dict]:
        state = self.load()
        if state is None:
            return []
        out = []
        for nd in state["nodes"]:
            out.append({
                **nd,
                "alive": _pid_alive(nd["pid"]),
                "ready": _admin_ready(nd["admin"]),
            })
        return out

    def stop(self) -> int:
        state = self.load()
        if state is None:
            return 0
        stopped = 0
        for nd in state["nodes"]:
            if _pid_alive(nd["pid"]):
                try:
                    os.kill(nd["pid"], signal.SIGTERM)
                    stopped += 1
                except OSError:
                    pass
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and any(
            _pid_alive(nd["pid"]) for nd in state["nodes"]
        ):
            time.sleep(0.2)
        for nd in state["nodes"]:
            if _pid_alive(nd["pid"]):
                try:
                    os.kill(nd["pid"], signal.SIGKILL)
                except OSError:
                    pass
        return stopped

    def purge(self) -> None:
        import shutil

        self.stop()
        shutil.rmtree(self.base_dir, ignore_errors=True)

    def brokers(self) -> str:
        state = self.load()
        if state is None:
            return ""
        return ",".join(f"127.0.0.1:{nd['kafka']}" for nd in state["nodes"])


def cmd_container(args) -> int:
    cluster = LocalCluster(args.dir or DEFAULT_DIR)
    if args.container_cmd == "start":
        extra = {}
        for kv in getattr(args, "set", None) or []:
            k, _, v = kv.partition("=")
            extra[k] = v
        state = cluster.start(args.nodes, extra_sets=extra)
        print(f"started {len(state['nodes'])} broker(s) in {cluster.base_dir}")
        print(f"brokers: {cluster.brokers()}")
        for nd in state["nodes"]:
            print(
                f"  node {nd['node_id']}: kafka 127.0.0.1:{nd['kafka']} "
                f"admin 127.0.0.1:{nd['admin']} pid {nd['pid']}"
            )
        return 0
    if args.container_cmd == "status":
        rows = cluster.status()
        if not rows:
            print("no local cluster")
            return 1
        for nd in rows:
            state = "ready" if nd["ready"] else ("up" if nd["alive"] else "DOWN")
            print(
                f"node {nd['node_id']}: {state} kafka 127.0.0.1:{nd['kafka']} "
                f"admin 127.0.0.1:{nd['admin']} pid {nd['pid']}"
            )
        return 0
    if args.container_cmd == "stop":
        print(f"stopped {cluster.stop()} broker(s)")
        return 0
    if args.container_cmd == "purge":
        cluster.purge()
        print(f"purged {cluster.base_dir}")
        return 0
    print("usage: rpk container {start|status|stop|purge}", file=sys.stderr)
    return 2
