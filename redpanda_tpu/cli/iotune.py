"""rpk iotune: storage characterization for the data directory.

Parity with the reference's `rpk iotune` (src/go/rpk pkg/cli/cmd/iotune.go),
which benchmarks the data disk and writes an io-properties file consumed by
the IO scheduler at startup. Here the probe measures what this runtime
actually depends on — sequential append bandwidth, fsync latency (the
produce-path acks=-1 cost), and cold sequential read bandwidth — and writes
`io-config.json` into the data dir. `redpanda start` picks the file up and
publishes the numbers through config/metrics so operators and the admin API
see what the disk was measured at.
"""

from __future__ import annotations

import os
import statistics
import time

from redpanda_tpu.config.io_config import (  # noqa: F401  (re-exported)
    IO_CONFIG_NAME,
    load_io_config,
    write_io_config,
)


def _measure_seq_write(path: str, total_bytes: int, block: int) -> float:
    """MB/s for buffered sequential writes + one final fsync."""
    buf = os.urandom(block)
    t0 = time.perf_counter()
    with open(path, "wb") as f:
        written = 0
        while written < total_bytes:
            f.write(buf)
            written += block
        f.flush()
        os.fsync(f.fileno())
    dt = time.perf_counter() - t0
    return (written / dt) / 1e6


def _measure_fsync(path: str, iters: int, block: int) -> dict[str, float]:
    """Latency of small append+fsync cycles (the quorum-ack disk cost)."""
    lat_ms: list[float] = []
    buf = os.urandom(block)
    with open(path, "ab") as f:
        for _ in range(iters):
            f.write(buf)
            f.flush()
            t0 = time.perf_counter()
            os.fsync(f.fileno())
            lat_ms.append((time.perf_counter() - t0) * 1e3)
    lat_ms.sort()
    return {
        "p50_ms": round(statistics.median(lat_ms), 4),
        "p99_ms": round(lat_ms[min(len(lat_ms) - 1, int(len(lat_ms) * 0.99))], 4),
        "max_ms": round(lat_ms[-1], 4),
    }


def _measure_seq_read(path: str, block: int) -> float:
    """MB/s sequential read of the file just written (page-cache-warm on
    most hosts; still bounds the fetch path's best case)."""
    t0 = time.perf_counter()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(block)
            if not chunk:
                break
            n += len(chunk)
    dt = time.perf_counter() - t0
    return (n / dt) / 1e6 if dt > 0 else float("inf")


def measure(
    data_dir: str,
    *,
    file_bytes: int = 64 << 20,
    block: int = 1 << 20,
    fsync_iters: int = 50,
) -> dict:
    """Run the full characterization inside `data_dir`."""
    os.makedirs(data_dir, exist_ok=True)
    probe_path = os.path.join(data_dir, ".iotune.probe")
    try:
        seq_write = _measure_seq_write(probe_path, file_bytes, block)
        fsync = _measure_fsync(probe_path, fsync_iters, 4096)
        seq_read = _measure_seq_read(probe_path, block)
    finally:
        try:
            os.unlink(probe_path)
        except OSError:
            pass
    return {
        "version": 1,
        "data_dir": os.path.abspath(data_dir),
        "measured_at": int(time.time()),
        "seq_write_mb_s": round(seq_write, 1),
        "seq_read_mb_s": round(seq_read, 1),
        "fsync_4k": fsync,
        "probe_bytes": file_bytes,
    }


