"""Operator CLI (src/go/rpk parity)."""

from redpanda_tpu.cli.rpk import main

__all__ = ["main"]
