"""rpk — the operator CLI.

Parity with src/go/rpk (pkg/cli/cmd): broker lifecycle, topic CRUD +
produce/consume, ACLs, users, wasm (transform) deploy/remove/generate,
cluster info, config get/set, debug bundle, generate
grafana-dashboard/prometheus-config, and tune (the autotune story —
reported as informational here: kernel tuning is outside this runtime's
scope, docs/www/autotune.md).

Usage: python -m redpanda_tpu <command> ...   (or the `rpk` console entry)
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
import subprocess
import sys

DEFAULT_BROKERS = "127.0.0.1:9092"
DEFAULT_ADMIN = "127.0.0.1:9644"


def _parse_brokers(s: str) -> list[tuple[str, int]]:
    out = []
    for hp in s.split(","):
        host, _, port = hp.strip().partition(":")
        out.append((host, int(port or 9092)))
    return out


async def _client(args):
    from redpanda_tpu.kafka.client.client import KafkaClient

    sasl = (args.user, args.password) if getattr(args, "user", None) else None
    return await KafkaClient(_parse_brokers(args.brokers), sasl=sasl).connect()


async def _admin_request(args, method: str, path: str, body=None, query=None):
    import json as _json
    import urllib.parse

    from redpanda_tpu.http import HttpClient

    # user-supplied segments (names etc.) must be percent-encoded for the
    # request line; structural separators stay intact. Query VALUES go via
    # `query` (urlencode: one correct encoding) — pre-encoding them into
    # `path` would double-encode '%' here.
    path = urllib.parse.quote(path, safe="/?&=")
    if query:
        path += ("&" if "?" in path else "?") + urllib.parse.urlencode(query)
    async with HttpClient(f"http://{args.admin_api}") as c:
        headers = {}
        payload = b""
        if body is not None:
            payload = _json.dumps(body).encode()
            headers["content-type"] = "application/json"
        resp = await c.request(method, path, headers=headers, body=payload)
        try:
            return resp.status, _json.loads(resp.body)
        except Exception:
            return resp.status, resp.body.decode("utf-8", "replace")


# ================================================================ redpanda start
async def cmd_start(args) -> int:
    import logging

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(levelname)s %(name)s %(message)s"
    )
    from redpanda_tpu.app import Application
    from redpanda_tpu.config import Configuration

    cfg = Configuration()
    if args.config:
        cfg.load_yaml(args.config)
    for kv in args.set or []:
        k, _, v = kv.partition("=")
        cfg.set(k, v)
    app = await Application(cfg).start()
    print(
        f"redpanda_tpu started: kafka {cfg.kafka_api_host}:{app.kafka_server.port}, "
        f"admin {cfg.admin_api_host}:{app.admin.port}"
    )
    try:
        await app.run_forever()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    return 0


# ================================================================ topics
async def cmd_topic(args) -> int:
    client = await _client(args)
    try:
        if args.topic_cmd == "create":
            configs = dict(kv.split("=", 1) for kv in (args.topic_config or []))
            await client.create_topic(
                args.name, partitions=args.partitions,
                replication=args.replicas, configs=configs or None,
            )
            print(f"created topic {args.name}")
        elif args.topic_cmd == "delete":
            await client.delete_topic(args.name)
            print(f"deleted topic {args.name}")
        elif args.topic_cmd == "list":
            md = await client.refresh_metadata()
            for t in sorted(md["topics"], key=lambda t: t["name"]):
                if t["error_code"] == 0:
                    print(f"{t['name']}\t{len(t.get('partitions') or [])} partitions")
        elif args.topic_cmd == "describe":
            md = await client.refresh_metadata([args.name], auto_create=False)
            t = next((t for t in md["topics"] if t["name"] == args.name), None)
            if t is None or t["error_code"] != 0:
                print(f"topic not found: {args.name}", file=sys.stderr)
                return 1
            print(json.dumps(t, indent=2))
        elif args.topic_cmd == "produce":
            data = sys.stdin.buffer.read() if args.value == "-" else args.value.encode()
            off = await client.produce(args.name, args.partition, [(args.key.encode() if args.key else None, data)])
            print(f"produced to {args.name}/{args.partition} at offset {off}")
        elif args.topic_cmd == "consume":
            offset = args.offset
            if offset < 0:
                offset = await client.earliest_offset(args.name, args.partition)
            n = 0
            while n < args.num:
                batches, hwm = await client.fetch(args.name, args.partition, offset, max_wait_ms=500)
                if not batches:
                    if offset >= hwm:
                        break
                    continue
                for b in batches:
                    for r in b.records():
                        print(json.dumps({
                            "offset": b.header.base_offset + r.offset_delta,
                            "key": r.key.decode("utf-8", "replace") if r.key else None,
                            "value": r.value.decode("utf-8", "replace") if r.value else None,
                        }))
                        n += 1
                        if n >= args.num:
                            break
                    offset = b.last_offset + 1
        return 0
    finally:
        await client.close()


# ================================================================ acl
async def cmd_acl(args) -> int:
    from redpanda_tpu.kafka.protocol import messages as m
    from redpanda_tpu.security.acl import (
        AclOperation, AclPermission, PatternType, ResourceType,
    )

    client = await _client(args)
    try:
        conn = await client.any_connection()
        if args.acl_cmd == "create":
            resp = await conn.request(m.CREATE_ACLS, {"creations": [{
                "resource_type": int(ResourceType[args.resource]),
                "resource_name": args.resource_name,
                "resource_pattern_type": int(PatternType.literal),
                "principal": args.principal if args.principal.startswith("User:") else f"User:{args.principal}",
                "host": args.host,
                "operation": int(AclOperation[args.operation]),
                "permission_type": int(AclPermission.deny if args.deny else AclPermission.allow),
            }]})
            code = resp["results"][0]["error_code"]
            print("created" if code == 0 else f"failed: error {code}")
            return 0 if code == 0 else 1
        if args.acl_cmd == "list":
            resp = await conn.request(m.DESCRIBE_ACLS, {
                "resource_type_filter": int(ResourceType.any),
                "resource_name_filter": None,
                "pattern_type_filter": int(PatternType.any),
                "principal_filter": None, "host_filter": None,
                "operation": int(AclOperation.any),
                "permission_type": int(AclPermission.any),
            })
            for res in resp["resources"]:
                for acl in res["acls"]:
                    print(
                        f"{ResourceType(res['resource_type']).name}:{res['resource_name']}\t"
                        f"{acl['principal']}\t{AclOperation(acl['operation']).name}\t"
                        f"{AclPermission(acl['permission_type']).name}"
                    )
        return 0
    finally:
        await client.close()


# ================================================================ wasm (transforms)
_TRANSFORM_TEMPLATE = {
    "name": "my-transform",
    "input_topics": ["source-topic"],
    # TransformSpec wire form (ops/transforms.py to_json); this example
    # keeps records containing `"level":"error"` and projects two fields
    "spec": {
        "name": "errors-only",
        "ops": [
            {"op": "filter_contains", "pattern": '"level":"error"',
             "negate": False, "nonnum_suffix": False},
            {"op": "map_project", "fields": [
                {"kind": "int", "key": "code"},
                {"kind": "str", "key": "msg", "max_len": 32},
            ]},
        ],
    },
}


async def cmd_wasm(args) -> int:
    if args.wasm_cmd == "generate":
        print(json.dumps(_TRANSFORM_TEMPLATE, indent=2))
        return 0
    from redpanda_tpu.coproc import wasm_event
    from redpanda_tpu.models.fundamental import COPROC_INTERNAL_TOPIC

    client = await _client(args)
    try:
        if args.wasm_cmd == "deploy":
            # rpk shares the reactor checker with the broker: read the spec
            # off-loop even though the CLI loop has nothing else scheduled
            doc = json.loads(await asyncio.to_thread(_read_text, args.file))
            if "py_source" in doc:
                # sandboxed python transform (validated client-side here
                # and again on every broker at enable time)
                rec = wasm_event.make_py_deploy_record(
                    doc["name"], doc["py_source"], doc["input_topics"],
                    policy=doc.get("policy", "skip"),
                )
            else:
                rec = wasm_event.make_deploy_record(
                    doc["name"], json.dumps(doc["spec"]), doc["input_topics"]
                )
        else:  # remove
            rec = wasm_event.make_remove_record(args.name)
        from redpanda_tpu.models.record import RecordBatch

        batch = wasm_event.deploy_batch([rec])
        await client.produce_batches(COPROC_INTERNAL_TOPIC, 0, [batch])
        print(f"{args.wasm_cmd} event produced to {COPROC_INTERNAL_TOPIC}")
        return 0
    finally:
        await client.close()


# ================================================================ cluster / user / config
async def cmd_cluster(args) -> int:
    if getattr(args, "cluster_cmd", None) == "rebalance":
        # each node sheds its own excess leaderships; hit every admin given
        total = []
        failures = 0
        for admin in (args.admin_apis or args.admin_api).split(","):
            ns = argparse.Namespace(**{**vars(args), "admin_api": admin.strip()})
            status, body = await _admin_request(
                ns, "POST", "/v1/partitions/rebalance_leaders"
            )
            if status != 200:
                print(f"{admin}: error {status} {body}", file=sys.stderr)
                failures += 1
                continue
            total.extend(body.get("transferred", []))
            print(f"{admin}: moved {len(body.get('transferred', []))}, "
                  f"leader counts {body.get('leader_counts')}")
        print(f"total transferred: {len(total)}")
        # nonzero when ANY node could not rebalance: scripted callers must
        # not read a partial pass as success
        return 1 if failures else 0
    status, brokers = await _admin_request(args, "GET", "/v1/brokers")
    if status != 200:
        print(f"admin api error {status}", file=sys.stderr)
        return 1
    print(f"{'ID':<5}{'HOST':<20}{'KAFKA':<22}{'STATUS':<10}")
    for b in brokers:
        print(
            f"{b['node_id']:<5}{b['host']:<20}"
            f"{b['kafka_host']}:{b['kafka_port']:<15}{b['membership_status']:<10}"
        )
    return 0


async def cmd_user(args) -> int:
    if args.user_cmd == "create":
        status, body = await _admin_request(
            args, "POST", "/v1/security/users",
            {"username": args.name, "password": args.new_password,
             "algorithm": args.mechanism},
        )
    elif args.user_cmd == "delete":
        status, body = await _admin_request(args, "DELETE", f"/v1/security/users/{args.name}")
    else:  # list
        status, body = await _admin_request(args, "GET", "/v1/security/users")
    print(json.dumps(body, indent=2) if status == 200 else f"error {status}: {body}")
    return 0 if status == 200 else 1


async def cmd_config(args) -> int:
    if args.config_cmd == "get":
        status, body = await _admin_request(args, "GET", "/v1/config")
        if status != 200:
            return 1
        if args.key:
            print(json.dumps(body.get(args.key)))
        else:
            print(json.dumps(body, indent=2, sort_keys=True))
        return 0
    print("config set requires editing the yaml + restart (needs_restart properties)", file=sys.stderr)
    return 1


# ================================================================ debug / generate / tune
def _write_text(path: str, data: str) -> None:
    """Blocking file write, called via asyncio.to_thread from the async
    CLI commands (RCT103: no blocking I/O on the loop)."""
    with open(path, "w") as f:
        f.write(data)


async def cmd_debug(args) -> int:
    """debug diagnostics: bundle (tar.gz of admin state), trace (render
    the broker's recent pandaprobe spans), coproc (engine breaker +
    fault-domain stats), governor (decision journal + per-domain posture),
    slo (objective verdicts + breach exemplars), failpoints (honey-badger
    arm/disarm)."""
    import io
    import tarfile
    import time

    if args.debug_cmd == "trace":
        if getattr(args, "cluster", False):
            # pandascope: the cluster-assembled view — one trace stitched
            # across every broker it touched (admin fans out to peers)
            path = (
                f"/v1/trace/cluster/{args.id}"
                if args.id is not None
                else f"/v1/trace/cluster?limit={args.limit}"
            )
            status, body = await _admin_request(args, "GET", path)
            if status != 200:
                print(f"admin api returned {status}: {body}")
                return 1
            if args.json:
                print(json.dumps(body, indent=2))
                return 0
            try:
                from tools.traceview import render_report, render_trace
            except ImportError:
                print(json.dumps(body, indent=2))
                return 0
            if args.id is not None:
                if body.get("unreachable"):
                    print(
                        f"(partial view: nodes {body['unreachable']} "
                        f"unreachable)"
                    )
                print(render_trace(body))
                return 0
            unreachable = [
                t["node"] for t in body.get("targets", [])
                if not t.get("reachable")
            ]
            if unreachable:
                print(f"(partial view: nodes {unreachable} unreachable)")
            if not body.get("traces"):
                print(
                    "no assembled cluster traces (slow ring empty — "
                    "nothing breached the slow threshold yet)"
                )
                return 0
            print(render_report(body, max_traces=args.limit))
            return 0
        path = (
            f"/v1/trace/slow?limit={args.limit}"
            if args.slow
            else f"/v1/trace/recent?limit={args.limit}"
        )
        status, body = await _admin_request(args, "GET", path)
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2))
            return 0
        if args.slow:
            spans = body.get("spans", [])
            if not spans:
                print(f"no spans over {body.get('threshold_ms')} ms")
            for s in spans:
                extra = {
                    k: v for k, v in s.items()
                    if k not in ("trace_id", "name", "start_us", "dur_us", "thread")
                }
                print(
                    f"{s['name']:<28}{s['dur_us'] / 1000.0:>10.2f}ms  "
                    f"trace={s['trace_id']} thread={s['thread']} {extra or ''}"
                )
            return 0
        try:
            from tools.traceview import render_report
        except ImportError:  # rpk installed without the tools tree
            print(json.dumps(body, indent=2))
            return 0
        if not body.get("enabled") and not body.get("traces"):
            print("tracer is disabled and the ring is empty; enable with "
                  "`trace_enabled: true` in the broker config")
            return 0
        print(render_report(body, max_traces=args.limit))
        return 0

    if args.debug_cmd == "coproc":
        status, body = await _admin_request(args, "GET", "/v1/coproc/status")
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0
        if not body.get("enabled"):
            print("coproc disabled (set coproc_enable: true)")
            return 0
        b = body.get("breaker") or {}
        print(
            f"breaker: {b.get('state', '?'):<10} trips={b.get('trips', 0)} "
            f"consecutive_failures={b.get('consecutive_failures', 0)}"
            f"/{b.get('threshold', '?')} cooldown={b.get('cooldown_ms', '?')}ms"
        )
        print(f"scripts: {', '.join(body.get('scripts') or []) or '(none)'}")
        mesh = body.get("mesh")
        if mesh:
            print(
                f"mesh:    {mesh.get('devices', '?')} devices, "
                f"decision={mesh.get('decision')}, "
                f"launches={mesh.get('launches', 0)}, "
                f"demotions={mesh.get('demotions', 0)}, "
                f"rows_per_device={mesh.get('rows_per_device')}"
            )
        stats = body.get("stats") or {}
        shown = {
            k: v for k, v in sorted(stats.items())
            if k.startswith(("t_", "n_", "bytes_")) or k == "host_workers"
        }
        for k, v in shown.items():
            v = round(v, 6) if isinstance(v, float) else v
            print(f"  {k:<28}{v}")
        for k in (
            "columnar_backend", "host_pool_probe", "host_pool_probe_prev",
            "host_pool_recal", "columnar_probe", "parse_path", "parse_probe",
            "colcache", "arena", "breakers", "lockwatch", "leakwatch",
        ):
            if stats.get(k) is not None:
                print(f"  {k:<28}{stats[k]}")
        return 0

    if args.debug_cmd == "profile":
        if args.perfetto:
            query = {"launches": str(args.launches)}
            if args.federated:
                query["federated"] = "1"
            status, body = await _admin_request(
                args, "GET", "/v1/profile/timeline", query=query
            )
            if status != 200:
                print(f"admin api returned {status}: {body}")
                return 1
            data = json.dumps(body)
            await asyncio.to_thread(_write_text, args.perfetto, data)
            events = body.get("traceEvents") or []
            extra = ""
            if body.get("unreachable"):
                extra = f" (PARTIAL: unreachable {body['unreachable']})"
            n_counters = sum(1 for e in events if e.get("ph") == "C")
            tracks = len({e["name"] for e in events if e.get("ph") == "C"})
            print(
                f"wrote {args.perfetto}: {len(events)} events, "
                f"{body.get('launches', 0)} launches, "
                f"{body.get('journal_events', '?')} journal instants, "
                f"{n_counters} counter samples on {tracks} trend tracks"
                f"{extra} — load it at https://ui.perfetto.dev"
            )
            return 0
        status, body = await _admin_request(args, "GET", "/v1/profile")
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0
        rec = body.get("recorder") or {}
        prof = body.get("profiler") or {}
        tracing = (
            "on" if body.get("tracing")
            else "OFF — timelines stay empty; set trace_enabled: true"
        )
        print(
            f"flight recorder: {'on' if body.get('enabled') else 'off'} "
            f"(tracing {tracing})"
        )
        print(
            f"  spans {rec.get('spans', 0)}/{rec.get('capacity', 0)} "
            f"(committed {rec.get('spans_recorded', 0)}), "
            f"launches {rec.get('launches', 0)}"
        )
        print(
            f"wall profiler: "
            f"{'running' if prof.get('running') else 'off'} "
            f"hz={prof.get('hz', 0)} samples={prof.get('samples', 0)} "
            f"stacks={prof.get('distinct_stacks', 0)}"
        )
        if args.top:
            rows = body.get("top") or []
            if not rows:
                print("no profile samples (set profile_hz, e.g. 19)")
                return 0
            print(f"{'SAMPLES':>8}  {'AFFINITY':<12}{'THREAD':<26}FRAME")
            for r in rows:
                print(
                    f"{r.get('samples', 0):>8}  "
                    f"{r.get('affinity', '?'):<12}"
                    f"{r.get('thread', '?'):<26}{r.get('frame', '?')}"
                )
            return 0
        totals = body.get("stage_totals_s") or {}
        if totals:
            print("stage totals (s, ring window):")
            ordered = sorted(totals.items(), key=lambda kv: -kv[1])
            for k, v in ordered[:16]:
                print(f"  {k:<40}{v:>12.6f}")
        return 0

    if args.debug_cmd == "trend":
        query = {}
        if getattr(args, "series", None):
            query["series"] = args.series
        if getattr(args, "limit", 0):
            query["limit"] = str(args.limit)
        if getattr(args, "federated", False):
            query["federated"] = "1"
        status, body = await _admin_request(
            args, "GET", "/v1/history", query=query or None
        )
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0

        def _render_node(doc: dict, indent: str = "") -> None:
            wins = doc.get("windows") or []
            print(
                f"{indent}history: {doc.get('windows_retained', 0)} windows "
                f"(interval {doc.get('interval_s', '?')}s, "
                f"recorder {'on' if doc.get('recorder_running') else 'OFF'}, "
                f"{doc.get('bytes', 0)}/{doc.get('bytes_max', 0)} bytes, "
                f"evicted {doc.get('evicted_total', 0)})"
            )
            print(
                f"{indent}breaches: {doc.get('breaches_total', 0)} journaled "
                f"(governor trend domain; `rpk debug governor` shows them)"
            )
            ewma = doc.get("ewma") or {}
            latest = wins[-1].get("tracks", {}) if wins else {}
            names = sorted(set(latest) | set(ewma))
            if names:
                print(
                    f"{indent}{'TRACK':<44}{'LATEST':>12}{'EWMA':>12}"
                    f"{'BAND':>12}  STATE"
                )
            for name in names:
                st = ewma.get(name) or {}
                cur = latest.get(name)
                print(
                    f"{indent}{name:<44}"
                    f"{cur if cur is not None else '-':>12}"
                    f"{st.get('mean', '-'):>12}"
                    f"{st.get('band', '-'):>12}  "
                    f"{'BREACHED' if st.get('breached') else 'ok'}"
                )

        if args.federated:
            if body.get("unreachable"):
                print(f"PARTIAL: unreachable {body['unreachable']}")
            for node in sorted(body.get("nodes") or {}, key=str):
                print(f"node {node}:")
                _render_node(body["nodes"][node], indent="  ")
            return 0
        _render_node(body)
        return 0

    if args.debug_cmd == "resources":
        query = {"federated": "1"} if args.federated else None
        status, body = await _admin_request(
            args, "GET", "/v1/resources", query=query
        )
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0
        if args.federated:
            print(
                f"cluster pressure: {body.get('pressure', '?')}"
                + (
                    f" (worst node {body['pressure_node']})"
                    if body.get("pressure_node") else ""
                )
                + (
                    f"  PARTIAL: unreachable {body['unreachable']}"
                    if body.get("unreachable") else ""
                )
            )
            accounts = body.get("accounts") or {}
            if accounts:
                print(
                    f"{'ACCOUNT':<16}{'HELD':>12}{'PEAK':>12}{'LIMIT':>12}"
                    f"{'WORST-OCC':>11}  NODE"
                )
            for name, a in sorted(accounts.items()):
                print(
                    f"{name:<16}{a.get('held_bytes', 0):>12}"
                    f"{a.get('peak_bytes', 0):>12}"
                    f"{a.get('limit_bytes', 0):>12}"
                    f"{a.get('max_occupancy', 0):>11.1%}  "
                    f"{a.get('max_occupancy_node') or '-'}"
                )
            for node in sorted(body.get("nodes") or {}):
                nb = body["nodes"][node]
                print(
                    f"node {node}: pressure={nb.get('pressure', '?')} "
                    f"max_occ={nb.get('max_occupancy', 0):.1%} "
                    f"in {nb.get('max_occupancy_account') or '(none)'}"
                )
            return 0
        if not body.get("enabled"):
            print("no budget plane installed (bare broker?)")
            return 0
        print(
            f"pressure: {body.get('pressure', '?')} "
            f"(max occupancy {body.get('max_occupancy', 0):.1%} in "
            f"{body.get('max_occupancy_account') or '(none)'}; warn at "
            f"{body.get('warn_pct', 0):.0%}, critical at "
            f"{body.get('critical_pct', 0):.0%})"
        )
        print(f"total:    {body.get('total_bytes', 0)} bytes")
        accounts = body.get("accounts") or {}
        if accounts:
            print(
                f"{'ACCOUNT':<16}{'HELD':>12}{'PEAK':>12}{'LIMIT':>12}"
                f"{'OCC':>8}"
            )
        for name, a in sorted(accounts.items()):
            print(
                f"{name:<16}{a.get('held_bytes', 0):>12}"
                f"{a.get('peak_bytes', 0):>12}{a.get('limit_bytes', 0):>12}"
                f"{a.get('occupancy', 0):>8.1%}"
            )
        for key in ("produce_admission", "coproc_admission"):
            ctl = body.get(key)
            if ctl:
                print(
                    f"{key}: admitted={ctl.get('admitted', 0)} "
                    f"sheds={ctl.get('sheds', 0)} "
                    f"throttle={ctl.get('base_throttle_ms', '?')}-"
                    f"{ctl.get('max_throttle_ms', '?')}ms"
                )
        auto = body.get("autotune")
        if auto:
            print(
                f"autotune: enabled={auto.get('enabled')} "
                f"group_ticks={auto.get('group_ticks')}"
                f"/{auto.get('group_ticks_cap')} "
                f"launch_depth={auto.get('launch_depth')}"
                f"/{auto.get('launch_depth_cap')} "
                f"hold={auto.get('hold_s')}s"
            )
        return 0

    if args.debug_cmd == "governor":
        query = {"limit": str(args.limit)}
        if args.domain:
            query["domain"] = args.domain
        status, body = await _admin_request(
            args, "GET", "/v1/governor", query=query
        )
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0
        posture = body.get("posture")
        if posture:
            print("posture:")
            for dom in (
                "host_pool", "columnar_backend", "device_lz4",
                "harvest_path", "sharded_seal",
            ):
                print(f"  {dom:<20}{posture.get(dom) or '(undecided)'}")
            for dom, b in sorted((posture.get("breakers") or {}).items()):
                print(
                    f"  breaker[{dom}]".ljust(22)
                    + f"{b.get('state', '?')} trips={b.get('trips', 0)} "
                    f"consecutive={b.get('consecutive_failures', 0)}"
                    f"/{b.get('threshold', '?')}"
                )
            for dom, ms in sorted(
                (posture.get("deadlines_ms") or {}).items()
            ):
                print(f"  deadline[{dom}]".ljust(22) + f"{ms}ms")
        else:
            print("no live coproc engine (journal below is process-wide)")
        summary = body.get("summary") or {}
        print(
            f"journal: {summary.get('entries', 0)} entries "
            f"(seq {summary.get('seq', 0)}, "
            f"{summary.get('dropped', 0)} dropped, "
            f"capacity {summary.get('capacity', 0)})"
        )
        entries = body.get("journal") or []
        if entries:
            print(f"{'SEQ':>5}  {'DOMAIN':<18}{'VERDICT':<12}REASON")
        for e in entries:
            print(
                f"{e['seq']:>5}  {e['domain']:<18}{e['verdict']:<12}"
                f"{e['reason']}"
            )
            inputs = e.get("inputs") or {}
            if inputs:
                print(f"{'':>7}inputs: {json.dumps(inputs, sort_keys=True)}")
        return 0

    if args.debug_cmd == "slo":
        # mark names are user input riding a query string: sent via the
        # `query` dict so they get exactly ONE correct encoding (a name
        # with '&'/'=' must not split the query; pre-quoting into the path
        # would get '%' re-encoded by _admin_request)
        if args.set_mark is not None:
            query = {"name": args.set_mark}
            if getattr(args, "federated", False):
                query["federated"] = "1"
            status, body = await _admin_request(
                args, "POST", "/v1/slo/mark", query=query
            )
            if status != 200:
                print(f"admin api returned {status}: {body}")
                return 1
            if body.get("federated"):
                print(
                    f"federated mark {body['mark']!r} set over nodes "
                    f"{body.get('nodes')}"
                    + (
                        f" (unreachable: {body['unreachable']})"
                        if body.get("unreachable") else ""
                    )
                )
            else:
                print(
                    f"mark {body['mark']!r} set over {body['series']} series"
                )
            return 0
        query = {}
        if args.mark:
            query["mark"] = args.mark
        if getattr(args, "federated", False):
            query["federated"] = "1"
        status, body = await _admin_request(
            args, "GET", "/v1/slo", query=query or None,
        )
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0
        verdict = "PASS" if body.get("pass") else "FAIL"
        print(
            f"scenario {body.get('scenario')}: {verdict} "
            f"({body.get('failed', 0)} failed, {body.get('no_data', 0)} no-data; "
            f"window {body.get('window')})"
        )
        fed_meta = body.get("federation")
        if fed_meta is not None:
            line = (
                f"federated over nodes {fed_meta.get('nodes')}"
            )
            if fed_meta.get("unreachable"):
                line += (
                    f" — PARTIAL: {fed_meta['unreachable']} unreachable"
                )
            print(line)
        print(
            f"{'OBJECTIVE':<24}{'METRIC':<30}{'Q':>5}{'OBSERVED':>12}"
            f"{'THRESHOLD':>12}{'SAMPLES':>9}  STATUS"
        )
        for o in body.get("objectives", []):
            obs = o.get("observed_ms")
            print(
                f"{o['name']:<24}{o['metric']:<30}"
                f"{('p%g' % o['quantile']):>5}"
                f"{(('%.2fms' % obs) if obs is not None else '-'):>12}"
                f"{('%gms' % o['threshold_ms']):>12}"
                f"{o.get('samples', 0):>9}  {o['status']}"
            )
            for ex in (o.get("exemplars") or [])[:5]:
                print(
                    f"    breach exemplar: trace={ex['trace_id']} "
                    f"{ex['value_us'] / 1000.0:.2f}ms "
                    f"(bucket <= {ex['bucket_us'] / 1000.0:.2f}ms) — "
                    f"`rpk debug trace --slow` resolves it "
                    f"(--cluster --id {ex['trace_id']} assembles it)"
                )
            for node, nv in sorted((o.get("per_node") or {}).items()):
                obs_n = nv.get("observed_ms")
                print(
                    f"    node {node}: "
                    f"{(('%.2fms' % obs_n) if obs_n is not None else '-')} "
                    f"({nv.get('samples', 0)} samples, {nv.get('status')})"
                )
        if body.get("exemplars_enabled") is False:
            # local reports only: the federated report has no exemplar
            # layer at all (exemplar rings are per-process)
            print(
                "note: tracer disabled — breaches carry no exemplars "
                "(set trace_enabled: true)"
            )
        return 0

    if args.debug_cmd == "failpoints":
        if args.fp_cmd == "list":
            status, body = await _admin_request(args, "GET", "/v1/failure-probes")
            if status != 200:
                print(f"admin api returned {status}: {body}")
                return 1
            armed = body.get("armed") or {}
            counts = body.get("counts") or {}
            print(f"honey badger enabled: {body.get('enabled', False)}")
            for module, probes_ in sorted((body.get("modules") or {}).items()):
                for probe in probes_:
                    effect = armed.get(module, {}).get(probe, "-")
                    rem = counts.get(module, {}).get(probe)
                    if rem is not None:
                        effect = f"{effect} (x{rem} left)"
                    print(f"  {module + '.' + probe:<40}{effect}")
            return 0
        if args.fp_cmd == "arm":
            path = f"/v1/failure-probes/{args.module}/{args.probe}/{args.type}"
            query = {}
            if args.count is not None:
                query["count"] = str(args.count)
            if getattr(args, "delay_ms", None) is not None:
                query["delay_ms"] = str(args.delay_ms)
            status, body = await _admin_request(
                args, "PUT", path, query=query or None
            )
        else:  # disarm
            status, body = await _admin_request(
                args, "DELETE",
                f"/v1/failure-probes/{args.module}/{args.probe}",
            )
        if status != 200:
            print(f"admin api returned {status}: {body}")
            return 1
        print(json.dumps(body))
        return 0

    bundle: dict[str, object] = {}
    for name, path in [
        ("config.json", "/v1/config"),
        ("brokers.json", "/v1/brokers"),
        ("partitions.json", "/v1/partitions"),
        ("metrics.txt", "/metrics"),
        ("traces.json", "/v1/trace/recent"),
        # pandascope cluster view: the slow ring's traces assembled across
        # every broker they touched + the merged multi-node scrape
        ("cluster_traces.json", "/v1/trace/cluster"),
        ("federated_metrics.json", "/v1/federation/metrics"),
        ("coproc.json", "/v1/coproc/status"),
        ("governor.json", "/v1/governor"),
        ("resources.json", "/v1/resources"),
        # pandapulse: profiler/recorder status + the launch timeline (the
        # Perfetto-loadable artifact — open timeline.json at ui.perfetto.dev)
        ("profile.json", "/v1/profile"),
        ("timeline.json", "/v1/profile/timeline"),
        # pandatrend: the metrics-history ring (per-window rates/quantiles
        # + EWMA band state) — what `rpk debug trend` renders
        ("history.json", "/v1/history"),
        ("slo.json", "/v1/slo"),
        ("failpoints.json", "/v1/failure-probes"),
    ]:
        status, body = await _admin_request(args, "GET", path)
        bundle[name] = body if status == 200 else {"error": status}
    out = args.output or f"debug-bundle-{int(time.time())}.tar.gz"
    with tarfile.open(out, "w:gz") as tar:
        for name, content in bundle.items():
            data = (
                content.encode() if isinstance(content, str)
                else json.dumps(content, indent=2).encode()
            )
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    print(f"wrote {out}")
    return 0


def cmd_generate(args) -> int:
    if args.generate_cmd == "k8s-manifests":
        from redpanda_tpu.cli.k8s import generate_manifests

        print(generate_manifests(
            name=args.name, namespace=args.namespace,
            replicas=args.replicas, image=args.image, storage=args.storage,
        ))
        return 0
    if args.generate_cmd == "prometheus-config":
        print(json.dumps({
            "scrape_configs": [{
                "job_name": "redpanda_tpu",
                "static_configs": [{"targets": [args.admin_api]}],
                "metrics_path": "/metrics",
            }]
        }, indent=2))
    else:  # grafana-dashboard
        print(json.dumps({
            "title": "redpanda_tpu",
            "panels": [
                {"title": "Partitions", "expr": "redpanda_tpu_partitions_total"},
                {"title": "Topics", "expr": "redpanda_tpu_topics_total"},
                {"title": "Produce latency", "expr": "redpanda_tpu_kafka_produce_latency_us_bucket"},
                {"title": "Fetch latency", "expr": "redpanda_tpu_kafka_fetch_latency_us_bucket"},
                {"title": "Storage append latency", "expr": "redpanda_tpu_storage_append_latency_us_bucket"},
                {"title": "Raft replicate latency", "expr": "redpanda_tpu_raft_replicate_latency_us_bucket"},
                {"title": "Coproc stage latency", "expr": "redpanda_tpu_coproc_stage_latency_us_bucket"},
                {"title": "Device link bytes", "expr": "redpanda_tpu_coproc_device_transfer_bytes_total"},
            ],
        }, indent=2))
    return 0


def cmd_tune(args) -> int:
    """Checker/tunable autotune (tuners/check.go + checked_tunable.go):
    each tuner reads real kernel state, reports ok/would-tune/unsupported,
    and mutates when permitted; --dry-run stops after the check."""
    from redpanda_tpu.cli.tuners import all_tuners, format_outcomes, run_tuners

    known = [t.name for t in all_tuners()]
    if args.tuner == "list":
        print("\n".join(known))
        return 0
    names = None if args.tuner == "all" else [args.tuner]
    if names and names[0] not in known:
        print(f"unknown tuner {names[0]!r}; `rpk tune list` shows them", file=sys.stderr)
        return 1
    outcomes = run_tuners(
        names,
        root=args.root,
        dry_run=args.dry_run,
        ballast_path=args.ballast_path,
        ballast_size=args.ballast_size,
    )
    print(format_outcomes(outcomes, args.dry_run))
    # exit 1 when anything errored or an apply failed verification
    bad = any(o.error or (o.applied and o.post_ok is False) for o in outcomes)
    return 1 if bad else 0


def cmd_iotune(args) -> int:
    """Benchmark the data dir and persist io-config.json (the reference's
    `rpk iotune` io-properties flow); `start` publishes the numbers."""
    from redpanda_tpu.cli.iotune import measure, write_io_config

    data_dir = args.directory
    print(f"iotune: characterizing {data_dir} ...")
    try:
        result = measure(
            data_dir,
            file_bytes=args.probe_mb << 20,
            fsync_iters=args.fsync_iters,
        )
        path = write_io_config(data_dir, result)
    except OSError as e:
        # permission denied / disk full mid-probe: clean refusal, not a
        # traceback (the default directory needs broker-level privileges)
        print(f"iotune: cannot characterize {data_dir}: {e}", file=sys.stderr)
        return 1
    print(f"  seq write : {result['seq_write_mb_s']:.1f} MB/s")
    print(f"  seq read  : {result['seq_read_mb_s']:.1f} MB/s")
    f = result["fsync_4k"]
    print(f"  fsync 4k  : p50 {f['p50_ms']} ms, p99 {f['p99_ms']} ms")
    print(f"written {path}")
    return 0


# ================================================================ arg parsing
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rpk", description=__doc__)
    p.add_argument("--brokers", default=DEFAULT_BROKERS, help="host:port[,host:port]")
    p.add_argument("--admin-api", default=DEFAULT_ADMIN)
    p.add_argument("--user", help="SASL username")
    p.add_argument("--password", help="SASL password")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a broker")
    sp.add_argument("--config", help="redpanda.yaml path")
    sp.add_argument("--set", action="append", help="key=value override")

    tp = sub.add_parser("topic", help="topic operations")
    tsub = tp.add_subparsers(dest="topic_cmd", required=True)
    tc = tsub.add_parser("create")
    tc.add_argument("name")
    tc.add_argument("-p", "--partitions", type=int, default=1)
    tc.add_argument("-r", "--replicas", type=int, default=1)
    tc.add_argument("-c", "--topic-config", action="append", help="key=value")
    td = tsub.add_parser("delete")
    td.add_argument("name")
    tsub.add_parser("list")
    tde = tsub.add_parser("describe")
    tde.add_argument("name")
    tpr = tsub.add_parser("produce")
    tpr.add_argument("name")
    tpr.add_argument("value", help="record value ('-' = stdin)")
    tpr.add_argument("-p", "--partition", type=int, default=0)
    tpr.add_argument("-k", "--key", default=None)
    tco = tsub.add_parser("consume")
    tco.add_argument("name")
    tco.add_argument("-p", "--partition", type=int, default=0)
    tco.add_argument("-o", "--offset", type=int, default=-1)
    tco.add_argument("-n", "--num", type=int, default=10)

    ap = sub.add_parser("acl", help="acl operations")
    asub = ap.add_subparsers(dest="acl_cmd", required=True)
    ac = asub.add_parser("create")
    ac.add_argument("--resource", choices=["topic", "group", "cluster", "transactional_id"], required=True)
    ac.add_argument("--resource-name", required=True)
    ac.add_argument("--principal", required=True)
    ac.add_argument("--operation", required=True)
    ac.add_argument("--host", default="*")
    ac.add_argument("--deny", action="store_true")
    asub.add_parser("list")

    wp = sub.add_parser("wasm", help="inline transform operations")
    wsub = wp.add_subparsers(dest="wasm_cmd", required=True)
    wsub.add_parser("generate", help="print a transform template")
    wd = wsub.add_parser("deploy")
    wd.add_argument("file", help="transform JSON (see wasm generate)")
    wr = wsub.add_parser("remove")
    wr.add_argument("name")

    cp = sub.add_parser("cluster", help="cluster info + leadership balance")
    csub = cp.add_subparsers(dest="cluster_cmd")
    csub.add_parser("info")
    crb = csub.add_parser("rebalance", help="spread partition leaderships")
    crb.add_argument(
        "--admin-apis",
        help="comma-separated admin endpoints, one per broker "
        "(each node sheds its own excess)",
    )

    up = sub.add_parser("user", help="SCRAM users (admin api)")
    usub = up.add_subparsers(dest="user_cmd", required=True)
    uc = usub.add_parser("create")
    uc.add_argument("name")
    uc.add_argument("--new-password", required=True)
    uc.add_argument("--mechanism", default="SCRAM-SHA-256")
    ud = usub.add_parser("delete")
    ud.add_argument("name")
    usub.add_parser("list")

    cfp = sub.add_parser("config", help="configuration")
    cfsub = cfp.add_subparsers(dest="config_cmd", required=True)
    cg = cfsub.add_parser("get")
    cg.add_argument("key", nargs="?")
    cfsub.add_parser("set")

    dp = sub.add_parser("debug", help="diagnostics")
    dsub = dp.add_subparsers(dest="debug_cmd", required=True)
    db = dsub.add_parser("bundle")
    db.add_argument("-o", "--output")
    dt = dsub.add_parser("trace", help="recent pandaprobe spans (admin api)")
    dt.add_argument("--slow", action="store_true", help="slow-request log only")
    dt.add_argument("--limit", type=int, default=10, help="traces/spans to fetch")
    dt.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dt.add_argument(
        "--cluster", action="store_true",
        help="pandascope: assemble traces across every broker they "
             "touched (admin fans out to peers; no --id = the slow "
             "ring's traces)",
    )
    dt.add_argument(
        "--id", type=int, default=None, metavar="TRACE_ID",
        help="with --cluster: assemble this one trace id",
    )
    dc = dsub.add_parser(
        "coproc", help="engine breaker + fault-domain + stage stats"
    )
    dc.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dres = dsub.add_parser(
        "resources",
        help="budget plane: account occupancy, pressure, admission + "
             "autotune state (admin api)",
    )
    dres.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dres.add_argument(
        "--federated", action="store_true",
        help="merge every node's budget-account occupancy (admin fans "
             "out to peers; occupancy/pressure report the worst node)",
    )
    dprof = dsub.add_parser(
        "profile",
        help="pandapulse flight recorder + wall profiler (admin api)",
    )
    dprof.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dprof.add_argument(
        "--perfetto", default=None, metavar="OUT.json",
        help="write the Chrome trace-event launch timeline (governor "
             "verdicts + admission episodes as instant events); load it "
             "at https://ui.perfetto.dev",
    )
    dprof.add_argument(
        "--top", action="store_true",
        help="wall-profile leaf-frame attribution table (needs profile_hz)",
    )
    dprof.add_argument(
        "--launches", type=int, default=0,
        help="with --perfetto: newest N launches (0 = every launch in the ring)",
    )
    dprof.add_argument(
        "--federated", action="store_true",
        help="with --perfetto: assemble the cluster timeline across "
             "every broker (like rpk debug trace --cluster)",
    )
    dtrend = dsub.add_parser(
        "trend",
        help="pandatrend metrics history: per-window rates/quantiles, "
             "EWMA bands + breach state (admin api GET /v1/history)",
    )
    dtrend.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dtrend.add_argument(
        "--series", default=None,
        help="substring filter over series keys (counters/gauges/hists/tracks)",
    )
    dtrend.add_argument(
        "--limit", type=int, default=0,
        help="newest N windows only (0 = the whole retained ring)",
    )
    dtrend.add_argument(
        "--federated", action="store_true",
        help="fan out to every broker's admin: per-node window rings "
             "side by side (windows never merge across wall clocks)",
    )
    dgov = dsub.add_parser(
        "governor",
        help="coproc decision journal + per-domain posture (admin api)",
    )
    dgov.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dgov.add_argument(
        "--limit", type=int, default=32, help="journal entries to fetch"
    )
    dgov.add_argument(
        "--domain", default=None,
        help="filter the journal to one decision domain",
    )
    dslo = dsub.add_parser(
        "slo", help="SLO verdicts over the pandaprobe histograms (admin api)"
    )
    dslo.add_argument("--json", action="store_true", help="raw JSON, no rendering")
    dslo.add_argument(
        "--mark", default=None,
        help="judge only observations since this named baseline",
    )
    dslo.add_argument(
        "--set-mark", default=None, metavar="NAME",
        help="snapshot a named baseline instead of evaluating",
    )
    dslo.add_argument(
        "--federated", action="store_true",
        help="judge the objectives over the merged multi-node /metrics "
             "scrape (node-labeled drill-down) instead of this broker's "
             "registry",
    )
    dfp = dsub.add_parser(
        "failpoints", help="list/arm/disarm honey-badger failure probes"
    )
    fpsub = dfp.add_subparsers(dest="fp_cmd", required=True)
    fpsub.add_parser("list")
    fpa = fpsub.add_parser("arm")
    fpa.add_argument("module")
    fpa.add_argument("probe")
    fpa.add_argument(
        "type", choices=["exception", "delay", "wedge", "terminate", "corrupt"],
    )
    fpa.add_argument(
        "--count", type=int, default=None,
        help="auto-disarm after N injections (1 = one-shot)",
    )
    fpa.add_argument(
        "--delay-ms", type=int, default=None, dest="delay_ms",
        help="size the injected delay (the knob lives in the broker "
             "process; remote chaos drivers have no other way to set it)",
    )
    fpd = fpsub.add_parser("disarm")
    fpd.add_argument("module")
    fpd.add_argument("probe")

    gp = sub.add_parser("generate", help="monitoring + deployment configs")
    gsub = gp.add_subparsers(dest="generate_cmd", required=True)
    gsub.add_parser("grafana-dashboard")
    gsub.add_parser("prometheus-config")
    gk = gsub.add_parser("k8s-manifests")
    gk.add_argument("--name", default="redpanda-tpu")
    gk.add_argument("--namespace", default="default")
    gk.add_argument("--replicas", type=int, default=3)
    gk.add_argument("--image", default="redpanda-tpu:latest")
    gk.add_argument("--storage", default="20Gi")

    tns = sub.add_parser("tune", help="check and apply kernel tuners (autotune)")
    tns.add_argument(
        "tuner", nargs="?", default="all",
        help="'all', 'list', or one tuner name",
    )
    tns.add_argument(
        "--dry-run", action="store_true",
        help="report required changes without mutating anything",
    )
    tns.add_argument(
        "--root", default="/",
        help="filesystem root for /proc and /sys (tests/containers)",
    )
    tns.add_argument("--ballast-path", default=None)
    tns.add_argument("--ballast-size", type=int, default=None)
    iop = sub.add_parser("iotune", help="benchmark the data dir, write io-config.json")
    # default must match the broker's data_directory default so a stock
    # `rpk iotune` + `redpanda start` pair actually connects
    iop.add_argument("--directory", default="/var/lib/redpanda_tpu")
    iop.add_argument("--probe-mb", type=int, default=64, help="probe file size")
    iop.add_argument("--fsync-iters", type=int, default=50)

    cnp = sub.add_parser("container", help="local multi-broker dev cluster")
    cnsub = cnp.add_subparsers(dest="container_cmd")
    # --dir goes on every SUBparser so `rpk container start --dir X` works
    # (options on the parent are only accepted before the subcommand)
    cns = cnsub.add_parser("start")
    cns.add_argument("-n", "--nodes", type=int, default=1)
    cns.add_argument("--dir", help="cluster state directory")
    cns.add_argument(
        "--set", action="append", metavar="K=V",
        help="extra broker config overrides (repeatable), e.g. coproc_enable=1",
    )
    for name in ("status", "stop", "purge"):
        cnsub.add_parser(name).add_argument("--dir", help="cluster state directory")

    plp = sub.add_parser("plugin", help="external rpk-<name> plugins")
    plsub = plp.add_subparsers(dest="plugin_cmd")
    plsub.add_parser("list")
    return p


def _find_plugins() -> dict[str, str]:
    """rpk-<name> executables on PATH (the reference's plugin discovery,
    src/go/rpk plugin system: any `rpk-foo` binary serves `rpk foo`)."""
    out: dict[str, str] = {}
    for d in os.environ.get("PATH", "").split(os.pathsep):
        try:
            entries = os.listdir(d or ".")
        except OSError:
            continue
        for e in entries:
            if e.startswith("rpk-"):
                path = os.path.join(d or ".", e)
                if os.access(path, os.X_OK) and e[4:] not in out:
                    out[e[4:]] = path
    return out


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    parser = build_parser()
    # plugin fallback BEFORE parsing: `rpk foo ...` execs `rpk-foo ...`
    # when foo is not a built-in; the built-in set is derived from the
    # parser itself so a new subcommand can never silently lose to a
    # same-named plugin
    known = next(
        a.choices.keys()
        for a in parser._subparsers._group_actions  # noqa: SLF001
        if hasattr(a, "choices")
    )
    if argv and not argv[0].startswith("-") and argv[0] not in known:
        plugin = _find_plugins().get(argv[0])
        if plugin is not None:
            return subprocess.call([plugin, *argv[1:]])
    args = parser.parse_args(argv)
    if args.cmd == "container":
        from redpanda_tpu.cli.container import cmd_container

        return cmd_container(args)
    if args.cmd == "plugin":
        for name, path in sorted(_find_plugins().items()):
            print(f"{name:<20} {path}")
        return 0
    table = {
        "start": cmd_start,
        "topic": cmd_topic,
        "acl": cmd_acl,
        "wasm": cmd_wasm,
        "cluster": cmd_cluster,
        "user": cmd_user,
        "config": cmd_config,
    }
    if args.cmd == "debug":
        return asyncio.run(cmd_debug(args))
    if args.cmd == "generate":
        return cmd_generate(args)
    if args.cmd == "tune":
        return cmd_tune(args)
    if args.cmd == "iotune":
        return cmd_iotune(args)
    return asyncio.run(table[args.cmd](args))


def _read_text(path: str) -> str:
    with open(path) as f:
        return f.read()


if __name__ == "__main__":
    sys.exit(main())
