"""Failure-injection registry ("honey badger").

Parity with finjector/hbadger.h:23-60: subsystems register named probes;
tests (or the admin API) arm a probe on a module with one of four effects —
raise an exception, delay, wedge (block at the site until disarmed or
``wedge_max_s``, simulating a hung device fetch / dead link), or terminate
(here: raise SystemExit, since we have no per-shard process to kill). The
reference compiles probes out of release builds (hbadger.h:30-37); here
arming is a no-op unless ``honey_badger.enable()`` was called, so
production paths stay branch-cheap (the breaker_overhead microbench gates
the disabled check at <1% of the coproc launch path).

Admin wiring: ``GET /v1/failure-probes`` lists registered modules/probes
and what is currently armed; ``PUT /v1/failure-probes/{module}/{probe}/
{exception|delay|wedge|terminate}`` arms (enabling the registry first) and
``DELETE /v1/failure-probes/{module}/{probe}`` disarms — surfaced by
``rpk debug failpoints``. The coproc fault domains (device dispatch, mask
fetch, harvest, shard worker, sandbox compile) register in
coproc/faults.py; per-RPC-method probes are generated alongside services
(tools/rpcgen.py:159-165 renders a failure_probes struct per service) and
rpc.service mirrors that by registering ``<service>.<method>`` probes
automatically; the transport layer registers ``rpc.send``.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from dataclasses import dataclass, field

EXCEPTION = "exception"
DELAY = "delay"
TERMINATE = "terminate"
WEDGE = "wedge"

EFFECTS = (EXCEPTION, DELAY, WEDGE, TERMINATE)


class ProbeTriggered(Exception):
    """Raised by an armed 'exception' probe."""


@dataclass
class _Module:
    probes: set = field(default_factory=set)
    armed: dict = field(default_factory=dict)  # probe -> effect


class HoneyBadger:
    def __init__(self) -> None:
        self._enabled = False
        self._modules: dict[str, _Module] = defaultdict(_Module)
        self.delay_ms = 50
        # A wedge simulates an indefinite hang, but an orphaned wedge (the
        # operator forgot to disarm) must not hold a broker thread forever:
        # the site blocks until the probe is disarmed OR this cap elapses.
        # Tests lower it to keep deadline-abandonment runs fast.
        self.wedge_max_s = 2.0

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False
        for m in self._modules.values():
            m.armed.clear()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def register_probe(self, module: str, *probes: str) -> None:
        self._modules[module].probes.update(probes)

    def modules(self) -> dict[str, list[str]]:
        return {name: sorted(m.probes) for name, m in self._modules.items()}

    def armed(self) -> dict[str, dict[str, str]]:
        """module -> {probe: effect} for every currently-armed probe."""
        return {
            name: dict(m.armed)
            for name, m in self._modules.items()
            if m.armed
        }

    def set_exception(self, module: str, probe: str) -> None:
        self._arm(module, probe, EXCEPTION)

    def set_delay(self, module: str, probe: str) -> None:
        self._arm(module, probe, DELAY)

    def set_termination(self, module: str, probe: str) -> None:
        self._arm(module, probe, TERMINATE)

    def set_wedge(self, module: str, probe: str) -> None:
        self._arm(module, probe, WEDGE)

    def unset(self, module: str, probe: str) -> None:
        # plain lookup, not the defaultdict: disarming a typo'd name must
        # not conjure a phantom module entry into modules()/armed()
        m = self._modules.get(module)
        if m is not None:
            m.armed.pop(probe, None)

    def _arm(self, module: str, probe: str, effect: str) -> None:
        if not self._enabled:
            return
        self._modules[module].armed[probe] = effect

    def _wedged(self, module: str, probe: str) -> bool:
        return (
            self._enabled
            and self._modules[module].armed.get(probe) == WEDGE
        )

    async def maybe_inject(self, module: str, probe: str) -> None:
        """Await point placed at each probe site."""
        if not self._enabled:
            return
        effect = self._modules[module].armed.get(probe)
        if effect is None:
            return
        if effect == DELAY:
            await asyncio.sleep(self.delay_ms / 1000)
        elif effect == EXCEPTION:
            raise ProbeTriggered(f"{module}.{probe}")
        elif effect == WEDGE:
            deadline = time.monotonic() + self.wedge_max_s
            while time.monotonic() < deadline and self._wedged(module, probe):
                await asyncio.sleep(0.01)
        elif effect == TERMINATE:
            raise SystemExit(f"honey badger terminate: {module}.{probe}")

    def inject_sync(self, module: str, probe: str) -> None:
        """Synchronous probe site (storage paths, coproc device legs)."""
        if not self._enabled:
            return
        effect = self._modules[module].armed.get(probe)
        if effect == EXCEPTION:
            raise ProbeTriggered(f"{module}.{probe}")
        if effect == TERMINATE:
            raise SystemExit(f"honey badger terminate: {module}.{probe}")
        if effect == DELAY:
            # deliberate BLOCKING sleep: a delay fault at a sync site must
            # actually delay (stalling the loop is the injected fault —
            # this only ever runs with the badger explicitly enabled)
            time.sleep(self.delay_ms / 1000)
        elif effect == WEDGE:
            # block like a hung device fetch until disarmed (or the cap):
            # this is what the engine's per-attempt deadlines must cut
            # through by abandoning the wedged worker
            deadline = time.monotonic() + self.wedge_max_s
            while time.monotonic() < deadline and self._wedged(module, probe):
                time.sleep(0.01)


honey_badger = HoneyBadger()
