"""Failure-injection registry ("honey badger").

Parity with finjector/hbadger.h:23-60: subsystems register named probes;
tests (or the admin API) arm a probe on a module with one of four effects —
raise an exception, delay, wedge (block at the site until disarmed or
``wedge_max_s``, simulating a hung device fetch / dead link), or terminate
(here: raise SystemExit, since we have no per-shard process to kill). The
reference compiles probes out of release builds (hbadger.h:30-37); here
arming is a no-op unless ``honey_badger.enable()`` was called, so
production paths stay branch-cheap (the breaker_overhead microbench gates
the disabled check at <1% of the coproc launch path).

Admin wiring: ``GET /v1/failure-probes`` lists registered modules/probes
and what is currently armed (plus remaining counts for count-limited
probes); ``PUT /v1/failure-probes/{module}/{probe}/
{exception|delay|wedge|terminate}[?count=N]`` arms (enabling the registry
first; ``count=1`` = one-shot, auto-disarming after its first injection)
and ``DELETE /v1/failure-probes/{module}/{probe}`` disarms — surfaced by
``rpk debug failpoints arm [--count N]``. The coproc fault domains (device dispatch, mask
fetch, harvest, shard worker, sandbox compile) register in
coproc/faults.py; per-RPC-method probes are generated alongside services
(tools/rpcgen.py:159-165 renders a failure_probes struct per service) and
rpc.service mirrors that by registering ``<service>.<method>`` probes
automatically; the transport layer registers ``rpc.send``.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

EXCEPTION = "exception"
DELAY = "delay"
TERMINATE = "terminate"
WEDGE = "wedge"
# data-corruption fault: the SITE mutates its own bytes when the claim
# fires (an injector can't reach into a site's buffers) — used by the raft
# append path to prove the device-plane CRC validation rejects torn blobs
CORRUPT = "corrupt"

EFFECTS = (EXCEPTION, DELAY, WEDGE, TERMINATE, CORRUPT)


class ProbeTriggered(Exception):
    """Raised by an armed 'exception' probe."""


@dataclass
class _Module:
    probes: set = field(default_factory=set)
    armed: dict = field(default_factory=dict)  # probe -> effect
    # probe -> remaining injections; absent = armed until disarmed. A probe
    # armed with count=1 ("one-shot") auto-disarms after its first
    # injection — deterministic single-fault tests without a disarm race.
    counts: dict = field(default_factory=dict)


class HoneyBadger:
    def __init__(self) -> None:
        self._enabled = False
        self._modules: dict[str, _Module] = defaultdict(_Module)
        # serializes count-limited claims: probe sites fire concurrently
        # (pool workers, harvester, RPC handlers), and "exactly N
        # injections" needs an atomic select+decrement. Only taken when
        # the registry is enabled — the disabled fast path stays lock-free.
        self._claim_lock = threading.Lock()
        self.delay_ms = 50
        # A wedge simulates an indefinite hang, but an orphaned wedge (the
        # operator forgot to disarm) must not hold a broker thread forever:
        # the site blocks until the probe is disarmed OR this cap elapses.
        # Tests lower it to keep deadline-abandonment runs fast.
        self.wedge_max_s = 2.0

    def enable(self) -> None:
        self._enabled = True  # pandalint: disable=RAC1101 -- benign monotonic bool: probe sites read one attribute lock-free BY DESIGN (hbadger.h's compiled-out posture); arming happens before the faulted traffic, and a racy read costs one missed/extra injection, never corruption

    def disable(self) -> None:
        self._enabled = False  # pandalint: disable=RAC1101 -- same single-flag design as enable(); count-limited claims take _claim_lock, the flag itself is a benign gate
        for m in self._modules.values():
            m.armed.clear()
            m.counts.clear()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def register_probe(self, module: str, *probes: str) -> None:
        self._modules[module].probes.update(probes)

    def modules(self) -> dict[str, list[str]]:
        return {name: sorted(m.probes) for name, m in self._modules.items()}

    def armed(self) -> dict[str, dict[str, str]]:
        """module -> {probe: effect} for every currently-armed probe."""
        return {
            name: dict(m.armed)
            for name, m in self._modules.items()
            if m.armed
        }

    def armed_counts(self) -> dict[str, dict[str, int]]:
        """module -> {probe: remaining injections} for count-limited
        probes only (unlimited probes don't appear)."""
        return {
            name: dict(m.counts)
            for name, m in self._modules.items()
            if m.counts
        }

    def remaining(self, module: str, probe: str) -> int | None:
        """Remaining injections for a count-limited probe; None when the
        probe is unlimited or not armed."""
        m = self._modules.get(module)
        return None if m is None else m.counts.get(probe)

    def set_exception(self, module: str, probe: str, count: int | None = None) -> None:
        self._arm(module, probe, EXCEPTION, count)

    def set_delay(self, module: str, probe: str, count: int | None = None) -> None:
        self._arm(module, probe, DELAY, count)

    def set_termination(self, module: str, probe: str, count: int | None = None) -> None:
        self._arm(module, probe, TERMINATE, count)

    def set_wedge(self, module: str, probe: str, count: int | None = None) -> None:
        self._arm(module, probe, WEDGE, count)

    def set_corrupt(self, module: str, probe: str, count: int | None = None) -> None:
        self._arm(module, probe, CORRUPT, count)

    def corrupt_claim(self, module: str, probe: str) -> bool:
        """True when an armed CORRUPT probe fires for this call — the SITE
        then flips its own bytes (count budgets consume per claim, exactly
        like the other effects). A probe armed with a non-corrupt effect
        is NOT consumed here: the site's maybe_inject/inject_sync owns it."""
        if not self._enabled:
            return False
        m = self._modules.get(module)
        if m is None or m.armed.get(probe) != CORRUPT:
            return False
        effect, _ = self._claim(module, probe)
        return effect == CORRUPT

    def unset(self, module: str, probe: str) -> None:
        # plain lookup, not the defaultdict: disarming a typo'd name must
        # not conjure a phantom module entry into modules()/armed()
        m = self._modules.get(module)
        if m is not None:
            m.armed.pop(probe, None)
            m.counts.pop(probe, None)

    def _arm(self, module: str, probe: str, effect: str, count: int | None = None) -> None:
        if not self._enabled:
            return
        m = self._modules[module]
        m.armed[probe] = effect
        if count is not None and int(count) > 0:
            m.counts[probe] = int(count)
        else:
            # re-arming without a count clears a stale one-shot budget
            m.counts.pop(probe, None)

    def _claim(self, module: str, probe: str) -> tuple[str | None, bool]:
        """Atomically select the effect for ONE injection, consuming a
        count-limited budget (probe sites race from pool workers — an
        unlocked check-then-consume would fire a count=1 probe twice).
        Returns (effect, disarm_after): effect is None when nothing is
        armed or the budget is spent; disarm_after=True means this was a
        count-limited WEDGE's last injection — the wedge block polls the
        armed state, so it stays armed through the block and the SITE
        disarms it afterwards (counts pinned at 0 meanwhile, so a racing
        claim sees the drained budget, not an unlimited wedge). Other
        effects disarm right here at zero. The registry stays enabled
        either way — the admin DELETE handler owns the
        last-probe-disables-registry rule."""
        with self._claim_lock:
            m = self._modules.get(module)
            effect = m.armed.get(probe) if m is not None else None
            if effect is None:
                return None, False
            c = m.counts.get(probe)
            if c is None:
                return effect, False  # unlimited
            if c <= 0:
                return None, False  # drained wedge mid-block elsewhere
            if c == 1:
                if effect == WEDGE:
                    m.counts[probe] = 0
                    return effect, True
                m.armed.pop(probe, None)
                m.counts.pop(probe, None)
                return effect, False
            m.counts[probe] = c - 1
            return effect, False

    def _wedged(self, module: str, probe: str) -> bool:
        return (
            self._enabled
            and self._modules[module].armed.get(probe) == WEDGE
        )

    async def maybe_inject(self, module: str, probe: str) -> None:
        """Await point placed at each probe site."""
        if not self._enabled:
            return
        effect, disarm_after = self._claim(module, probe)
        if effect == DELAY:
            await asyncio.sleep(self.delay_ms / 1000)
        elif effect == EXCEPTION:
            raise ProbeTriggered(f"{module}.{probe}")
        elif effect == WEDGE:
            deadline = time.monotonic() + self.wedge_max_s
            while time.monotonic() < deadline and self._wedged(module, probe):
                await asyncio.sleep(0.01)
            if disarm_after:
                self.unset(module, probe)
        elif effect == TERMINATE:
            raise SystemExit(f"honey badger terminate: {module}.{probe}")

    def inject_sync(self, module: str, probe: str) -> None:
        """Synchronous probe site (storage paths, coproc device legs)."""
        if not self._enabled:
            return
        effect, disarm_after = self._claim(module, probe)
        if effect == EXCEPTION:
            raise ProbeTriggered(f"{module}.{probe}")
        if effect == TERMINATE:
            raise SystemExit(f"honey badger terminate: {module}.{probe}")
        if effect == DELAY:
            # deliberate BLOCKING sleep: a delay fault at a sync site must
            # actually delay (stalling the loop is the injected fault —
            # this only ever runs with the badger explicitly enabled)
            time.sleep(self.delay_ms / 1000)
        elif effect == WEDGE:
            # block like a hung device fetch until disarmed (or the cap):
            # this is what the engine's per-attempt deadlines must cut
            # through by abandoning the wedged worker
            deadline = time.monotonic() + self.wedge_max_s
            while time.monotonic() < deadline and self._wedged(module, probe):
                time.sleep(0.01)
            if disarm_after:
                self.unset(module, probe)


honey_badger = HoneyBadger()
