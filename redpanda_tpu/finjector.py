"""Failure-injection registry ("honey badger").

Parity with finjector/hbadger.h:23-60: subsystems register named probes;
tests (or the admin API) arm a probe on a module with one of three effects —
raise an exception, delay, or terminate (here: raise SystemExit, since we
have no per-shard process to kill). The reference compiles probes out of
release builds (hbadger.h:30-37); here arming is a no-op unless
``honey_badger.enable()`` was called, so production paths stay branch-cheap.

Per-RPC-method probes are generated alongside services (tools/rpcgen.py:
159-165 renders a failure_probes struct per service); rpc.service mirrors
that by registering ``<service>.<method>`` probes automatically.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict
from dataclasses import dataclass, field

EXCEPTION = "exception"
DELAY = "delay"
TERMINATE = "terminate"


class ProbeTriggered(Exception):
    """Raised by an armed 'exception' probe."""


@dataclass
class _Module:
    probes: set = field(default_factory=set)
    armed: dict = field(default_factory=dict)  # probe -> effect


class HoneyBadger:
    def __init__(self) -> None:
        self._enabled = False
        self._modules: dict[str, _Module] = defaultdict(_Module)
        self.delay_ms = 50

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False
        for m in self._modules.values():
            m.armed.clear()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def register_probe(self, module: str, *probes: str) -> None:
        self._modules[module].probes.update(probes)

    def modules(self) -> dict[str, list[str]]:
        return {name: sorted(m.probes) for name, m in self._modules.items()}

    def set_exception(self, module: str, probe: str) -> None:
        self._arm(module, probe, EXCEPTION)

    def set_delay(self, module: str, probe: str) -> None:
        self._arm(module, probe, DELAY)

    def set_termination(self, module: str, probe: str) -> None:
        self._arm(module, probe, TERMINATE)

    def unset(self, module: str, probe: str) -> None:
        self._modules[module].armed.pop(probe, None)

    def _arm(self, module: str, probe: str, effect: str) -> None:
        if not self._enabled:
            return
        self._modules[module].armed[probe] = effect

    async def maybe_inject(self, module: str, probe: str) -> None:
        """Await point placed at each probe site."""
        if not self._enabled:
            return
        effect = self._modules[module].armed.get(probe)
        if effect is None:
            return
        if effect == DELAY:
            await asyncio.sleep(self.delay_ms / 1000)
        elif effect == EXCEPTION:
            raise ProbeTriggered(f"{module}.{probe}")
        elif effect == TERMINATE:
            raise SystemExit(f"honey badger terminate: {module}.{probe}")

    def inject_sync(self, module: str, probe: str) -> None:
        """Synchronous probe site (storage paths)."""
        if not self._enabled:
            return
        effect = self._modules[module].armed.get(probe)
        if effect == EXCEPTION:
            raise ProbeTriggered(f"{module}.{probe}")
        if effect == TERMINATE:
            raise SystemExit(f"honey badger terminate: {module}.{probe}")
        if effect == DELAY:
            # deliberate BLOCKING sleep: a delay fault at a sync site must
            # actually delay (stalling the loop is the injected fault —
            # this only ever runs with the badger explicitly enabled)
            import time

            time.sleep(self.delay_ms / 1000)


honey_badger = HoneyBadger()
