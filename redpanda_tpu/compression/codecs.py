"""Individual codec implementations.

- gzip: stdlib zlib (parity with compression/internal/gzip_compressor).
- zstd: `zstandard` package with a per-process reusable compressor
  (parity with the per-core stream_zstd workspace, compression/stream_zstd.h).
- lz4: LZ4 *frame* format via ctypes on the system liblz4
  (parity with compression/internal/lz4_frame_compressor).
- snappy: xerial/java-framed snappy via ctypes on the system libsnappy
  (parity with compression/internal/snappy_java_compressor — Kafka's snappy
  framing is the xerial stream format).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import struct
import zlib

try:
    import zstandard
except ImportError:  # optional: zstd produce/fetch raises, everything else works
    zstandard = None

# ------------------------------------------------------------------ gzip

def gzip_compress(data: bytes) -> bytes:
    co = zlib.compressobj(wbits=31)  # gzip container
    return co.compress(data) + co.flush()


def gzip_uncompress(data: bytes) -> bytes:
    return zlib.decompress(data, wbits=47)  # auto gzip/zlib


# ------------------------------------------------------------------ zstd
_zc = None
_zd = None


def _zstd_ctx():
    global _zc, _zd
    if zstandard is None:
        raise RuntimeError("zstd codec unavailable: `zstandard` is not installed")
    if _zc is None:
        # per-process reusable contexts (parity with stream_zstd workspaces)
        _zc = zstandard.ZstdCompressor(level=3)
        _zd = zstandard.ZstdDecompressor()
    return _zc, _zd


def zstd_compress(data: bytes) -> bytes:
    zc, _ = _zstd_ctx()
    return zc.compress(data)


def zstd_uncompress(data: bytes) -> bytes:
    # Streaming loop: handles frames without a content-size header (the
    # form streaming producers emit) with no fixed output cap.
    _, zd = _zstd_ctx()
    dobj = zd.decompressobj()
    out = dobj.decompress(data)
    return out


# ------------------------------------------------------------------ lz4 frame
_LZ4F_VERSION = 100


def _load_lz4():
    path = ctypes.util.find_library("lz4") or "liblz4.so.1"
    lib = ctypes.CDLL(path)
    lib.LZ4F_compressFrameBound.restype = ctypes.c_size_t
    lib.LZ4F_compressFrameBound.argtypes = [ctypes.c_size_t, ctypes.c_void_p]
    lib.LZ4F_compressFrame.restype = ctypes.c_size_t
    lib.LZ4F_compressFrame.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
    ]
    lib.LZ4F_isError.restype = ctypes.c_uint
    lib.LZ4F_isError.argtypes = [ctypes.c_size_t]
    lib.LZ4F_createDecompressionContext.restype = ctypes.c_size_t
    lib.LZ4F_createDecompressionContext.argtypes = [ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint]
    lib.LZ4F_freeDecompressionContext.restype = ctypes.c_size_t
    lib.LZ4F_freeDecompressionContext.argtypes = [ctypes.c_void_p]
    lib.LZ4F_decompress.restype = ctypes.c_size_t
    lib.LZ4F_decompress.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_void_p,
    ]
    return lib


_lz4_lib = None


def _lz4_handle():
    global _lz4_lib
    if _lz4_lib is None:
        _lz4_lib = _load_lz4()
    return _lz4_lib


class _Lz4Proxy:
    def __getattr__(self, name):
        return getattr(_lz4_handle(), name)


_lz4 = _Lz4Proxy()


def lz4_compress(data: bytes) -> bytes:
    bound = _lz4.LZ4F_compressFrameBound(len(data), None)
    dst = ctypes.create_string_buffer(bound)
    n = _lz4.LZ4F_compressFrame(dst, bound, data, len(data), None)
    if _lz4.LZ4F_isError(n):
        raise RuntimeError("LZ4F_compressFrame failed")
    return dst.raw[:n]


def lz4_uncompress(data: bytes) -> bytes:
    ctx = ctypes.c_void_p()
    err = _lz4.LZ4F_createDecompressionContext(ctypes.byref(ctx), _LZ4F_VERSION)
    if _lz4.LZ4F_isError(err):
        raise RuntimeError("LZ4F context creation failed")
    try:
        out = bytearray()
        src = ctypes.create_string_buffer(bytes(data), len(data))
        src_off = 0
        chunk = ctypes.create_string_buffer(256 * 1024)
        while src_off < len(data):
            dst_size = ctypes.c_size_t(len(chunk))
            src_size = ctypes.c_size_t(len(data) - src_off)
            rc = _lz4.LZ4F_decompress(
                ctx,
                chunk, ctypes.byref(dst_size),
                ctypes.byref(src, src_off), ctypes.byref(src_size),
                None,
            )
            if _lz4.LZ4F_isError(rc):
                raise RuntimeError("LZ4F_decompress failed")
            out += chunk.raw[: dst_size.value]
            src_off += src_size.value
            if rc == 0 and src_size.value == 0:
                break
        return bytes(out)
    finally:
        _lz4.LZ4F_freeDecompressionContext(ctx)


# ------------------------------------------------------------------ snappy (xerial-framed)
def _load_snappy():
    path = ctypes.util.find_library("snappy") or "libsnappy.so.1"
    lib = ctypes.CDLL(path)
    lib.snappy_compress.restype = ctypes.c_int
    lib.snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.snappy_uncompress.restype = ctypes.c_int
    lib.snappy_uncompress.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.snappy_max_compressed_length.restype = ctypes.c_size_t
    lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
    lib.snappy_uncompressed_length.restype = ctypes.c_int
    lib.snappy_uncompressed_length.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t),
    ]
    return lib


_snappy_lib = None


def _snappy_handle():
    global _snappy_lib
    if _snappy_lib is None:
        _snappy_lib = _load_snappy()
    return _snappy_lib


class _SnappyProxy:
    def __getattr__(self, name):
        return getattr(_snappy_handle(), name)


_snappy = _SnappyProxy()

_XERIAL_MAGIC = b"\x82SNAPPY\x00"
_XERIAL_HEADER = _XERIAL_MAGIC + struct.pack(">ii", 1, 1)
_XERIAL_BLOCK = 32 * 1024


def _snappy_raw_compress(data: bytes) -> bytes:
    bound = _snappy.snappy_max_compressed_length(len(data))
    dst = ctypes.create_string_buffer(bound)
    n = ctypes.c_size_t(bound)
    rc = _snappy.snappy_compress(data, len(data), dst, ctypes.byref(n))
    if rc != 0:
        raise RuntimeError("snappy_compress failed")
    return dst.raw[: n.value]


def _snappy_raw_uncompress(data: bytes) -> bytes:
    buf = ctypes.create_string_buffer(bytes(data), len(data))
    n = ctypes.c_size_t()
    rc = _snappy.snappy_uncompressed_length(buf, len(data), ctypes.byref(n))
    if rc != 0:
        raise RuntimeError("snappy_uncompressed_length failed")
    dst = ctypes.create_string_buffer(n.value)
    out_n = ctypes.c_size_t(n.value)
    rc = _snappy.snappy_uncompress(buf, len(data), dst, ctypes.byref(out_n))
    if rc != 0:
        raise RuntimeError("snappy_uncompress failed")
    return dst.raw[: out_n.value]


def snappy_compress(data: bytes) -> bytes:
    out = bytearray(_XERIAL_HEADER)
    for i in range(0, max(len(data), 1), _XERIAL_BLOCK):
        block = data[i : i + _XERIAL_BLOCK]
        comp = _snappy_raw_compress(block)
        out += struct.pack(">i", len(comp)) + comp
    return bytes(out)


def snappy_uncompress(data: bytes) -> bytes:
    if data[: len(_XERIAL_MAGIC)] != _XERIAL_MAGIC:
        # raw snappy block (non-java producers)
        return _snappy_raw_uncompress(data)
    pos = len(_XERIAL_HEADER)
    out = bytearray()
    while pos < len(data):
        (blen,) = struct.unpack_from(">i", data, pos)
        pos += 4
        out += _snappy_raw_uncompress(data[pos : pos + blen])
        pos += blen
    return bytes(out)
