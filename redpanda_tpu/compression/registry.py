"""Codec registry (parity with compression/compression.h:21, compression.cc:18-54).

Static dispatch over ``models.record.Compression`` with a pluggable backend
boundary: the default ``host`` backend runs native codecs (zlib, zstd via the
zstandard package, lz4-frame and snappy via ctypes on the system libraries);
a ``tpu`` backend can be registered to route batch payload (de)compression
through the device bridge (the plugin seam the north star requires — the CPU
path stays intact).
"""

from __future__ import annotations

from typing import Callable

from redpanda_tpu.models.record import Compression
from redpanda_tpu.compression import codecs as _codecs


class CompressionError(Exception):
    pass


class _Backend:
    def __init__(self, name: str, table: dict[Compression, tuple[Callable, Callable]]):
        self.name = name
        self.table = table

    def compress(self, data: bytes, codec: Compression) -> bytes:
        if codec == Compression.none:
            return data
        try:
            fn = self.table[codec][0]
        except KeyError:
            raise CompressionError(f"codec {codec.name} unsupported by backend {self.name}")
        return fn(data)

    def uncompress(self, data: bytes, codec: Compression) -> bytes:
        if codec == Compression.none:
            return data
        try:
            fn = self.table[codec][1]
        except KeyError:
            raise CompressionError(f"codec {codec.name} unsupported by backend {self.name}")
        return fn(data)


_HOST = _Backend(
    "host",
    {
        Compression.gzip: (_codecs.gzip_compress, _codecs.gzip_uncompress),
        Compression.zstd: (_codecs.zstd_compress, _codecs.zstd_uncompress),
        Compression.lz4: (_codecs.lz4_compress, _codecs.lz4_uncompress),
        Compression.snappy: (_codecs.snappy_compress, _codecs.snappy_uncompress),
    },
)

_backends: dict[str, _Backend] = {"host": _HOST}
_active = _HOST


def register_backend(name: str, table: dict[Compression, tuple[Callable, Callable]], *, activate: bool = False):
    global _active
    backend = _Backend(name, table)
    _backends[name] = backend
    if activate:
        _active = backend
    return backend


def active_backend() -> str:
    return _active.name


def compress(data: bytes, codec: Compression | int) -> bytes:
    return _active.compress(bytes(data), Compression(codec))


def uncompress(data: bytes, codec: Compression | int) -> bytes:
    return _active.uncompress(bytes(data), Compression(codec))


def is_available(codec: Compression | int) -> bool:
    """Can the active backend actually run this codec in THIS process?

    gzip (stdlib zlib) is always available; zstd needs the `zstandard`
    package; lz4/snappy need the system libraries. Callers that merely
    prefer a codec (e.g. the coproc output recompressor) use this to fall
    back instead of failing per batch.
    """
    codec = Compression(codec)
    if codec == Compression.none:
        return True
    if codec not in _active.table:
        return False  # the active backend's table is authoritative
    if _active is not _HOST:
        return True  # plugin backends declare support via their table
    if codec == Compression.gzip:
        return True  # stdlib zlib
    if codec == Compression.zstd:
        return _codecs.zstandard is not None
    try:
        if codec == Compression.lz4:
            _codecs._lz4_handle()
        elif codec == Compression.snappy:
            _codecs._snappy_handle()
    except OSError:
        return False
    return True
