from redpanda_tpu.compression.registry import (
    compress,
    uncompress,
    register_backend,
    active_backend,
    is_available,
)

__all__ = [
    "compress",
    "uncompress",
    "register_backend",
    "active_backend",
    "is_available",
]
