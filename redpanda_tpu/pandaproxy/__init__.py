"""HTTP APIs: REST proxy + schema registry (src/v/pandaproxy parity).

Both are pure Kafka clients of the local broker (the reference's proxy is
an in-proc kafka::client user — pandaproxy/rest, schema_registry share
``pandaproxy::server``); here each is an owned-HTTP-server app over the embedded
``KafkaClient``.
"""

from redpanda_tpu.pandaproxy.rest import RestProxy
from redpanda_tpu.pandaproxy.schema_registry import SchemaRegistry

__all__ = ["RestProxy", "SchemaRegistry"]
