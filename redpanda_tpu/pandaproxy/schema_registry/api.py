"""Schema registry HTTP API.

Parity with pandaproxy/schema_registry (api-doc/schema_registry.json):
- POST /subjects/{subject}/versions          (register)
- POST /subjects/{subject}                   (lookup by schema)
- GET  /subjects                             · DELETE /subjects/{subject}
- GET  /subjects/{subject}/versions
- GET  /subjects/{subject}/versions/{v}      (v = number | "latest")
- GET  /schemas/ids/{id}
- GET/PUT /config · GET/PUT /config/{subject}
- POST /compatibility/subjects/{subject}/versions/{v}
Mutations append to the ``_schemas`` topic through a sequenced writer and
the store replays the log (seq_writer.h pattern) — restart-safe and
cluster-convergent.
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.http import web

from redpanda_tpu.kafka.client.client import KafkaClient
from redpanda_tpu.pandaproxy.schema_registry import avro_compat
from redpanda_tpu.pandaproxy.schema_registry.store import (
    SCHEMAS_TOPIC,
    IncompatibleSchema,
    SchemaStore,
)

logger = logging.getLogger("rptpu.schema_registry")

CT = "application/vnd.schemaregistry.v1+json"


class SchemaRegistry:
    def __init__(
        self,
        bootstrap: list[tuple[str, int]],
        host: str = "127.0.0.1",
        port: int = 8081,
        sasl: tuple[str, str] | None = None,
    ) -> None:
        self.bootstrap = bootstrap
        self.host = host
        self.port = port
        self.sasl = sasl
        self.client: KafkaClient | None = None
        self.store = SchemaStore()
        self._runner: web.AppRunner | None = None
        self._replayed = 0
        self._write_lock = asyncio.Lock()

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "SchemaRegistry":
        self.client = await KafkaClient(self.bootstrap, sasl=self.sasl).connect()
        try:
            await self.client.create_topic(SCHEMAS_TOPIC, partitions=1, configs={"cleanup.policy": "compact"})
        except Exception:
            pass  # exists
        await self._replay()
        app = web.Application()
        app.add_routes([
            web.get("/subjects", self._subjects),
            web.post("/subjects/{subject}", self._lookup),
            web.delete("/subjects/{subject}", self._delete_subject),
            web.get("/subjects/{subject}/versions", self._versions),
            web.post("/subjects/{subject}/versions", self._register),
            web.get("/subjects/{subject}/versions/{version}", self._get_version),
            web.get("/schemas/ids/{id}", self._by_id),
            web.get("/config", self._get_config),
            web.put("/config", self._put_config),
            web.get("/config/{subject}", self._get_config),
            web.put("/config/{subject}", self._put_config),
            web.post(
                "/compatibility/subjects/{subject}/versions/{version}", self._check_compat
            ),
        ])
        from redpanda_tpu.utils.http_server import start_site

        self._runner, self.port = await start_site(
            app, self.host, self.port, logger, "schema registry"
        )
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self.client is not None:
            await self.client.close()
            self.client = None

    # ------------------------------------------------------------ log io
    async def _replay(self) -> None:
        offset = self._replayed
        while True:
            batches, hwm = await self.client.fetch(
                SCHEMAS_TOPIC, 0, offset, max_wait_ms=0
            )
            if not batches:
                break
            for b in batches:
                for r in b.records():
                    if r.key is not None:
                        self.store.apply(r.key, r.value)
                offset = b.header.base_offset + b.header.record_count
        self._replayed = offset

    async def _append(self, records: list[tuple[bytes, bytes | None]]) -> None:
        if records:
            await self.client.produce(SCHEMAS_TOPIC, 0, records)
        await self._replay()

    # ------------------------------------------------------------ handlers
    def _err(self, status: int, code: int, message: str) -> web.Response:
        return web.json_response(
            {"error_code": code, "message": message}, status=status, content_type=CT
        )

    async def _subjects(self, req: web.Request) -> web.Response:
        await self._replay()
        subs = sorted(s for s in self.store.subjects if self.store.live_versions(s))
        return web.json_response(subs, content_type=CT)

    @staticmethod
    def _schema_text(body: dict) -> str | None:
        """Accept both a JSON-string schema and an inline JSON object."""
        schema = body.get("schema")
        if isinstance(schema, (dict, list)):
            import json

            return json.dumps(schema)
        return schema or None

    async def _register(self, req: web.Request) -> web.Response:
        subject = req.match_info["subject"]
        body = await req.json()
        schema = self._schema_text(body)
        if not schema:
            return self._err(422, 42201, "schema field required")
        if body.get("schemaType", "AVRO") != "AVRO":
            return self._err(422, 42204, "only AVRO schemas supported")
        # seq_writer semantics: append, re-replay, and verify OUR schema owns
        # the version we claimed — a concurrent registry instance may have
        # won the offset race, in which case we retry against the new state.
        for _ in range(5):
            async with self._write_lock:
                await self._replay()
                try:
                    records, schema_id = self.store.register_records(subject, schema)
                except IncompatibleSchema as e:
                    return self._err(409, 409, str(e))
                except avro_compat.SchemaParseError as e:
                    return self._err(422, 42201, f"invalid avro schema: {e}")
                await self._append(records)
                winner = self.store.find_schema(subject, schema)
                if winner is not None:
                    return web.json_response({"id": winner.schema_id}, content_type=CT)
            await asyncio.sleep(0.01)
        return self._err(500, 50001, "write conflict; retry")

    async def _lookup(self, req: web.Request) -> web.Response:
        subject = req.match_info["subject"]
        body = await req.json()
        await self._replay()
        v = self.store.find_schema(subject, self._schema_text(body) or "")
        if v is None:
            return self._err(404, 40403, "schema not found")
        return web.json_response(
            {"subject": subject, "version": v.version, "id": v.schema_id, "schema": v.schema},
            content_type=CT,
        )

    async def _delete_subject(self, req: web.Request) -> web.Response:
        subject = req.match_info["subject"]
        async with self._write_lock:
            await self._replay()
            versions = [v.version for v in self.store.live_versions(subject)]
            if not versions:
                return self._err(404, 40401, f"subject not found: {subject}")
            await self._append(self.store.delete_subject_records(subject))
        return web.json_response(versions, content_type=CT)

    async def _versions(self, req: web.Request) -> web.Response:
        subject = req.match_info["subject"]
        await self._replay()
        live = self.store.live_versions(subject)
        if not live:
            return self._err(404, 40401, f"subject not found: {subject}")
        return web.json_response([v.version for v in live], content_type=CT)

    def _resolve_version(self, subject: str, version: str):
        live = self.store.live_versions(subject)
        if not live:
            return None
        if version == "latest":
            return live[-1]
        try:
            n = int(version)
        except ValueError:
            return None
        return next((v for v in live if v.version == n), None)

    async def _get_version(self, req: web.Request) -> web.Response:
        await self._replay()
        v = self._resolve_version(req.match_info["subject"], req.match_info["version"])
        if v is None:
            return self._err(404, 40402, "version not found")
        return web.json_response(
            {"subject": v.subject, "version": v.version, "id": v.schema_id, "schema": v.schema},
            content_type=CT,
        )

    async def _by_id(self, req: web.Request) -> web.Response:
        await self._replay()
        try:
            schema_id = int(req.match_info["id"])
        except ValueError:
            return self._err(404, 40403, "schema id must be an integer")
        schema = self.store.by_id.get(schema_id)
        if schema is None:
            return self._err(404, 40403, "schema not found")
        return web.json_response({"schema": schema}, content_type=CT)

    async def _get_config(self, req: web.Request) -> web.Response:
        await self._replay()
        subject = req.match_info.get("subject")
        if subject:
            level = self.store.compatibility_of(subject)
        else:
            level = self.store.global_compatibility
        return web.json_response({"compatibilityLevel": level}, content_type=CT)

    async def _put_config(self, req: web.Request) -> web.Response:
        body = await req.json()
        level = body.get("compatibility", "").upper()
        if level not in avro_compat.LEVELS:
            return self._err(422, 42203, f"invalid compatibility level: {level}")
        subject = req.match_info.get("subject")
        async with self._write_lock:
            key, value = self.store.config_record(subject, level)
            await self._append([(key, value)])
        return web.json_response({"compatibility": level}, content_type=CT)

    async def _check_compat(self, req: web.Request) -> web.Response:
        subject = req.match_info["subject"]
        body = await req.json()
        await self._replay()
        try:
            new = avro_compat.parse(self._schema_text(body) or "")
        except avro_compat.SchemaParseError as e:
            return self._err(422, 42201, str(e))
        version = req.match_info["version"]
        if version == "latest":
            live = self.store.live_versions(subject)
            if not live:
                return self._err(404, 40401, f"subject not found: {subject}")
            olds = [avro_compat.parse(v.schema) for v in live]
        else:
            v = self._resolve_version(subject, version)
            if v is None:
                return self._err(404, 40402, "version not found")
            olds = [avro_compat.parse(v.schema)]
        level = self.store.compatibility_of(subject)
        ok = avro_compat.compatible(new, olds, level)
        return web.json_response({"is_compatible": ok}, content_type=CT)
