"""Avro schema parsing + compatibility checking.

Parity with pandaproxy/schema_registry/avro.h + schema_util: the registry
(2021 snapshot) supports Avro schemas with the standard compatibility
levels. This implements the Avro spec's schema-resolution subset the
registry needs:

- canonical parse of {primitive, record, enum, array, map, union, fixed}
- reader/writer compatibility: name match for named types, field-by-field
  record rules (missing writer field needs a reader default; extra writer
  fields ignored), enum symbol subset, union member resolution, and the
  numeric promotion chain int → long → float → double (+ string↔bytes).

Levels: BACKWARD (new reads old), FORWARD (old reads new), FULL (both),
NONE, and the *_TRANSITIVE variants checked against all prior versions.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}
_PROMOTIONS = {
    "int": {"long", "float", "double"},
    "long": {"float", "double"},
    "float": {"double"},
    "string": {"bytes"},
    "bytes": {"string"},
}


class SchemaParseError(ValueError):
    pass


@dataclass
class AvroSchema:
    type: str
    name: str | None = None
    fields: list[dict] = field(default_factory=list)  # record: {name, schema, has_default}
    symbols: list[str] = field(default_factory=list)  # enum
    items: "AvroSchema | None" = None  # array
    values: "AvroSchema | None" = None  # map
    branches: list["AvroSchema"] = field(default_factory=list)  # union
    size: int = 0  # fixed


def parse(schema_json: str | dict | list) -> AvroSchema:
    if isinstance(schema_json, str):
        try:
            schema_json = json.loads(schema_json)
        except json.JSONDecodeError:
            # bare primitive like `"string"` already decoded by caller? no:
            # a raw primitive name without quotes is invalid JSON
            raise SchemaParseError("schema is not valid JSON")
    return _parse(schema_json, names={})


def _parse(node, names: dict) -> AvroSchema:
    if isinstance(node, str):
        if node in PRIMITIVES:
            return AvroSchema(node)
        if node in names:
            return names[node]
        raise SchemaParseError(f"unknown type reference: {node}")
    if isinstance(node, list):
        return AvroSchema("union", branches=[_parse(b, names) for b in node])
    if not isinstance(node, dict) or "type" not in node:
        raise SchemaParseError(f"malformed schema node: {node!r}")
    t = node["type"]
    if t in PRIMITIVES:
        return AvroSchema(t)
    if t == "record" or t == "error":
        name = node.get("name")
        if not name:
            raise SchemaParseError("record needs a name")
        rec = AvroSchema("record", name=name)
        names[name] = rec
        for f in node.get("fields", []):
            if "name" not in f or "type" not in f:
                raise SchemaParseError(f"malformed field: {f!r}")
            rec.fields.append({
                "name": f["name"],
                "schema": _parse(f["type"], names),
                "has_default": "default" in f,
            })
        return rec
    if t == "enum":
        if not node.get("name"):
            raise SchemaParseError("enum needs a name")
        return AvroSchema("enum", name=node["name"], symbols=list(node.get("symbols", [])))
    if t == "array":
        return AvroSchema("array", items=_parse(node["items"], names))
    if t == "map":
        return AvroSchema("map", values=_parse(node["values"], names))
    if t == "fixed":
        if not node.get("name"):
            raise SchemaParseError("fixed needs a name")
        return AvroSchema("fixed", name=node["name"], size=int(node.get("size", 0)))
    # {"type": [...]} union wrapper or nested named reference
    if isinstance(t, (list, dict)):
        return _parse(t, names)
    raise SchemaParseError(f"unknown type: {t}")


def reader_can_read(reader: AvroSchema, writer: AvroSchema, _seen=None) -> bool:
    """Avro schema-resolution rules: can data written with `writer` be read
    with `reader`?"""
    if _seen is None:
        _seen = set()
    key = (id(reader), id(writer))
    if key in _seen:
        return True  # recursive types: assume ok at the cycle point
    _seen.add(key)

    # union handling first (spec: resolve unions before other rules)
    if writer.type == "union":
        return all(reader_can_read(reader, b, _seen) for b in writer.branches)
    if reader.type == "union":
        return any(reader_can_read(b, writer, _seen) for b in reader.branches)

    if reader.type in PRIMITIVES or writer.type in PRIMITIVES:
        if reader.type == writer.type:
            return True
        return reader.type in _PROMOTIONS.get(writer.type, set())

    if reader.type != writer.type:
        return False
    if reader.type == "record":
        if reader.name != writer.name:
            return False
        writer_fields = {f["name"]: f for f in writer.fields}
        for rf in reader.fields:
            wf = writer_fields.get(rf["name"])
            if wf is None:
                if not rf["has_default"]:
                    return False  # reader field absent in writer, no default
            elif not reader_can_read(rf["schema"], wf["schema"], _seen):
                return False
        return True
    if reader.type == "enum":
        return reader.name == writer.name and set(writer.symbols) <= set(reader.symbols)
    if reader.type == "array":
        return reader_can_read(reader.items, writer.items, _seen)
    if reader.type == "map":
        return reader_can_read(reader.values, writer.values, _seen)
    if reader.type == "fixed":
        return reader.name == writer.name and reader.size == writer.size
    return False


LEVELS = {
    "NONE", "BACKWARD", "FORWARD", "FULL",
    "BACKWARD_TRANSITIVE", "FORWARD_TRANSITIVE", "FULL_TRANSITIVE",
}


def compatible(new: AvroSchema, olds: list[AvroSchema], level: str) -> bool:
    """Check `new` against prior versions under the given level. `olds` is
    ordered oldest→newest; non-transitive levels check only the latest."""
    if level == "NONE" or not olds:
        return True
    check = olds if level.endswith("_TRANSITIVE") else olds[-1:]
    base = level.replace("_TRANSITIVE", "")
    for old in check:
        if base in ("BACKWARD", "FULL") and not reader_can_read(new, old):
            return False
        if base in ("FORWARD", "FULL") and not reader_can_read(old, new):
            return False
    return True
