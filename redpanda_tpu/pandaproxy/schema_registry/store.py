"""Schema storage in the ``_schemas`` topic.

Parity with pandaproxy/schema_registry seq_writer.h + sharded_store.h: every
mutation is a record appended to a single-partition replicated topic
(key = {keytype, subject, version}, value = the schema envelope), and the
in-memory store is rebuilt by replaying that log — so registry state
survives restarts and, in a cluster, every proxy instance converges by
reading the same topic.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field

from redpanda_tpu.pandaproxy.schema_registry import avro_compat

logger = logging.getLogger("rptpu.schema_registry")

SCHEMAS_TOPIC = "_schemas"
DEFAULT_COMPAT = "BACKWARD"


@dataclass
class SchemaVersion:
    subject: str
    version: int
    schema_id: int
    schema: str  # canonical JSON text
    deleted: bool = False


@dataclass
class SubjectState:
    versions: list[SchemaVersion] = field(default_factory=list)
    compatibility: str | None = None


class SchemaStore:
    """In-memory state + the log-replay apply function."""

    def __init__(self) -> None:
        self.subjects: dict[str, SubjectState] = {}
        self.by_id: dict[int, str] = {}
        self.global_compatibility = DEFAULT_COMPAT
        self.next_id = 1

    # ------------------------------------------------------------ apply (log replay)
    def apply(self, key: bytes, value: bytes | None) -> None:
        try:
            k = json.loads(key.decode())
        except Exception:
            return
        kt = k.get("keytype")
        if kt == "SCHEMA":
            if value is None:
                # tombstone: hard-delete the version
                st = self.subjects.get(k["subject"])
                if st:
                    st.versions = [v for v in st.versions if v.version != k["version"]]
                return
            v = json.loads(value.decode())
            sv = SchemaVersion(
                v["subject"], v["version"], v["id"], v["schema"], v.get("deleted", False)
            )
            st = self.subjects.setdefault(sv.subject, SubjectState())
            st.versions = [x for x in st.versions if x.version != sv.version]
            st.versions.append(sv)
            st.versions.sort(key=lambda x: x.version)
            self.by_id[sv.schema_id] = sv.schema
            self.next_id = max(self.next_id, sv.schema_id + 1)
        elif kt == "CONFIG":
            if value is None:
                return
            v = json.loads(value.decode())
            if k.get("subject"):
                self.subjects.setdefault(k["subject"], SubjectState()).compatibility = v[
                    "compatibilityLevel"
                ]
            else:
                self.global_compatibility = v["compatibilityLevel"]

    # ------------------------------------------------------------ queries
    def live_versions(self, subject: str) -> list[SchemaVersion]:
        st = self.subjects.get(subject)
        return [v for v in st.versions if not v.deleted] if st else []

    def all_versions(self, subject: str) -> list[SchemaVersion]:
        """Every version including soft-deleted ones (version numbers are
        allocated over this list so they are never reused)."""
        st = self.subjects.get(subject)
        return list(st.versions) if st else []

    def compatibility_of(self, subject: str) -> str:
        st = self.subjects.get(subject)
        return (st.compatibility if st and st.compatibility else None) or self.global_compatibility

    def find_schema(self, subject: str, schema: str) -> SchemaVersion | None:
        canon = _canonical(schema)
        for v in self.live_versions(subject):
            if _canonical(v.schema) == canon:
                return v
        return None

    # ------------------------------------------------------------ mutations (return records)
    def register_records(self, subject: str, schema: str) -> tuple[list[tuple[bytes, bytes | None]], int]:
        """Validates + builds the records to append; returns (records, id).
        Raises on incompatibility / parse errors."""
        parsed = avro_compat.parse(schema)
        existing = self.find_schema(subject, schema)
        if existing is not None:
            return [], existing.schema_id
        olds = [avro_compat.parse(v.schema) for v in self.live_versions(subject)]
        level = self.compatibility_of(subject)
        if not avro_compat.compatible(parsed, olds, level):
            raise IncompatibleSchema(
                f"schema is not {level}-compatible with subject {subject}"
            )
        # Version numbers are never reused (Confluent semantics): compute
        # from ALL versions including soft-deleted ones, else a re-register
        # after soft-deleting the latest would overwrite its tombstoned
        # SCHEMA record key.
        all_versions = self.all_versions(subject)
        version = (max(v.version for v in all_versions) + 1) if all_versions else 1
        schema_id = self.next_id
        key = json.dumps(
            {"keytype": "SCHEMA", "subject": subject, "version": version},
            separators=(",", ":"),
        ).encode()
        value = json.dumps(
            {"subject": subject, "version": version, "id": schema_id,
             "schema": schema, "deleted": False},
            separators=(",", ":"),
        ).encode()
        return [(key, value)], schema_id

    def delete_subject_records(self, subject: str) -> list[tuple[bytes, bytes | None]]:
        out = []
        for v in self.live_versions(subject):
            key = json.dumps(
                {"keytype": "SCHEMA", "subject": subject, "version": v.version},
                separators=(",", ":"),
            ).encode()
            value = json.dumps(
                {"subject": subject, "version": v.version, "id": v.schema_id,
                 "schema": v.schema, "deleted": True},
                separators=(",", ":"),
            ).encode()
            out.append((key, value))
        return out

    def config_record(self, subject: str | None, level: str) -> tuple[bytes, bytes]:
        key = json.dumps(
            {"keytype": "CONFIG", "subject": subject}, separators=(",", ":")
        ).encode()
        value = json.dumps({"compatibilityLevel": level}, separators=(",", ":")).encode()
        return key, value


class IncompatibleSchema(ValueError):
    pass


def _canonical(schema) -> str:
    try:
        if not isinstance(schema, str):
            return json.dumps(schema, sort_keys=True, separators=(",", ":"))
        return json.dumps(json.loads(schema), sort_keys=True, separators=(",", ":"))
    except (json.JSONDecodeError, TypeError):
        return schema if isinstance(schema, str) else repr(schema)
