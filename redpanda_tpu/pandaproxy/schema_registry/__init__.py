"""Schema registry (pandaproxy/schema_registry parity)."""

from redpanda_tpu.pandaproxy.schema_registry.api import SchemaRegistry

__all__ = ["SchemaRegistry"]
