"""Kafka REST proxy.

Parity with pandaproxy/rest (api/api-doc/rest.json:1-468):
- GET  /brokers
- GET  /topics                      · GET /topics/{topic}
- POST /topics/{topic}              (produce; records may carry partition)
- GET  /topics/{topic}/partitions
- POST /consumers/{group}                          (create instance)
- DELETE /consumers/{group}/instances/{name}
- POST /consumers/{group}/instances/{name}/subscription
- GET  /consumers/{group}/instances/{name}/records
- POST /consumers/{group}/instances/{name}/offsets
Payload format: the Kafka REST v2 JSON embedded format (base64 for binary
keys/values, like the reference's json/requests parsing).
"""

from __future__ import annotations

import base64
import logging
import uuid

from redpanda_tpu.http import web

from redpanda_tpu.kafka.client.client import KafkaClient
from redpanda_tpu.kafka.client.consumer import GroupConsumer
from redpanda_tpu.kafka.protocol.errors import KafkaError

logger = logging.getLogger("rptpu.pandaproxy")

JSON_V2 = "application/vnd.kafka.json.v2+json"
BINARY_V2 = "application/vnd.kafka.binary.v2+json"


class EmbeddedFormatError(ValueError):
    pass


def _decode_value(v, json_format: bool) -> bytes | None:
    """Embedded-format value. The CONTENT TYPE picks the codec (like the
    reference's vnd.kafka.{json,binary}.v2 handling): json format stores the
    JSON literal; binary format requires base64 strings — guessing from the
    value shape would corrupt strings that happen to parse as base64."""
    import json

    if v is None:
        return None
    if json_format:
        return json.dumps(v, separators=(",", ":")).encode()
    if not isinstance(v, str):
        raise EmbeddedFormatError("binary format requires base64 string values")
    try:
        return base64.b64decode(v, validate=True)
    except Exception as e:
        raise EmbeddedFormatError(f"invalid base64: {e}") from e


def _encode_value(v: bytes | None):
    return None if v is None else base64.b64encode(v).decode()


class RestProxy:
    def __init__(
        self,
        bootstrap: list[tuple[str, int]],
        host: str = "127.0.0.1",
        port: int = 8082,
        sasl: tuple[str, str] | None = None,
    ) -> None:
        self.bootstrap = bootstrap
        self.host = host
        self.port = port
        self.sasl = sasl
        self.client: KafkaClient | None = None
        self._consumers: dict[tuple[str, str], GroupConsumer] = {}
        self._runner: web.AppRunner | None = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "RestProxy":
        self.client = await KafkaClient(self.bootstrap, sasl=self.sasl).connect()
        app = web.Application()
        app.add_routes([
            web.get("/brokers", self._brokers),
            web.get("/topics", self._topics),
            web.get("/topics/{topic}", self._topic),
            web.post("/topics/{topic}", self._produce),
            web.get("/topics/{topic}/partitions", self._partitions),
            web.post("/consumers/{group}", self._create_consumer),
            web.delete("/consumers/{group}/instances/{name}", self._delete_consumer),
            web.post("/consumers/{group}/instances/{name}/subscription", self._subscribe),
            web.get("/consumers/{group}/instances/{name}/records", self._records),
            web.post("/consumers/{group}/instances/{name}/offsets", self._commit),
        ])
        from redpanda_tpu.utils.http_server import start_site

        self._runner, self.port = await start_site(
            app, self.host, self.port, logger, "rest proxy"
        )
        return self

    async def stop(self) -> None:
        for consumer in self._consumers.values():
            try:
                await consumer.leave()
            except Exception:
                pass
        self._consumers.clear()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self.client is not None:
            await self.client.close()
            self.client = None

    # ------------------------------------------------------------ metadata
    async def _brokers(self, req: web.Request) -> web.Response:
        md = await self.client.refresh_metadata()
        return web.json_response({"brokers": [b["node_id"] for b in md["brokers"]]})

    async def _topics(self, req: web.Request) -> web.Response:
        md = await self.client.refresh_metadata()
        return web.json_response(
            sorted(t["name"] for t in md["topics"] if t["error_code"] == 0)
        )

    async def _topic_payload(self, name: str) -> dict | None:
        # pure lookup: must not auto-create (the reference proxy's metadata
        # queries pass allow_auto_topic_creation=false)
        md = await self.client.refresh_metadata([name], auto_create=False)
        t = next((t for t in md["topics"] if t["name"] == name), None)
        if t is None or t["error_code"] != 0:
            return None
        return {
            "name": name,
            "partitions": [
                {
                    "partition": p["partition_index"],
                    "leader": p["leader_id"],
                    "replicas": [
                        {"broker": r, "leader": r == p["leader_id"], "in_sync": True}
                        for r in p["replica_nodes"]
                    ],
                }
                for p in t.get("partitions") or []
            ],
        }

    async def _topic(self, req: web.Request) -> web.Response:
        payload = await self._topic_payload(req.match_info["topic"])
        if payload is None:
            return web.json_response(
                {"error_code": 40401, "message": "topic not found"}, status=404
            )
        return web.json_response(payload)

    async def _partitions(self, req: web.Request) -> web.Response:
        payload = await self._topic_payload(req.match_info["topic"])
        if payload is None:
            return web.json_response(
                {"error_code": 40401, "message": "topic not found"}, status=404
            )
        return web.json_response(payload["partitions"])

    # ------------------------------------------------------------ produce
    async def _produce(self, req: web.Request) -> web.Response:
        topic = req.match_info["topic"]
        json_format = "json.v2" in (req.content_type or "")
        body = await req.json()
        records = body.get("records", [])
        # one produce per partition, not per record (produce_batcher shape)
        by_partition: dict[int, list[tuple[int, tuple]]] = {}
        try:
            for i, rec in enumerate(records):
                partition = rec.get("partition", 0)
                kv = (
                    _decode_value(rec.get("key"), json_format),
                    _decode_value(rec.get("value"), json_format),
                )
                by_partition.setdefault(partition, []).append((i, kv))
        except EmbeddedFormatError as e:
            return web.json_response(
                {"error_code": 42201, "message": str(e)}, status=422
            )
        results: dict[int, dict] = {}
        for partition, entries in by_partition.items():
            try:
                base = await self.client.produce(
                    topic, partition, [kv for _, kv in entries]
                )
                for j, (i, _) in enumerate(entries):
                    results[i] = {"partition": partition, "offset": base + j, "error_code": None}
            except KafkaError as e:
                for i, _ in entries:
                    results[i] = {
                        "partition": partition, "offset": -1,
                        "error_code": int(e.code), "error": str(e),
                    }
        return web.json_response({"offsets": [results[i] for i in range(len(records))]})

    # ------------------------------------------------------------ consumers
    def _instance(self, req: web.Request) -> GroupConsumer | None:
        return self._consumers.get(
            (req.match_info["group"], req.match_info["name"])
        )

    async def _create_consumer(self, req: web.Request) -> web.Response:
        group = req.match_info["group"]
        body = await req.json() if req.can_read_body else {}
        name = body.get("name") or f"rest-{uuid.uuid4().hex[:12]}"
        if (group, name) in self._consumers:
            return web.json_response(
                {"error_code": 40902, "message": "consumer instance exists"}, status=409
            )
        consumer = GroupConsumer(self.client, group, topics=[])
        self._consumers[(group, name)] = consumer
        return web.json_response({
            "instance_id": name,
            "base_uri": f"http://{self.host}:{self.port}/consumers/{group}/instances/{name}",
        })

    async def _delete_consumer(self, req: web.Request) -> web.Response:
        consumer = self._consumers.pop(
            (req.match_info["group"], req.match_info["name"]), None
        )
        if consumer is None:
            return web.json_response(
                {"error_code": 40403, "message": "unknown instance"}, status=404
            )
        await consumer.leave()
        return web.Response(status=204)

    async def _subscribe(self, req: web.Request) -> web.Response:
        consumer = self._instance(req)
        if consumer is None:
            return web.json_response(
                {"error_code": 40403, "message": "unknown instance"}, status=404
            )
        body = await req.json()
        consumer.topics = list(body.get("topics", []))
        await consumer.join()
        return web.Response(status=204)

    async def _records(self, req: web.Request) -> web.Response:
        consumer = self._instance(req)
        if consumer is None:
            return web.json_response(
                {"error_code": 40403, "message": "unknown instance"}, status=404
            )
        got = await consumer.poll()
        out = []
        for (topic, partition), recs in sorted(got.items()):
            for off, r in recs:
                out.append({
                    "topic": topic,
                    "partition": partition,
                    "offset": off,
                    "key": _encode_value(r.key),
                    "value": _encode_value(r.value),
                })
        return web.json_response(out, content_type="application/json")

    async def _commit(self, req: web.Request) -> web.Response:
        consumer = self._instance(req)
        if consumer is None:
            return web.json_response(
                {"error_code": 40403, "message": "unknown instance"}, status=404
            )
        await consumer.commit()
        return web.Response(status=204)
