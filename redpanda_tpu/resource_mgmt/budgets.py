"""The budget plane: per-subsystem byte accounts + the pressure signal.

Parity with resource_mgmt/memory_groups.h: the reference statically divides
each shard's memory between subsystems so no single workload can balloon the
heap, and its autotune posture targets ~90% utilization with predictable
latency. Here one ``BudgetPlane`` per process carves a configurable total
into named ``MemoryAccount``s:

- ``kafka_produce`` — bytes of produce record payloads in flight between
  admission and the replicate ack (kafka/server/handlers.py).
- ``rpc``           — inbound internal-rpc request bodies between dispatch
  admission and response write (rpc/server.py InflightGate).
- ``coproc``        — staged transform rows held from ``submit_group``
  admission until the ticket harvests (coproc/engine.py), plus the column
  cache rides the same account's pressure signal.
- ``storage``       — append buffers inflight through ``DiskLog.append``.
- ``raft``          — replicate-batcher entries between submit and flush.

Two acquisition disciplines on ONE account type:

- ``try_acquire``/``release`` — synchronous, non-blocking, thread-safe: the
  ADMISSION users (kafka produce, coproc submit, rpc dispatch) shed with a
  retriable backpressure error instead of queueing, so exhaustion is a
  judged, counted event — never silent queue growth, never after-ack loss.
- ``async acquire``/``release`` — FIFO-waiting (the MemoryBudget
  semantics): the BUDGET users (storage append, raft batcher) sit *behind*
  an admission gate, so waiting is bounded backpressure, not unbounded
  queueing. Waiters are granted on the loop thread only.

``MemoryPressure`` (ok/warn/critical) derives from the worst account's
occupancy and is recomputed on every acquire/release; level CHANGES fire
registered listeners synchronously (the coproc engine trims its arena
free-list and column cache on critical). The exit threshold sits 5 points
below the entry threshold so occupancy oscillating on a boundary cannot
flap listeners.

Gauges: ``resource_account_held_bytes{account=}``, ``..._limit_bytes``,
``..._peak_bytes`` and ``resource_pressure_state`` (0 ok / 1 warn /
2 critical) — the occupancy inputs the governor's admission autotune and
the loadgen overload gate both judge.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import weakref
from collections import deque

from redpanda_tpu.metrics import registry

logger = logging.getLogger("rptpu.resources")

PRESSURE_OK = "ok"
PRESSURE_WARN = "warn"
PRESSURE_CRITICAL = "critical"
PRESSURE_LEVELS = (PRESSURE_OK, PRESSURE_WARN, PRESSURE_CRITICAL)
PRESSURE_NUM = {PRESSURE_OK: 0.0, PRESSURE_WARN: 1.0, PRESSURE_CRITICAL: 2.0}

# the memory_groups.h-style split of the plane total (fractions sum to 1)
DEFAULT_SPLIT: dict[str, float] = {
    "kafka_produce": 0.25,
    "rpc": 0.125,
    "coproc": 0.25,
    "storage": 0.25,
    "raft": 0.125,
}

# how far below the entry threshold occupancy must fall before the level
# steps back down (flap guard for workloads oscillating on a boundary)
_EXIT_MARGIN = 0.05


class MemoryAccount:
    """One subsystem's byte account.

    ``try_acquire`` is the admission path: non-blocking, thread-safe, and a
    request larger than the whole account is CLAMPED to the limit (it may
    proceed alone rather than being unservable forever — the reference's
    semaphore-units posture for oversized requests). Callers must release
    the value ``try_acquire`` returned, not the value they asked for.

    ``acquire`` is the waiting path (storage/raft budget users): FIFO
    waiters granted synchronously by ``release`` on the loop thread, the
    proven MemoryBudget discipline. Accounts whose releases happen on
    engine/executor threads must use only the try_acquire discipline —
    granting an asyncio future from a foreign thread is not safe, and the
    plane keeps the two user populations disjoint by construction.
    """

    def __init__(self, name: str, limit_bytes: int, plane: "BudgetPlane | None" = None):
        self.name = name
        self.limit = max(1, int(limit_bytes))
        self._held = 0
        self._peak = 0
        self._lock = threading.Lock()
        self._plane = plane
        self._waiters: deque[tuple[int, asyncio.Future]] = deque()

    # ------------------------------------------------------------ admission
    def try_acquire(self, n: int) -> int:
        """Reserve up to ``n`` bytes without blocking. Returns the amount
        actually reserved (clamped to the limit; 0 means REFUSED — the
        caller sheds). Zero/negative requests reserve nothing and admit."""
        if n <= 0:
            return 0
        n = min(int(n), self.limit)
        with self._lock:
            if self._held + n > self.limit:
                return 0
            self._held += n
            if self._held > self._peak:
                self._peak = self._held
        self._pressure_changed()
        return n

    # ------------------------------------------------------------ waiting
    async def acquire(self, n: int) -> int:
        """Reserve ``n`` bytes (clamped to the limit), waiting FIFO until
        available. Loop-thread only (see class docstring)."""
        if n <= 0:
            return 0
        n = min(int(n), self.limit)
        granted = False
        with self._lock:
            if self._held + n <= self.limit and not self._waiters:
                self._held += n
                if self._held > self._peak:
                    self._peak = self._held
                granted = True
        if granted:
            self._pressure_changed()
            return n
        fut = asyncio.get_running_loop().create_future()
        with self._lock:
            self._waiters.append((n, fut))
        try:
            await fut  # resolved by _drain with the bytes already deducted
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                self.release(n)  # grant landed before cancellation
            else:
                with self._lock:
                    try:
                        self._waiters.remove((n, fut))
                    except ValueError:
                        pass
                self._drain()
            raise
        self._pressure_changed()
        return n

    def release(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._held = max(0, self._held - int(n))
        self._drain()
        self._pressure_changed()

    def _drain(self) -> None:
        """Grant parked FIFO waiters (same liveness rules as MemoryBudget:
        cancelled/dead-loop heads are skipped, never block live waiters)."""
        to_grant = []
        with self._lock:
            while self._waiters:
                n, fut = self._waiters[0]
                if fut.cancelled():
                    self._waiters.popleft()
                    continue
                try:
                    dead = fut.get_loop().is_closed()
                except RuntimeError:
                    dead = True
                if dead:
                    self._waiters.popleft()
                    continue
                if self._held + n > self.limit:
                    break
                self._waiters.popleft()
                self._held += n
                if self._held > self._peak:
                    self._peak = self._held
                to_grant.append(fut)
        for fut in to_grant:
            if not fut.done():
                fut.set_result(None)

    def _pressure_changed(self) -> None:
        if self._plane is not None:
            self._plane._recompute_pressure(self)

    # ------------------------------------------------------------ views
    @property
    def held(self) -> int:
        return self._held

    @property
    def peak(self) -> int:
        return self._peak

    def occupancy(self) -> float:
        with self._lock:
            return self._held / self.limit

    def reset_peak(self) -> None:
        with self._lock:
            self._peak = self._held

    def snapshot(self) -> dict:
        with self._lock:
            held, peak = self._held, self._peak
        return {
            "limit_bytes": self.limit,
            "held_bytes": held,
            "peak_bytes": peak,
            "occupancy": round(held / self.limit, 4),
            "waiters": len(self._waiters),
        }


class BudgetPlane:
    """The process budget split into named accounts + the pressure signal."""

    def __init__(
        self,
        total_bytes: int = 512 * 1024 * 1024,
        split: dict[str, float] | None = None,
        *,
        warn_pct: float = 0.75,
        critical_pct: float = 0.90,
        register_gauges: bool = False,
    ) -> None:
        self.total_bytes = max(1, int(total_bytes))
        split = dict(split or DEFAULT_SPLIT)
        self.warn_pct = float(warn_pct)
        self.critical_pct = float(critical_pct)
        # leakwatch (coproc/leakwatch.py): with coproc_leakwatch on, each
        # account is handed out through a balance-recording proxy; when
        # off, wrap() returns the raw account — zero steady-state cost.
        # Deferred import: resource_mgmt must not pull coproc eagerly.
        from redpanda_tpu.coproc import leakwatch

        self.accounts: dict[str, MemoryAccount] = {
            name: leakwatch.wrap(
                MemoryAccount(
                    name, max(1, int(self.total_bytes * frac)), plane=self
                ),
                f"account.{name}",
            )
            for name, frac in split.items()
        }
        self._level = PRESSURE_OK
        self._level_lock = threading.Lock()
        self._listeners: list = []
        if register_gauges:
            self.register_gauges()

    def account(self, name: str) -> MemoryAccount:
        return self.accounts[name]

    # ------------------------------------------------------------ pressure
    def pressure(self) -> str:
        with self._level_lock:
            return self._level

    def max_occupancy(self) -> tuple[str, float]:
        """(account name, occupancy) of the fullest account."""
        worst_name, worst = "", 0.0
        for name, acct in self.accounts.items():
            occ = acct.occupancy()
            if occ >= worst:
                worst_name, worst = name, occ
        return worst_name, worst

    def add_pressure_listener(self, fn) -> None:
        """``fn(level: str, snapshot: dict)`` fired synchronously on level
        CHANGE from whatever thread moved the occupancy — listeners must be
        cheap and thread-safe (the engine's arena/colcache trims are).
        The plane outlives its listeners' owners (it is process-wide):
        owners that die must ``remove_pressure_listener`` (the engine does
        in ``shutdown()``) or their dead closures accumulate."""
        self._listeners.append(fn)

    def remove_pressure_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _recompute_pressure(self, acct: MemoryAccount | None = None) -> None:
        # Fast path for the admission hot pair: in the ok regime, a
        # mutation that leaves ITS OWN account below the warn line cannot
        # move the level (every other account sat below it at the last
        # recompute, and each mutation recomputes) — one occupancy read
        # instead of the full account sweep.
        if acct is not None:
            with self._level_lock:
                level = self._level
            if level == PRESSURE_OK and acct.occupancy() < self.warn_pct:
                return
        _, occ = self.max_occupancy()
        with self._level_lock:
            old = self._level
            if occ >= self.critical_pct:
                new = PRESSURE_CRITICAL
            elif occ >= self.warn_pct:
                # already critical: hold until occupancy exits with margin
                if old == PRESSURE_CRITICAL and occ >= self.critical_pct - _EXIT_MARGIN:
                    new = PRESSURE_CRITICAL
                else:
                    new = PRESSURE_WARN
            else:
                if (
                    old != PRESSURE_OK
                    and occ >= self.warn_pct - _EXIT_MARGIN
                ):
                    new = PRESSURE_WARN  # hold inside the exit margin
                else:
                    new = PRESSURE_OK
            if new == old:
                return
            self._level = new
        snap = self.snapshot()
        for fn in list(self._listeners):
            try:
                fn(new, snap)
            except Exception:
                logger.exception("pressure listener failed")

    # ------------------------------------------------------------ views
    def snapshot(self) -> dict:
        worst_name, worst = self.max_occupancy()
        return {
            "total_bytes": self.total_bytes,
            "pressure": self.pressure(),
            "warn_pct": self.warn_pct,
            "critical_pct": self.critical_pct,
            "max_occupancy": round(worst, 4),
            "max_occupancy_account": worst_name,
            "accounts": {
                name: acct.snapshot() for name, acct in self.accounts.items()
            },
        }

    # ------------------------------------------------------------ gauges
    def register_gauges(self) -> None:
        """Weakref-bound labeled gauges (the governor-gauge posture: a new
        plane's registration overwrites the old one's; a collected plane
        reads -1 instead of stale occupancy)."""
        ref = weakref.ref(self)
        for name in self.accounts:
            registry.gauge(
                "resource_account_held_bytes",
                _acct_gauge(ref, name, "held"),
                "Bytes currently held in the subsystem memory account",
                account=name,
            )
            registry.gauge(
                "resource_account_limit_bytes",
                _acct_gauge(ref, name, "limit"),
                "Configured byte limit of the subsystem memory account",
                account=name,
            )
            registry.gauge(
                "resource_account_peak_bytes",
                _acct_gauge(ref, name, "peak"),
                "Peak held bytes since start (or reset_peak)",
                account=name,
            )
        registry.gauge(
            "resource_pressure_state",
            _pressure_gauge(ref),
            "Memory pressure level (0 ok, 1 warn, 2 critical, -1 no plane)",
        )


def _acct_gauge(ref, name: str, field: str):
    def fn() -> float:
        plane = ref()
        if plane is None:
            return -1.0
        acct = plane.accounts[name]
        return float(getattr(acct, field))

    return fn


def _pressure_gauge(ref):
    def fn() -> float:
        plane = ref()
        if plane is None:
            return -1.0
        return PRESSURE_NUM.get(plane.pressure(), -1.0)

    return fn


# ------------------------------------------------------------ process plane
# Installed by Application.start (config resource_memory_total_mb); bare
# engines/tests run plane-less (admission disabled) unless they install
# their own. A module accessor rather than config plumbing because the
# storage/raft charge sites sit below the service graph (DiskLog has no
# broker reference), mirroring how shard_local_cfg() is reached.
_current: BudgetPlane | None = None


def install(plane: BudgetPlane | None) -> None:
    """Install the process plane. KNOWN LIMITATION: in-process multi-
    broker stacks (tests, inproc loadgen) share one interpreter, so the
    last Application's plane wins and every node's storage/raft appends
    charge it — per-node attribution there is approximate by design
    (mirroring the shared metrics registry). Real broker processes (the
    proc backend, production) each own exactly one plane. The admission
    controllers (kafka produce, coproc, rpc) are NOT affected: they hold
    direct references to their own broker's plane."""
    global _current
    _current = plane


def current() -> BudgetPlane | None:
    return _current


def account_or_none(name: str) -> MemoryAccount | None:
    plane = _current
    if plane is None:
        return None
    return plane.accounts.get(name)
