"""Admission control over the budget plane: shed-before-ack, never silent.

Parity with the reference's connection_context memory units + the Kafka
quota/throttle posture: a subsystem that cannot reserve its bytes REFUSES
the work up front with a retriable backpressure signal and a throttle
delay, instead of queueing unboundedly or failing after the ack. Three
admission points consume this module:

- kafka produce (kafka/server/handlers.py): shed → per-partition retriable
  ``throttling_quota_exceeded`` (KIP-599) + ``throttle_time_ms`` — the
  produce never reaches ``replicate``, so a shed write is never readable.
- coproc ``submit_group`` (coproc/engine.py): shed → ``ShedError`` before
  any dispatch; the pacemaker backs off ``retry_after_ms`` and re-reads
  the same offsets (nothing lost, nothing duplicated).
- rpc dispatch (rpc/server.py): ``InflightGate`` sheds whole requests at
  dispatch with ``wire.STATUS_BACKPRESSURE`` before the handler runs.

The throttle delay ramps with occupancy past the warn line — a barely-full
account answers "retry soon", a saturated one "back off hard" — so an
open-loop flood converges to the knee instead of retry-storming it.
"""

from __future__ import annotations

import threading
import weakref

from redpanda_tpu.metrics import Counter, registry
from redpanda_tpu.resource_mgmt.budgets import MemoryAccount


class ShedError(Exception):
    """Admission refused: retriable backpressure, never a data fault.

    ``retry_after_ms`` is the throttle hint the transport-level reply
    carries (kafka ``throttle_time_ms``, pacemaker backoff)."""

    def __init__(self, subsystem: str, retry_after_ms: int, detail: str = ""):
        self.subsystem = subsystem
        self.retry_after_ms = int(retry_after_ms)
        super().__init__(
            f"{subsystem} admission shed (retry after {retry_after_ms} ms)"
            + (f": {detail}" if detail else "")
        )


# lazy per-subsystem shed counters (<subsystem>_admission_shed_total),
# check-then-create under a lock like probes.coproc_failure_counter
_shed_counters: dict[str, Counter] = {}
_shed_lock = threading.Lock()


def shed_counter(subsystem: str) -> Counter:
    c = _shed_counters.get(subsystem)
    if c is None:
        with _shed_lock:
            c = _shed_counters.get(subsystem)
            if c is None:
                c = registry.counter(
                    f"{subsystem}_admission_shed_total",
                    "Requests shed by admission control (retriable "
                    "backpressure, counted not lost)",
                )
                _shed_counters[subsystem] = c
    return c


class AdmissionController:
    """Admission over one memory account.

    ``try_admit(n)`` reserves before the work is acked; a refusal returns
    ``(0, retry_after_ms)`` and counts one shed. The caller must
    ``release`` exactly what was reserved once the work's bytes leave the
    subsystem (response drained / ticket harvested), on every path —
    including exceptions (the leak-on-exception tests pin this)."""

    def __init__(
        self,
        account: MemoryAccount,
        subsystem: str,
        *,
        base_throttle_ms: int = 50,
        max_throttle_ms: int = 1000,
        warn_pct: float = 0.75,
        on_episode=None,
    ) -> None:
        self.account = account
        self.subsystem = subsystem
        self.base_throttle_ms = int(base_throttle_ms)
        self.max_throttle_ms = int(max_throttle_ms)
        self._warn_pct = float(warn_pct)
        # episode hook: ``on_episode(kind, info)`` fires on the FIRST shed
        # of an episode and on the first admit after one ("resumed") — the
        # application journals these through the governor so the decision
        # journal reconstructs every shed episode without a per-request
        # entry flooding the bounded ring
        self._on_episode = on_episode
        self._episode_open = False
        # counter lock: try_admit runs on engine/executor threads AND the
        # loop concurrently; unlocked += would lose updates
        self._stats_lock = threading.Lock()
        self._sheds = 0
        self._admitted = 0
        self._counter = shed_counter(subsystem)

    def throttle_ms(self) -> int:
        """Deterministic occupancy ramp: base at the warn line, max at a
        full account (linear between) — testable, no randomness."""
        occ = self.account.occupancy()
        if occ <= self._warn_pct:
            return self.base_throttle_ms
        frac = min(1.0, (occ - self._warn_pct) / max(1e-9, 1.0 - self._warn_pct))
        return int(
            self.base_throttle_ms
            + frac * (self.max_throttle_ms - self.base_throttle_ms)
        )

    def try_admit(self, n: int) -> tuple[int, int]:
        """(reserved_bytes, retry_after_ms). reserved == 0 for n > 0 means
        SHED (retry_after_ms says when); n <= 0 admits reserving nothing —
        and touches NO episode state (a zero-byte request during an open
        shed episode is not evidence the account recovered)."""
        if n <= 0:
            return 0, 0
        reserved = self.account.try_acquire(n)
        if n > 0 and reserved == 0:
            retry_ms = self.throttle_ms()
            with self._stats_lock:
                self._sheds += 1
                first = not self._episode_open
                self._episode_open = True
            self._counter.inc()
            if first and self._on_episode is not None:
                self._on_episode("shed", {
                    "subsystem": self.subsystem,
                    "requested_bytes": int(n),
                    "held_bytes": self.account.held,
                    "limit_bytes": self.account.limit,
                    "retry_after_ms": retry_ms,
                })
            return 0, retry_ms
        with self._stats_lock:
            self._admitted += 1
            resumed = self._episode_open
            self._episode_open = False
        if resumed and self._on_episode is not None:
            self._on_episode("resumed", {"subsystem": self.subsystem})
        return reserved, 0

    def admit(self, n: int) -> int:
        """Reserve or raise ShedError. Returns the reserved amount the
        caller must release."""
        reserved, retry_ms = self.try_admit(n)
        if n > 0 and reserved == 0:
            raise ShedError(self.subsystem, retry_ms)
        return reserved

    def release(self, reserved: int) -> None:
        self.account.release(reserved)

    def snapshot(self) -> dict:
        with self._stats_lock:
            admitted, sheds = self._admitted, self._sheds
        return {
            "subsystem": self.subsystem,
            "admitted": admitted,
            "sheds": sheds,
            "base_throttle_ms": self.base_throttle_ms,
            "max_throttle_ms": self.max_throttle_ms,
            "account": self.account.snapshot(),
        }


class InflightGate:
    """Dispatch-time inflight cap for the rpc server: bounds BOTH request
    count and body bytes (charged to the rpc account so the occupancy
    gauges and the pressure signal see them). ``try_enter`` runs on the
    accept loop per inbound request — two int compares on the admit path."""

    def __init__(
        self,
        account: MemoryAccount,
        *,
        max_requests: int = 1024,
        subsystem: str = "rpc",
        on_episode=None,
    ) -> None:
        self.account = account
        self.max_requests = max(1, int(max_requests))
        self._inflight = 0
        self._lock = threading.Lock()
        self._sheds = 0
        self._counter = shed_counter(subsystem)
        # same episode contract as AdmissionController: first shed /
        # first admit-after-sheds fire the hook once, so the decision
        # journal reconstructs rpc shed episodes too
        self._on_episode = on_episode
        self._episode_open = False
        self._subsystem = subsystem
        # live inflight depth as a gauge (weakref posture, like the
        # budget-plane account gauges): the pandatrend history ring
        # samples it into the `inflight:rpc` counter track
        ref = weakref.ref(self)
        registry.gauge(
            "rpc_inflight_requests",
            lambda: float(g._inflight) if (g := ref()) is not None else -1.0,
            "Requests currently inside the rpc dispatch inflight gate "
            "(-1 when the gate has been collected)",
            subsystem=subsystem,
        )

    def _shed(self, why: str) -> None:
        with self._lock:
            self._sheds += 1
            first = not self._episode_open
            self._episode_open = True
        self._counter.inc()
        if first and self._on_episode is not None:
            self._on_episode("shed", {
                "subsystem": self._subsystem, "reason": why,
                "inflight": self._inflight,
                "held_bytes": self.account.held,
                "limit_bytes": self.account.limit,
            })

    def try_enter(self, nbytes: int) -> int | None:
        """Reserved byte count to hand back to ``leave``, or None = SHED."""
        with self._lock:
            if self._inflight >= self.max_requests:
                over = True
            else:
                over = False
                self._inflight += 1
        if over:
            self._shed("inflight request cap")
            return None
        reserved = self.account.try_acquire(max(1, nbytes))
        if reserved == 0:
            with self._lock:
                self._inflight -= 1
            self._shed("rpc byte account exhausted")
            return None
        with self._lock:
            resumed = self._episode_open
            self._episode_open = False
        if resumed and self._on_episode is not None:
            self._on_episode("resumed", {"subsystem": self._subsystem})
        return reserved

    def leave(self, reserved: int) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
        self.account.release(reserved)

    def snapshot(self) -> dict:
        with self._lock:
            inflight, sheds = self._inflight, self._sheds
        return {
            "inflight": inflight,
            "max_requests": self.max_requests,
            "sheds": sheds,
            "account": self.account.snapshot(),
        }
