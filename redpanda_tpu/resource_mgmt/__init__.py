"""Resource management: memory budgets + scheduling groups.

Parity with resource_mgmt/ (memory_groups.h static memory split,
cpu_scheduling.h scheduling groups). The reference divides Seastar shard
memory between subsystems and gates every Kafka request on size-based
memory units before parsing (connection_context.cc:32). Here:

- ``MemoryBudget``: an async byte-budget semaphore. The Kafka server
  acquires a request's frame size before reading its body and releases it
  after the response drains, so a flood of large produce requests
  backpressures at the socket instead of ballooning the heap.
- ``MemoryGroups``: the static split of a total budget between subsystems
  (kafka request memory, rpc, coproc staging), mirroring memory_groups.h.
- ``SchedulingGroup``: a named concurrency gate + runtime counter for
  per-subsystem attribution (asyncio has no preemptive scheduler to donate
  shares to, so groups bound concurrent tasks and publish aggregate
  runtime to the metrics registry instead).

The BUDGET PLANE (budgets.py + admission.py, re-exported here) grows this
into the process-wide split: per-subsystem ``MemoryAccount``s carved from
one configurable total, a derived ok/warn/critical ``MemoryPressure``
signal, and admission controllers that shed with retriable backpressure
before the ack (kafka produce, coproc submit, rpc dispatch) instead of
queueing unboundedly. See budgets.py's docstring for the account map.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass

from redpanda_tpu.resource_mgmt.budgets import (  # noqa: F401  (re-exports)
    BudgetPlane,
    MemoryAccount,
    PRESSURE_CRITICAL,
    PRESSURE_OK,
    PRESSURE_WARN,
)
from redpanda_tpu.resource_mgmt.admission import (  # noqa: F401
    AdmissionController,
    InflightGate,
    ShedError,
)


class MemoryBudget(MemoryAccount):
    """Async byte budget: acquire(n) waits until n bytes are available.

    A single request larger than the whole budget is clamped to the budget
    (it proceeds alone rather than deadlocking), matching the reference's
    semaphore-units behavior for oversized requests.

    ONE implementation, not two: this is the budget plane's
    ``MemoryAccount`` (budgets.py) under its historical name — the FIFO
    waiter machinery with its delicate cancel-after-grant and
    dead-loop-head liveness rules lives there alone, plus the
    available/in_use views this class's consumers (the kafka frame
    memory gate) read."""

    def __init__(self, limit_bytes: int):
        super().__init__("memory_budget", limit_bytes)

    @property
    def available(self) -> int:
        return self.limit - self.held

    @property
    def in_use(self) -> int:
        return self.held


@dataclass
class MemoryGroups:
    """Static split of the process budget (memory_groups.h)."""

    total_bytes: int = 512 * 1024 * 1024

    @property
    def kafka_request_memory(self) -> int:
        return self.total_bytes // 4

    @property
    def rpc_memory(self) -> int:
        return self.total_bytes // 8

    @property
    def coproc_staging_memory(self) -> int:
        return self.total_bytes // 4

    @property
    def storage_cache_memory(self) -> int:
        return self.total_bytes - (
            self.kafka_request_memory + self.rpc_memory + self.coproc_staging_memory
        )


class SchedulingGroup:
    """Named concurrency gate with runtime attribution (cpu_scheduling.h's
    observable cousin: bounds concurrent tasks per subsystem and records
    cumulative runtime for /metrics)."""

    def __init__(self, name: str, max_concurrency: int = 0):
        self.name = name
        self._sem = asyncio.Semaphore(max_concurrency) if max_concurrency else None
        self.runtime_s = 0.0
        self.tasks_run = 0

    async def run(self, coro):
        if self._sem is not None:
            async with self._sem:
                return await self._timed(coro)
        return await self._timed(coro)

    async def _timed(self, coro):
        t0 = time.monotonic()
        try:
            return await coro
        finally:
            self.runtime_s += time.monotonic() - t0
            self.tasks_run += 1


def default_scheduling_groups() -> dict[str, SchedulingGroup]:
    """The reference's group set (application.h scheduling_groups)."""
    return {
        name: SchedulingGroup(name)
        for name in ("raft", "kafka", "cluster", "coproc", "admin", "archival")
    }
