"""Resource management: memory budgets + scheduling groups.

Parity with resource_mgmt/ (memory_groups.h static memory split,
cpu_scheduling.h scheduling groups). The reference divides Seastar shard
memory between subsystems and gates every Kafka request on size-based
memory units before parsing (connection_context.cc:32). Here:

- ``MemoryBudget``: an async byte-budget semaphore. The Kafka server
  acquires a request's frame size before reading its body and releases it
  after the response drains, so a flood of large produce requests
  backpressures at the socket instead of ballooning the heap.
- ``MemoryGroups``: the static split of a total budget between subsystems
  (kafka request memory, rpc, coproc staging), mirroring memory_groups.h.
- ``SchedulingGroup``: a named concurrency gate + runtime counter for
  per-subsystem attribution (asyncio has no preemptive scheduler to donate
  shares to, so groups bound concurrent tasks and publish aggregate
  runtime to the metrics registry instead).
"""

from __future__ import annotations

import asyncio
from collections import deque
import time
from dataclasses import dataclass


class MemoryBudget:
    """Async byte budget: acquire(n) waits until n bytes are available.

    A single request larger than the whole budget is clamped to the budget
    (it proceeds alone rather than deadlocking), matching the reference's
    semaphore-units behavior for oversized requests.
    """

    def __init__(self, limit_bytes: int):
        self.limit = limit_bytes
        self._available = limit_bytes
        # FIFO of (n, future) waiters, granted synchronously by release():
        # no tasks, no loop lookups — release is safe from any context ON
        # THE LOOP'S THREAD, including loopless shutdown paths (a lost
        # wakeup here would hang the produce-path backpressure gate
        # forever). Cross-thread release is NOT supported: set_result
        # wakes the waiter via its loop's call_soon, which is not
        # thread-safe.
        self._waiters: deque[tuple[int, asyncio.Future]] = deque()

    @property
    def available(self) -> int:
        return self._available

    @property
    def in_use(self) -> int:
        return self.limit - self._available

    async def acquire(self, n: int) -> int:
        """Returns the amount actually reserved (clamped to the limit)."""
        n = min(n, self.limit)
        # FIFO fairness: even if n fits, queue behind existing waiters so a
        # stream of small requests cannot starve a parked large one
        if self._available >= n and not self._waiters:
            self._available -= n
            return n
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((n, fut))
        try:
            await fut  # resolved by _drain with the bytes already deducted
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # grant landed before the cancellation: hand it back
                self.release(n)
            else:
                try:
                    self._waiters.remove((n, fut))
                except ValueError:
                    pass
                self._drain()  # our slot may unblock the next waiter
            raise
        return n

    def release(self, n: int) -> None:
        self._available = min(self._available + n, self.limit)
        self._drain()

    def _drain(self) -> None:
        while self._waiters:
            n, fut = self._waiters[0]
            # liveness BEFORE the size gate: a dead head larger than the
            # budget can never remove itself (its loop is closed, its
            # CancelledError handler will never run) and would otherwise
            # block every live waiter behind it forever
            if fut.cancelled():
                self._waiters.popleft()
                continue
            try:
                dead = fut.get_loop().is_closed()
            except RuntimeError:
                dead = True
            if dead:
                # a waiter whose loop is gone can never run: granting it
                # would leak the bytes AND set_result would raise from the
                # closed loop's call_soon — skip it like a cancelled one
                self._waiters.popleft()
                continue
            if n > self._available:
                break  # live head must wait; FIFO order preserved
            self._waiters.popleft()
            self._available -= n
            fut.set_result(None)


@dataclass
class MemoryGroups:
    """Static split of the process budget (memory_groups.h)."""

    total_bytes: int = 512 * 1024 * 1024

    @property
    def kafka_request_memory(self) -> int:
        return self.total_bytes // 4

    @property
    def rpc_memory(self) -> int:
        return self.total_bytes // 8

    @property
    def coproc_staging_memory(self) -> int:
        return self.total_bytes // 4

    @property
    def storage_cache_memory(self) -> int:
        return self.total_bytes - (
            self.kafka_request_memory + self.rpc_memory + self.coproc_staging_memory
        )


class SchedulingGroup:
    """Named concurrency gate with runtime attribution (cpu_scheduling.h's
    observable cousin: bounds concurrent tasks per subsystem and records
    cumulative runtime for /metrics)."""

    def __init__(self, name: str, max_concurrency: int = 0):
        self.name = name
        self._sem = asyncio.Semaphore(max_concurrency) if max_concurrency else None
        self.runtime_s = 0.0
        self.tasks_run = 0

    async def run(self, coro):
        if self._sem is not None:
            async with self._sem:
                return await self._timed(coro)
        return await self._timed(coro)

    async def _timed(self, coro):
        t0 = time.monotonic()
        try:
            return await coro
        finally:
            self.runtime_s += time.monotonic() - t0
            self.tasks_run += 1


def default_scheduling_groups() -> dict[str, SchedulingGroup]:
    """The reference's group set (application.h scheduling_groups)."""
    return {
        name: SchedulingGroup(name)
        for name in ("raft", "kafka", "cluster", "coproc", "admin", "archival")
    }
