from redpanda_tpu.utils.vint import (
    encode_uvarint,
    decode_uvarint,
    encode_zigzag,
    decode_zigzag,
    uvarint_size,
    zigzag_size,
)
from redpanda_tpu.utils.iobuf import IOBuf

__all__ = [
    "encode_uvarint",
    "decode_uvarint",
    "encode_zigzag",
    "decode_zigzag",
    "uvarint_size",
    "zigzag_size",
    "IOBuf",
]
