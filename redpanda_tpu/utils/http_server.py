"""Shared aiohttp server lifecycle (admin, REST proxy, schema registry).

One place for runner setup, ephemeral-port resolution, and the listen log —
the reference's analogous shared piece is ``pandaproxy::server``.
"""

from __future__ import annotations

import logging

from aiohttp import web


async def start_site(
    app: web.Application,
    host: str,
    port: int,
    logger: logging.Logger,
    name: str,
    ssl_context=None,
) -> tuple[web.AppRunner, int]:
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
    await site.start()
    if port == 0:
        port = runner.addresses[0][1]
    logger.info("%s listening on %s:%d", name, host, port)
    return runner, port
