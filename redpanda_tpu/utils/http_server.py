"""Shared HTTP server lifecycle (admin, REST proxy, schema registry).

One place for listener setup, ephemeral-port resolution, and the listen
log — the reference's analogous shared piece is ``pandaproxy::server``.
Serves on the OWNED HTTP/1.1 server (redpanda_tpu/http/server.py); no
third-party HTTP library.
"""

from __future__ import annotations

import logging

from redpanda_tpu.http import web


async def start_site(
    app: web.Application,
    host: str,
    port: int,
    logger: logging.Logger,
    name: str,
    ssl_context=None,
) -> tuple[web.AppRunner, int]:
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    port = await runner.listen(host, port, ssl_context=ssl_context, logger=logger)
    logger.info("%s listening on %s:%d", name, host, port)
    return runner, port
