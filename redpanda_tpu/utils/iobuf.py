"""Fragmented buffer — the data-plane currency.

Capability parity with the reference's ``bytes/iobuf.h``: an append-only
sequence of fragments supporting zero-copy share/slice, cheap concatenation,
and linearization only at API boundaries (wire encode, device packing).

On the host side Python's ``memoryview`` gives us refcounted zero-copy
windows; the native extension (native/) consumes the fragment list directly
when packing device arrays.
"""

from __future__ import annotations


class IOBuf:
    __slots__ = ("_frags", "_size")

    def __init__(self, data: bytes | bytearray | memoryview | None = None):
        self._frags: list[memoryview] = []
        self._size = 0
        if data is not None:
            self.append(data)

    def append(self, data) -> "IOBuf":
        if isinstance(data, IOBuf):
            self._frags.extend(data._frags)
            self._size += data._size
        else:
            mv = memoryview(data).cast("B")
            if len(mv):
                self._frags.append(mv)
                self._size += len(mv)
        return self

    def prepend(self, data) -> "IOBuf":
        mv = memoryview(data).cast("B")
        if len(mv):
            self._frags.insert(0, mv)
            self._size += len(mv)
        return self

    def __len__(self) -> int:
        return self._size

    def __bytes__(self) -> bytes:
        return b"".join(self._frags)

    def linearize(self) -> bytes:
        """Collapse to one contiguous bytes object (copies)."""
        if len(self._frags) == 1:
            return bytes(self._frags[0])
        return b"".join(self._frags)

    def share(self, pos: int, length: int) -> "IOBuf":
        """Zero-copy sub-window [pos, pos+length)."""
        if pos < 0 or length < 0 or pos + length > self._size:
            raise IndexError("share out of range")
        out = IOBuf()
        remaining = length
        for frag in self._frags:
            if remaining == 0:
                break
            if pos >= len(frag):
                pos -= len(frag)
                continue
            take = min(len(frag) - pos, remaining)
            out.append(frag[pos : pos + take])
            pos = 0
            remaining -= take
        return out

    def fragments(self) -> list[memoryview]:
        return list(self._frags)

    def __eq__(self, other) -> bool:
        if isinstance(other, (bytes, bytearray)):
            return self.linearize() == bytes(other)
        if isinstance(other, IOBuf):
            return len(self) == len(other) and self.linearize() == other.linearize()
        return NotImplemented

    def __repr__(self) -> str:
        return f"IOBuf(size={self._size}, frags={len(self._frags)})"
