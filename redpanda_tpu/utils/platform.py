"""Platform pinning helpers for the axon TPU environment.

The image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon; code that must run on the virtual CPU mesh (tests,
multi-chip dry runs, bench fallback) pins the live config instead of the
environment, and drops the axon backend factory so an unhealthy TPU tunnel
cannot hang CPU-only work.
"""

from __future__ import annotations

import os


def pin_cpu_if_requested() -> None:
    """When the operator set JAX_PLATFORMS=cpu, ALSO drop the axon TPU
    backend factory: the plugin registers regardless of the env var, and
    with an unhealthy device tunnel even cpu-backend jit can hang at
    plugin discovery. One shared gate for every cpu-pinnable entry point
    (broker startup, graft entries)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        force_cpu_platform()


def force_cpu_platform(n_virtual_devices: int | None = None) -> None:
    """Pin jax to the CPU backend; optionally request N virtual devices.

    The virtual-device flag only takes effect if the CPU backend has not
    initialized yet (XLA reads XLA_FLAGS at backend-init time).
    """
    if n_virtual_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_virtual_devices}"
        if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
