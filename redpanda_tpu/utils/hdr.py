"""Log-bucketed latency histogram.

Parity surface of utils/hdr_hist.h (the reference wraps HdrHistogram for
kafka latency probes, latency_probe.h:33-43): record values, query
percentiles, export cumulative buckets in prometheus histogram form. The
bucket layout is powers-of-two sub-divided into 4 (≈19% worst-case relative
error), which matches what the dashboards need without the full HDR tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field

_SUBBUCKETS = 4


def _bucket_of(value: int) -> int:
    if value < 1:
        value = 1
    exp = value.bit_length() - 1
    base = 1 << exp
    sub = ((value - base) * _SUBBUCKETS) >> exp  # 0.._SUBBUCKETS-1
    return exp * _SUBBUCKETS + sub


def _bucket_upper(idx: int) -> int:
    exp, sub = divmod(idx, _SUBBUCKETS)
    base = 1 << exp
    # ceil division: for base < _SUBBUCKETS a floor would yield an upper
    # bound BELOW values the bucket contains (e.g. record(1) → le="0")
    width = ((sub + 1) * base + _SUBBUCKETS - 1) // _SUBBUCKETS
    return base + width - 1


@dataclass
class HdrHist:
    unit: str = "us"
    _counts: dict[int, int] = field(default_factory=dict)
    _total: int = 0
    _sum: int = 0
    _max: int = 0

    def record(self, value: int) -> None:
        idx = _bucket_of(int(value))
        self._counts[idx] = self._counts.get(idx, 0) + 1
        self._total += 1
        self._sum += int(value)
        if value > self._max:
            self._max = int(value)

    @property
    def count(self) -> int:
        return self._total

    @property
    def sum(self) -> int:
        return self._sum

    @property
    def max(self) -> int:
        return self._max

    def mean(self) -> float:
        return self._sum / self._total if self._total else 0.0

    def percentile(self, p: float) -> int:
        """p in [0, 100]; returns the bucket upper bound at that rank."""
        if not self._total:
            return 0
        target = max(1, int(round(self._total * p / 100.0)))
        seen = 0
        # sorted(items()) materializes the dict in ONE GIL-atomic C call:
        # readers (the /metrics scrape, the SLO engine's snapshot) run on
        # other threads than some writers (harvester/executor stage
        # records), and iterating the live dict would raise "changed size
        # during iteration" the moment a writer occupies a new bucket —
        # i.e. exactly during the incident being judged. A point-in-time
        # smear against _total is acceptable; a crash is not.
        items = sorted(self._counts.items())
        for idx, n in items:
            seen += n
            if seen >= target:
                return _bucket_upper(idx)
        return _bucket_upper(items[-1][0]) if items else 0

    def cumulative_buckets(self) -> list[tuple[int, int]]:
        """[(upper_bound, cumulative_count)] for prometheus exposition.
        Safe against concurrent record(): see percentile()."""
        out = []
        seen = 0
        for idx, n in sorted(self._counts.items()):
            seen += n
            out.append((_bucket_upper(idx), seen))
        return out
