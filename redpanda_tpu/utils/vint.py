"""Variable-length integer codecs.

Capability parity with the reference's ``utils/vint.h`` (LEB128 unsigned
varints and zigzag-encoded signed varints, as used by the Kafka record
format). Layout is the Kafka/protobuf standard: 7 bits per byte, LSB group
first, high bit = continuation.
"""

from __future__ import annotations


def encode_uvarint(value: int) -> bytes:
    if value < 0:
        raise ValueError("uvarint cannot encode negative values")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_uvarint(buf, offset: int = 0) -> tuple[int, int]:
    """Return (value, bytes_consumed) reading from buf[offset:]."""
    result = 0
    shift = 0
    pos = offset
    while True:
        if pos >= len(buf):
            raise ValueError("truncated uvarint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos - offset
        shift += 7
        if shift > 70:
            raise ValueError("uvarint too long")


def encode_zigzag(value: int) -> bytes:
    if value >= 0:
        v = value << 1
    else:
        v = ((~value) << 1) | 1
    return encode_uvarint(v)


def decode_zigzag(buf, offset: int = 0) -> tuple[int, int]:
    u, n = decode_uvarint(buf, offset)
    return (u >> 1) ^ -(u & 1), n


def uvarint_size(value: int) -> int:
    return len(encode_uvarint(value))


def zigzag_size(value: int) -> int:
    return len(encode_zigzag(value))
