"""Broker aggregate: the service graph a request handler can reach.

Parity with kafka::request_context's view of the world (metadata_cache,
partition_manager, group router, quota manager — kafka/server/
request_context.h) plus the topic mutation entry points that the reference
routes through cluster::topics_frontend. Single-node phase: mutations apply
locally; the controller replaces the mutation path later.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from redpanda_tpu.cluster.partition import Partition, PartitionManager
from redpanda_tpu.cluster.topic_table import TopicConfig, TopicTable
from redpanda_tpu.models.fundamental import NTP, DEFAULT_NAMESPACE, NodeId
from redpanda_tpu.storage.log_manager import StorageApi


@dataclass
class BrokerConfig:
    node_id: NodeId = 0
    cluster_id: str = "redpanda_tpu"
    advertised_host: str = "127.0.0.1"
    advertised_port: int = 9092
    data_dir: str = "/tmp/redpanda_tpu"
    auto_create_topics: bool = True
    default_partitions: int = 1
    default_replication: int = 1
    fetch_poll_interval_s: float = 0.02
    sasl_enabled: bool = False
    superusers: list = field(default_factory=list)
    # client quotas (quota_manager.h): bytes/s per client-id, None=unlimited
    target_quota_byte_rate: int | None = None
    target_fetch_quota_byte_rate: int | None = None
    # produce-path memory gate (connection_context.cc:32 memory units)
    kafka_request_max_memory: int = 64 * 1024 * 1024
    # queue-depth latency control (qdc, application.cc:1002-1016); off by
    # default like the reference's kafka_qdc_enable
    kafka_qdc_enable: bool = False
    kafka_qdc_max_latency_ms: float = 80.0
    kafka_qdc_window_s: float = 1.0
    kafka_qdc_min_depth: int = 1
    kafka_qdc_max_depth: int = 100
    fetch_session_cache_size: int = 1000
    # consistency-testing ONLY: ack quorum produces at leader level,
    # deliberately violating acks=-1 so the linearizability checker can
    # prove it catches the violation (tools/consistency; never set this
    # in production)
    unsafe_relaxed_acks: bool = False


class Broker:
    def __init__(self, config: BrokerConfig, storage: StorageApi):
        self.config = config
        self.storage = storage
        self.topic_table = TopicTable()
        self.partition_manager = PartitionManager(storage, config.node_id)
        from redpanda_tpu.kafka.server.group_manager import GroupManager

        self.group_coordinator = GroupManager(self)
        self.metadata_cache = None  # multi-node: cluster.MetadataCache
        self.coproc_api = None  # wired once the transform engine attaches
        from redpanda_tpu.kafka.server.tx_coordinator import TxCoordinator

        self.tx_coordinator = TxCoordinator(self)
        self._rm_stms: dict = {}  # NTP -> RmStm
        from redpanda_tpu.kafka.server.fetch_session_cache import FetchSessionCache
        from redpanda_tpu.kafka.server.quota_manager import QuotaManager

        self.quota_manager = QuotaManager(
            produce_rate=config.target_quota_byte_rate,
            fetch_rate=config.target_fetch_quota_byte_rate,
        )
        self.fetch_sessions = FetchSessionCache(config.fetch_session_cache_size)
        # per-topic fetch-path transform policies (v8_engine equivalent)
        from redpanda_tpu.policy import DataPolicyTable, PolicyEngine

        self.data_policies = DataPolicyTable()
        self.policy_engine = PolicyEngine()
        self.controller_dispatcher = None  # multi-node: routes security/topic cmds
        self.controller_leader_fn = None  # multi-node: live controller leader id
        # SCRAM credentials + ACLs; cluster-replicated when a controller is
        # attached, applied locally otherwise (single-node mode)
        from redpanda_tpu.security import Authorizer, SecurityManager

        self.security = SecurityManager()
        self.authorizer = Authorizer(self.security.acls, set(config.superusers))
        self.sasl_enabled = config.sasl_enabled
        # resource_mgmt budget plane + produce admission controller:
        # installed by the application (app.py). None = admission off —
        # bare broker harnesses keep the historical semantics.
        self.budget_plane = None
        self.produce_admission = None

    async def replicate_security_cmd(self, cmd) -> None:
        """Route a user/ACL mutation: through the controller when clustered
        (security_frontend), straight into the local stores otherwise."""
        if self.controller_dispatcher is not None:
            await self.controller_dispatcher.replicate(cmd)
        else:
            await self.security.apply_command(cmd)

    # ------------------------------------------------------------ data policy
    async def set_data_policy(self, topic: str, name: str, spec_json: str) -> None:
        """data_policy_frontend: replicate through the controller when
        clustered, apply locally otherwise."""
        from redpanda_tpu.cluster.commands import create_data_policy_cmd

        cmd = create_data_policy_cmd(topic, name, spec_json)
        if self.controller_dispatcher is not None:
            await self.controller_dispatcher.replicate(cmd)
        else:
            await self.data_policies.apply_command(cmd)

    async def delete_data_policy(self, topic: str) -> None:
        from redpanda_tpu.cluster.commands import delete_data_policy_cmd

        cmd = delete_data_policy_cmd(topic)
        if self.controller_dispatcher is not None:
            await self.controller_dispatcher.replicate(cmd)
        else:
            await self.data_policies.apply_command(cmd)

    # ------------------------------------------------------------ recovery
    def _persist_topic_config(self, cfg: TopicConfig) -> None:
        """Topic configs go to the kvstore so restart recovery restores
        overrides (cleanup.policy, retention, …) — in a cluster the
        controller log is the durable copy instead."""
        import json

        from redpanda_tpu.storage.kvstore import KeySpace

        payload = {"ns": cfg.ns, "partitions": cfg.partition_count,
                   "revision": cfg.revision, "config": cfg.config_map()}
        self.storage.kvs.put(
            KeySpace.storage, f"topic_cfg/{cfg.ns}/{cfg.name}".encode(),
            json.dumps(payload).encode(),
        )

    async def recover_topics(self) -> None:
        """Single-node restart: rediscover topics from the on-disk log tree
        (<data>/<ns>/<topic>/<partition>) plus their persisted configs. In a
        cluster the controller STM replay rebuilds the topic table instead;
        here the disk IS the source of truth (log_manager.cc:179 recovery)."""
        import asyncio
        import json

        from redpanda_tpu.storage.kvstore import KeySpace

        base = self.storage.log_mgr.config.base_dir
        # the three-level dir walk is pure disk metadata: off-loop, so a
        # restart over a large data dir doesn't freeze the accept loop
        found = await asyncio.to_thread(_scan_topic_tree, base)
        for (ns, topic), n_parts in sorted(found.items()):
            if self.topic_table.contains(topic):
                continue
            cfg = TopicConfig(topic, n_parts, ns=ns)
            saved = self.storage.kvs.get(
                KeySpace.storage, f"topic_cfg/{ns}/{topic}".encode()
            )
            if saved is not None:
                payload = json.loads(saved.decode())
                cfg.revision = payload.get("revision", 0)
                for k, v in payload.get("config", {}).items():
                    cfg.apply_override(k, v)
            elif topic == "__consumer_offsets":
                cfg.cleanup_policy = "compact"
            await self.create_topic(cfg)

    def _log_overrides(self, config: TopicConfig):
        return config.log_overrides(self.storage.log_mgr.config)

    def update_log_configs(self, name: str) -> None:
        """Push altered topic storage configs into LIVE logs so retention /
        segment-size changes apply without a restart."""
        md = self.topic_table.get(name)
        if md is None:
            return
        new_cfg = md.config.log_overrides(self.storage.log_mgr.config)
        if new_cfg is None:
            new_cfg = self.storage.log_mgr.config
        for pa in md.assignments.values():
            p = self.partition_manager.get(pa.ntp)
            if p is not None:
                p.log.config = new_cfg

    def _next_revision(self) -> int:
        """Monotonic topic-incarnation counter (kvstore-durable), so a
        recreate never reuses a prior incarnation's archival paths."""
        from redpanda_tpu.storage.kvstore import KeySpace

        raw = self.storage.kvs.get(KeySpace.storage, b"topic_revision_counter")
        rev = (int(raw.decode()) if raw else 0) + 1
        self.storage.kvs.put(
            KeySpace.storage, b"topic_revision_counter", str(rev).encode()
        )
        return rev

    # ------------------------------------------------------------ topics
    async def _await_topic_table(self, pred, what: str, timeout: float = 15.0) -> None:
        """The requesting node applies committed controller commands
        asynchronously (its own STM replay); callers of the kafka API see
        the mutation once the LOCAL table reflects it.

        Polling is deliberate: TopicTable.wait_for_deltas() is a DRAINING
        single-consumer queue owned by the controller backend's reconcile
        loop — a second consumer here would steal its deltas."""
        import asyncio
        import time as _t

        deadline = _t.monotonic() + timeout
        while not pred():
            if _t.monotonic() > deadline:
                raise TimeoutError(f"{what} not applied locally in {timeout}s")
            await asyncio.sleep(0.05)

    async def create_topic(self, config: TopicConfig, *, local_only: bool = False) -> None:
        """Create a topic. Clustered: route through the controller leader
        (allocation + replicated create_topic_cmd — topics_frontend path,
        SURVEY §3.5); every replica node reconciles its own raft member.
        Standalone (or local_only, used for per-node materialized logs):
        single-replica local creation."""
        if self.controller_dispatcher is not None and not local_only:
            await self.controller_dispatcher.topic_op(0, {
                "name": config.name,
                "ns": config.ns,
                "partitions": config.partition_count,
                "replication": config.replication_factor,
                "overrides": {
                    k: v for k, v in config.config_map().items() if v is not None
                },
            })
            await self._await_topic_table(
                lambda: self.topic_table.contains(config.name),
                f"create {config.name}",
            )
            return
        if config.revision == 0:
            config.revision = self._next_revision()
        md = self.topic_table.add_topic(
            config, replicas_for=lambda p: [self.config.node_id]
        )
        for pa in md.assignments.values():
            await self.partition_manager.manage(
                pa.ntp, log_overrides=self._log_overrides(config)
            )
        self._persist_topic_config(config)

    async def delete_topic(self, name: str) -> None:
        from redpanda_tpu.storage.kvstore import KeySpace

        if self.controller_dispatcher is not None:
            md = self.topic_table.get(name)
            ns = md.config.ns if md is not None else "kafka"
            await self.controller_dispatcher.topic_op(1, {"name": name, "ns": ns})
            await self._await_topic_table(
                lambda: not self.topic_table.contains(name), f"delete {name}"
            )
            return
        md = self.topic_table.remove_topic(name)
        for pa in md.assignments.values():
            await self.partition_manager.remove(pa.ntp)
            # drop the producer/tx stm: a recreated topic must not inherit
            # the old incarnation's sequence/transaction state
            self._rm_stms.pop(pa.ntp, None)
        self.storage.kvs.remove(
            KeySpace.storage, f"topic_cfg/{md.config.ns}/{name}".encode()
        )

    async def create_partitions(self, name: str, new_count: int) -> None:
        if self.controller_dispatcher is not None:
            await self.controller_dispatcher.topic_op(
                2, {"name": name, "total": new_count}
            )
            await self._await_topic_table(
                lambda: (
                    (md := self.topic_table.get(name)) is not None
                    and md.config.partition_count >= new_count
                ),
                f"add_partitions {name}",
            )
            return
        self.topic_table.add_partitions(
            name, new_count, replicas_for=lambda p: [self.config.node_id]
        )
        md = self.topic_table.get(name)
        for pa in md.assignments.values():
            await self.partition_manager.manage(
                pa.ntp, log_overrides=self._log_overrides(md.config)
            )

    # ------------------------------------------------------------ lookup
    def get_partition(self, topic: str, partition: int, ns: str = DEFAULT_NAMESPACE) -> Partition | None:
        return self.partition_manager.get(NTP(ns, topic, partition))

    def rm_stm_for(self, partition: Partition):
        """Producer/tx state machine attached to a partition, created on
        first touch (partition.h stm_manager hooks). Callers must
        ``await ensure_rm_recovered`` before first use after restart."""
        from redpanda_tpu.cluster.rm_stm import RmStm

        stm = self._rm_stms.get(partition.ntp)
        if stm is None:
            stm = RmStm(partition)
            self._rm_stms[partition.ntp] = stm
        return stm

    async def recovered_rm_stm(self, partition: Partition):
        return await self.rm_stm_for(partition).ensure_recovered()

    def is_internal_topic(self, name: str) -> bool:
        return name.startswith("__") or name.startswith("_redpanda")


def _scan_topic_tree(base: str) -> dict[tuple[str, str], int]:
    """(ns, topic) -> partition count from <base>/<ns>/<topic>/<partition>."""
    import os

    found: dict[tuple[str, str], int] = {}
    if not os.path.isdir(base):
        return found
    for ns in os.listdir(base):
        ns_dir = os.path.join(base, ns)
        if not os.path.isdir(ns_dir):
            continue
        for topic in os.listdir(ns_dir):
            t_dir = os.path.join(ns_dir, topic)
            if not os.path.isdir(t_dir):
                continue
            parts = [p for p in os.listdir(t_dir) if p.isdigit()]
            if parts:
                found[(ns, topic)] = max(int(p) for p in parts) + 1
    return found
