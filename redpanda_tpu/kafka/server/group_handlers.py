"""Kafka consumer-group API handlers.

Parity with kafka/server/handlers/{join,sync,heartbeat,leave}_group.cc,
offset_commit/offset_fetch.cc, find_coordinator.cc, describe/list/
delete_groups.cc — routed through the broker's GroupManager (the
group_router's shard hop collapses to the asyncio loop here; coordinator-
ship is still enforced via the group-topic partition leadership).
"""

from __future__ import annotations

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.errors import ErrorCode as E
from redpanda_tpu.kafka.server.group import GroupState, OffsetCommit
from redpanda_tpu.kafka.server.group_manager import GROUP_TOPIC, GroupManager
from redpanda_tpu.kafka.server.security_handlers import authorize
from redpanda_tpu.security.acl import AclOperation, ResourceType


def _gm(ctx) -> GroupManager:
    return ctx.broker.group_coordinator


def _group_authorized(ctx, op: AclOperation, group_id: str) -> bool:
    return authorize(ctx, ResourceType.group, group_id, op)


# ------------------------------------------------------------ find_coordinator
async def handle_find_coordinator(ctx) -> dict:
    key = ctx.request["key"]
    if ctx.request.get("key_type", 0) == 1:
        # transaction coordinator: single logical coordinator on this broker
        # (tx_gateway); same node answer applies
        pass
    gm = _gm(ctx)
    await gm.start()
    cfg = ctx.broker.config
    ntp = gm.coordinator_ntp(key)
    leader_node = None
    md = ctx.broker.topic_table.get(GROUP_TOPIC)
    if md is not None and ntp.partition in md.assignments:
        p = ctx.broker.get_partition(GROUP_TOPIC, ntp.partition)
        if p is not None and p.is_leader():
            leader_node = cfg.node_id
        else:
            # clustered: live leadership is in the metadata cache (leaders
            # table via raft notifications + gossip); pa.leader only covers
            # the standalone path
            mdc = getattr(ctx.broker, "metadata_cache", None)
            if mdc is not None:
                leader_node = mdc.get_leader(ntp)
            else:
                leader_node = md.assignments[ntp.partition].leader
    if leader_node is None:
        return {
            "error_code": int(E.coordinator_not_available),
            "error_message": "coordinator election pending",
            "node_id": -1, "host": "", "port": -1,
            "throttle_time_ms": 0,
        }
    if leader_node == cfg.node_id:
        host, port = cfg.advertised_host, cfg.advertised_port
    else:
        broker_info = (
            ctx.broker.metadata_cache.get_broker(leader_node)
            if getattr(ctx.broker, "metadata_cache", None)
            else None
        )
        if broker_info is None:
            return {
                "error_code": int(E.coordinator_not_available),
                "error_message": "coordinator address unknown",
                "node_id": -1, "host": "", "port": -1,
                "throttle_time_ms": 0,
            }
        host, port = broker_info.kafka_host, broker_info.kafka_port
    return {
        "error_code": 0,
        "error_message": None,
        "node_id": leader_node,
        "host": host,
        "port": port,
        "throttle_time_ms": 0,
    }


# ------------------------------------------------------------ join/sync/heartbeat/leave
async def handle_join_group(ctx) -> dict:
    r = ctx.request
    if not _group_authorized(ctx, AclOperation.read, r["group_id"]):
        return dict(_join_error(E.group_authorization_failed, r["member_id"]), throttle_time_ms=0)
    g = await _gm(ctx).get_or_create(r["group_id"])
    if g is None:
        return dict(_join_error(E.not_coordinator, r["member_id"]), throttle_time_ms=0)
    if r["session_timeout_ms"] < 10 or r["session_timeout_ms"] > 1800_000:
        return dict(_join_error(E.invalid_session_timeout, r["member_id"]), throttle_time_ms=0)
    resp = await g.join(
        member_id=r["member_id"],
        group_instance_id=r.get("group_instance_id"),
        client_id=ctx.header.client_id or "",
        client_host=ctx.connection.client_host,
        session_timeout_ms=r["session_timeout_ms"],
        rebalance_timeout_ms=r.get("rebalance_timeout_ms", -1),
        protocol_type=r["protocol_type"],
        protocols=[(p["name"], p["metadata"]) for p in r["protocols"]],
    )
    resp["throttle_time_ms"] = 0
    return resp


def _join_error(code: E, member_id: str = "") -> dict:
    return {
        "error_code": int(code),
        "generation_id": -1,
        "protocol_name": "",
        "leader": "",
        "member_id": member_id,
        "members": [],
    }


async def handle_sync_group(ctx) -> dict:
    r = ctx.request
    if not _group_authorized(ctx, AclOperation.read, r["group_id"]):
        return {"error_code": int(E.group_authorization_failed), "assignment": b"", "throttle_time_ms": 0}
    gm = _gm(ctx)
    g = gm.get(r["group_id"]) if gm.is_coordinator(r["group_id"]) else None
    if g is None:
        code = E.not_coordinator if not gm.is_coordinator(r["group_id"]) else E.unknown_member_id
        return {"error_code": int(code), "assignment": b"", "throttle_time_ms": 0}
    resp = await g.sync(
        r["member_id"], r["generation_id"], r.get("assignments") or []
    )
    resp["throttle_time_ms"] = 0
    return resp


async def handle_heartbeat(ctx) -> dict:
    r = ctx.request
    if not _group_authorized(ctx, AclOperation.read, r["group_id"]):
        return {"error_code": int(E.group_authorization_failed), "throttle_time_ms": 0}
    gm = _gm(ctx)
    if not gm.is_coordinator(r["group_id"]):
        return {"error_code": int(E.not_coordinator), "throttle_time_ms": 0}
    g = gm.get(r["group_id"])
    if g is None:
        return {"error_code": int(E.unknown_member_id), "throttle_time_ms": 0}
    return {"error_code": int(g.heartbeat(r["member_id"], r["generation_id"])), "throttle_time_ms": 0}


async def handle_leave_group(ctx) -> dict:
    r = ctx.request
    if not _group_authorized(ctx, AclOperation.read, r["group_id"]):
        return {"error_code": int(E.group_authorization_failed), "members": [], "throttle_time_ms": 0}
    gm = _gm(ctx)
    if not gm.is_coordinator(r["group_id"]):
        return {"error_code": int(E.not_coordinator), "members": [], "throttle_time_ms": 0}
    g = gm.get(r["group_id"])
    if g is None:
        return {"error_code": int(E.unknown_member_id), "members": [], "throttle_time_ms": 0}
    member_ids = (
        [mm["member_id"] for mm in r["members"]]
        if ctx.api_version >= 3
        else [r["member_id"]]
    )
    results = await g.leave(member_ids)
    if ctx.api_version >= 3:
        return {
            "error_code": 0,
            "members": [
                {"member_id": mid, "group_instance_id": None, "error_code": int(code)}
                for mid, code in results
            ],
            "throttle_time_ms": 0,
        }
    return {"error_code": int(results[0][1]), "members": [], "throttle_time_ms": 0}


# ------------------------------------------------------------ offsets
async def handle_offset_commit(ctx) -> dict:
    r = ctx.request
    gm = _gm(ctx)
    group_ok = _group_authorized(ctx, AclOperation.read, r["group_id"])
    commits: dict[tuple[str, int], OffsetCommit] = {}
    per_partition_code: dict[tuple[str, int], E] = {}
    for t in r.get("topics") or []:
        topic_ok = authorize(ctx, ResourceType.topic, t["name"], AclOperation.read)
        for p in t["partitions"]:
            key = (t["name"], p["partition_index"])
            if not group_ok:
                per_partition_code[key] = E.group_authorization_failed
            elif not topic_ok:
                per_partition_code[key] = E.topic_authorization_failed
            else:
                commits[key] = OffsetCommit(
                    p["committed_offset"],
                    p.get("committed_leader_epoch", -1),
                    p.get("committed_metadata"),
                )
    code = E.none
    if commits:
        code = await gm.commit_offsets(
            r["group_id"], r.get("member_id", ""), r.get("generation_id", -1), commits
        )
    return {
        "throttle_time_ms": 0,
        "topics": [
            {
                "name": t["name"],
                "partitions": [
                    {
                        "partition_index": p["partition_index"],
                        "error_code": int(
                            per_partition_code.get(
                                (t["name"], p["partition_index"]), code
                            )
                        ),
                    }
                    for p in t["partitions"]
                ],
            }
            for t in r.get("topics") or []
        ],
    }


async def handle_offset_fetch(ctx) -> dict:
    r = ctx.request
    gm = _gm(ctx)
    if not _group_authorized(ctx, AclOperation.describe, r["group_id"]):
        return {"throttle_time_ms": 0, "topics": [], "error_code": int(E.group_authorization_failed)}
    await gm.start()
    if not gm.is_coordinator(r["group_id"]):
        return {"throttle_time_ms": 0, "topics": [], "error_code": int(E.not_coordinator)}
    g = gm.get(r["group_id"])
    requested = r.get("topics")
    out_topics = []
    if requested is None:
        # all offsets for the group
        by_topic: dict[str, list] = {}
        if g is not None:
            for (topic, p), oc in sorted(g.offsets.items()):
                by_topic.setdefault(topic, []).append((p, oc))
        for topic, plist in by_topic.items():
            out_topics.append({
                "name": topic,
                "partitions": [
                    {
                        "partition_index": p,
                        "committed_offset": oc.offset,
                        "committed_leader_epoch": oc.leader_epoch,
                        "metadata": oc.metadata,
                        "error_code": 0,
                    }
                    for p, oc in plist
                ],
            })
    else:
        for t in requested:
            parts = []
            for p in t["partition_indexes"]:
                oc = g.fetch_offset(t["name"], p) if g is not None else None
                parts.append({
                    "partition_index": p,
                    "committed_offset": oc.offset if oc else -1,
                    "committed_leader_epoch": oc.leader_epoch if oc else -1,
                    "metadata": oc.metadata if oc else None,
                    "error_code": 0,
                })
            out_topics.append({"name": t["name"], "partitions": parts})
    return {"throttle_time_ms": 0, "topics": out_topics, "error_code": 0}


# ------------------------------------------------------------ admin
async def handle_describe_groups(ctx) -> dict:
    gm = _gm(ctx)
    groups = []
    for gid in ctx.request.get("groups") or []:
        if not _group_authorized(ctx, AclOperation.describe, gid):
            groups.append({
                "error_code": int(E.group_authorization_failed),
                "group_id": gid, "group_state": "", "protocol_type": "",
                "protocol_data": "", "members": [],
            })
            continue
        if not gm.is_coordinator(gid):
            groups.append({
                "error_code": int(E.not_coordinator),
                "group_id": gid, "group_state": "", "protocol_type": "",
                "protocol_data": "", "members": [],
            })
            continue
        g = gm.get(gid)
        if g is None:
            entry = {
                "error_code": 0,
                "group_id": gid, "group_state": GroupState.dead.value,
                "protocol_type": "", "protocol_data": "", "members": [],
            }
        else:
            entry = g.describe()
        if ctx.api_version >= 3 and ctx.request.get("include_authorized_operations"):
            # KIP-430 bitfield; only for groups the caller may describe
            from redpanda_tpu.kafka.server.handlers import authorized_operations

            entry["authorized_operations"] = authorized_operations(
                ctx, ResourceType.group, gid
            )
        groups.append(entry)
    return {"throttle_time_ms": 0, "groups": groups}


async def handle_list_groups(ctx) -> dict:
    gm = _gm(ctx)
    await gm.start()
    return {
        "throttle_time_ms": 0,
        "error_code": 0,
        "groups": [
            {"group_id": g.group_id, "protocol_type": g.protocol_type or ""}
            for g in gm.groups.values()
            if _group_authorized(ctx, AclOperation.describe, g.group_id)
        ],
    }


async def handle_delete_groups(ctx) -> dict:
    gm = _gm(ctx)
    results = []
    for gid in ctx.request.get("groups_names") or []:
        if not _group_authorized(ctx, AclOperation.delete, gid):
            results.append({"group_id": gid, "error_code": int(E.group_authorization_failed)})
            continue
        if not gm.is_coordinator(gid):
            results.append({"group_id": gid, "error_code": int(E.not_coordinator)})
            continue
        code = await gm.delete_group(gid)
        results.append({"group_id": gid, "error_code": int(code)})
    return {"throttle_time_ms": 0, "results": results}


def register_group_handlers(handlers: dict) -> None:
    handlers[m.FIND_COORDINATOR] = handle_find_coordinator
    handlers[m.JOIN_GROUP] = handle_join_group
    handlers[m.SYNC_GROUP] = handle_sync_group
    handlers[m.HEARTBEAT] = handle_heartbeat
    handlers[m.LEAVE_GROUP] = handle_leave_group
    handlers[m.OFFSET_COMMIT] = handle_offset_commit
    handlers[m.OFFSET_FETCH] = handle_offset_fetch
    handlers[m.DESCRIBE_GROUPS] = handle_describe_groups
    handlers[m.LIST_GROUPS] = handle_list_groups
    handlers[m.DELETE_GROUPS] = handle_delete_groups
