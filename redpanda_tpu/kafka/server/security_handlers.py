"""Kafka SASL + ACL handlers.

Parity with kafka/server/handlers/{sasl_handshake,sasl_authenticate}.cc and
the ACL CRUD handlers (describe_acls/create_acls/delete_acls.cc), plus the
`authorize()` helper every data-path handler calls through its request
context (request_context.h authorized()). The SASL state machine lives on
the connection (requests.cc:99-160 interception; here the dispatch gate in
protocol.py enforces auth before any other API when SASL is enabled).
"""

from __future__ import annotations

import logging

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.errors import ErrorCode
from redpanda_tpu.security import SecurityManager
from redpanda_tpu.security.acl import (
    AclBinding,
    AclBindingFilter,
    AclEntry,
    AclOperation,
    AclPermission,
    PatternType,
    ResourcePattern,
    ResourceType,
    DEFAULT_CLUSTER_NAME,
)
from redpanda_tpu.security.scram import MECHANISMS, ScramError, ScramServerConversation

logger = logging.getLogger("rptpu.kafka.security")


def authorize(ctx, resource_type: ResourceType, name: str, op: AclOperation) -> bool:
    """True when the connection's principal may perform op; open when no
    authorizer is wired (single-node dev mode). The client's peer address
    feeds host-scoped ACL entries (request_context.h passes the connection
    address the same way)."""
    az = ctx.broker.authorizer
    if az is None:
        return True
    return az.authorized(
        resource_type, name, op,
        ctx.connection.authenticated_principal,
        host=ctx.connection.client_host,
    )


# ------------------------------------------------------------------ sasl
async def handle_sasl_handshake(ctx) -> dict:
    mech = ctx.request["mechanism"]
    conn = ctx.connection
    if mech not in MECHANISMS:
        return {
            "error_code": int(ErrorCode.unsupported_sasl_mechanism),
            "mechanisms": sorted(MECHANISMS),
        }
    sec: SecurityManager | None = ctx.broker.security
    algo = MECHANISMS[mech]
    lookup = (lambda u: None) if sec is None else sec.credentials.get
    conn.sasl_state = ScramServerConversation(lookup, algo)
    return {"error_code": 0, "mechanisms": sorted(MECHANISMS)}


async def handle_sasl_authenticate(ctx) -> dict:
    conn = ctx.connection

    def fail(msg: str) -> dict:
        conn.sasl_state = None
        return {
            "error_code": int(ErrorCode.sasl_authentication_failed),
            "error_message": msg,
            "auth_bytes": b"",
            "session_lifetime_ms": 0,
        }

    convo = conn.sasl_state
    if not isinstance(convo, ScramServerConversation):
        return fail("sasl handshake required before authenticate")
    try:
        if not convo._client_first_bare:
            out = convo.handle_client_first(ctx.request["auth_bytes"])
        else:
            out = convo.handle_client_final(ctx.request["auth_bytes"])
    except (ScramError, UnicodeDecodeError, ValueError) as e:
        return fail(str(e))
    if convo.complete:
        conn.authenticated_principal = f"User:{convo.username}"
        conn.sasl_state = None
    return {
        "error_code": 0,
        "error_message": None,
        "auth_bytes": out,
        "session_lifetime_ms": 0,
    }


# ------------------------------------------------------------------ acl crud
def _binding_from_creation(c: dict) -> AclBinding:
    return AclBinding(
        ResourcePattern(
            ResourceType(c["resource_type"]),
            c["resource_name"],
            PatternType(c.get("resource_pattern_type", int(PatternType.literal))),
        ),
        AclEntry(
            c["principal"], c["host"],
            AclOperation(c["operation"]), AclPermission(c["permission_type"]),
        ),
    )


def _filter_from_request(f: dict) -> AclBindingFilter:
    """Wire field names per the acl filter schema (messages.py
    _ACL_FILTER_REQ): *_filter variants, 0/absent = any."""

    def _enum(cls, v, default):
        return cls(v) if v else default

    return AclBindingFilter(
        resource_type=_enum(ResourceType, f.get("resource_type_filter"), ResourceType.any),
        name=f.get("resource_name_filter"),
        pattern_type=_enum(PatternType, f.get("pattern_type_filter"), PatternType.any),
        principal=f.get("principal_filter"),
        host=f.get("host_filter"),
        operation=_enum(AclOperation, f.get("operation"), AclOperation.any),
        permission=_enum(AclPermission, f.get("permission_type"), AclPermission.any),
    )


def _binding_wire(b: AclBinding) -> dict:
    return {
        "resource_type": int(b.pattern.resource_type),
        "resource_name": b.pattern.name,
        "pattern_type": int(b.pattern.pattern_type),
        "principal": b.entry.principal,
        "host": b.entry.host,
        "operation": int(b.entry.operation),
        "permission_type": int(b.entry.permission),
    }


async def handle_describe_acls(ctx) -> dict:
    if not authorize(ctx, ResourceType.cluster, DEFAULT_CLUSTER_NAME, AclOperation.describe):
        return {
            "error_code": int(ErrorCode.cluster_authorization_failed),
            "error_message": "cluster describe denied",
            "resources": [],
            "throttle_time_ms": 0,
        }
    sec: SecurityManager = ctx.broker.security
    flt = _filter_from_request(ctx.request)
    by_pattern: dict[ResourcePattern, list] = {}
    for b in sec.acls.describe(flt) if sec else []:
        by_pattern.setdefault(b.pattern, []).append(b.entry)
    return {
        "error_code": 0,
        "error_message": None,
        "throttle_time_ms": 0,
        "resources": [
            {
                "resource_type": int(p.resource_type),
                "resource_name": p.name,
                "pattern_type": int(p.pattern_type),
                "acls": [
                    {
                        "principal": e.principal,
                        "host": e.host,
                        "operation": int(e.operation),
                        "permission_type": int(e.permission),
                    }
                    for e in entries
                ],
            }
            for p, entries in by_pattern.items()
        ],
    }


async def handle_create_acls(ctx) -> dict:
    results = []
    if not authorize(ctx, ResourceType.cluster, DEFAULT_CLUSTER_NAME, AclOperation.alter):
        results = [
            {"error_code": int(ErrorCode.cluster_authorization_failed), "error_message": "denied"}
            for _ in ctx.request["creations"]
        ]
        return {"throttle_time_ms": 0, "results": results}
    bindings = []
    for c in ctx.request["creations"]:
        try:
            bindings.append(_binding_from_creation(c))
            results.append({"error_code": 0, "error_message": None})
        except (ValueError, KeyError) as e:
            results.append(
                {"error_code": int(ErrorCode.invalid_request), "error_message": str(e)}
            )
    if bindings:
        await ctx.broker.replicate_security_cmd(
            SecurityManager.create_acls_cmd(bindings)
        )
    return {"throttle_time_ms": 0, "results": results}


async def handle_delete_acls(ctx) -> dict:
    if not authorize(ctx, ResourceType.cluster, DEFAULT_CLUSTER_NAME, AclOperation.alter):
        return {
            "throttle_time_ms": 0,
            "filter_results": [
                {
                    "error_code": int(ErrorCode.cluster_authorization_failed),
                    "error_message": "denied",
                    "matching_acls": [],
                }
                for _ in ctx.request["filters"]
            ],
        }
    sec: SecurityManager = ctx.broker.security
    filter_results = []
    all_filters = []
    for f in ctx.request["filters"]:
        flt = _filter_from_request(f)
        matched = sec.acls.describe(flt) if sec else []
        all_filters.append(flt)
        filter_results.append(
            {
                "error_code": 0,
                "error_message": None,
                "matching_acls": [
                    dict(_binding_wire(b), error_code=0, error_message=None)
                    for b in matched
                ],
            }
        )
    if all_filters:
        await ctx.broker.replicate_security_cmd(
            SecurityManager.delete_acls_cmd(all_filters)
        )
    return {"throttle_time_ms": 0, "filter_results": filter_results}


def register_security_handlers(handlers: dict) -> None:
    handlers[m.SASL_HANDSHAKE] = handle_sasl_handshake
    handlers[m.SASL_AUTHENTICATE] = handle_sasl_authenticate
    handlers[m.DESCRIBE_ACLS] = handle_describe_acls
    handlers[m.CREATE_ACLS] = handle_create_acls
    handlers[m.DELETE_ACLS] = handle_delete_acls
