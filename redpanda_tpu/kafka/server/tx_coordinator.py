"""Transaction coordinator: producer-id allocation + tx state + gateway.

Parity with cluster/id_allocator_stm (producer id blocks), cluster/tm_stm
(transactional_id → {pid, epoch, state, partitions}) and
tx_gateway_frontend (the begin/commit choreography, tx_gateway.json RPCs).
The reference replicates coordinator state through dedicated raft groups;
here it rides the broker's kvstore WAL (single-node durable) with the same
state machine — the cluster path reuses these transitions behind partition
leadership of a tx-state topic when multi-node tx lands.

EOS flow (matching the reference's message order):
  InitProducerId → [AddPartitionsToTxn → produce…] → (AddOffsetsToTxn →
  TxnOffsetCommit)… → EndTxn{commit|abort} → rm_stm markers + group offsets.
"""

from __future__ import annotations

import enum
import json
import logging
import time

from redpanda_tpu.kafka.protocol.errors import ErrorCode as E
from redpanda_tpu.kafka.server.group import OffsetCommit
from redpanda_tpu.storage.kvstore import KeySpace

logger = logging.getLogger("rptpu.kafka.tx")

_PID_BLOCK = 1000  # id_allocator_stm hands out ranges, not single ids


def _new_lock():
    import asyncio

    return asyncio.Lock()


class TxState(enum.Enum):
    empty = "Empty"
    ongoing = "Ongoing"
    prepare_commit = "PrepareCommit"
    prepare_abort = "PrepareAbort"
    complete_commit = "CompleteCommit"
    complete_abort = "CompleteAbort"


class TxMetadata:
    def __init__(self, tx_id: str, pid: int, epoch: int, timeout_ms: int) -> None:
        self.tx_id = tx_id
        self.pid = pid
        self.epoch = epoch
        self.timeout_ms = timeout_ms
        self.state = TxState.empty
        self.partitions: set[tuple[str, int]] = set()
        # group_id -> staged offset commits, applied atomically on commit
        self.staged_offsets: dict[str, dict[tuple[str, int], OffsetCommit]] = {}
        self.last_update = time.monotonic()
        # runtime-only (not persisted): finish serialization + re-drive pacing
        self.finish_lock = _new_lock()
        self.redrive_attempts = 0
        self.next_redrive = 0.0

    def to_dict(self) -> dict:
        return {
            "tx_id": self.tx_id, "pid": self.pid, "epoch": self.epoch,
            "timeout_ms": self.timeout_ms, "state": self.state.value,
            "partitions": sorted(self.partitions),
            # staged offsets must survive a crash between TxnOffsetCommit
            # and the commit completing, or acked-committed offsets vanish
            "staged_offsets": {
                g: [[t, p, oc.offset, oc.leader_epoch, oc.metadata]
                    for (t, p), oc in commits.items()]
                for g, commits in self.staged_offsets.items()
            },
        }

    @staticmethod
    def from_dict(d: dict) -> "TxMetadata":
        md = TxMetadata(d["tx_id"], d["pid"], d["epoch"], d["timeout_ms"])
        md.state = TxState(d["state"])
        md.partitions = {(t, p) for t, p in d["partitions"]}
        for g, commits in d.get("staged_offsets", {}).items():
            md.staged_offsets[g] = {
                (t, p): OffsetCommit(off, epoch, meta)
                for t, p, off, epoch, meta in commits
            }
        return md


class TxCoordinator:
    def __init__(self, broker, expire_interval_s: float = 1.0) -> None:
        from redpanda_tpu.cluster.tx_gateway import TxRouter

        # local-only by default; the app swaps in a mesh-routed router
        # (metadata cache + connection cache) when clustered
        self.router = TxRouter(broker)
        self.broker = broker
        self.expire_interval_s = expire_interval_s
        self._txs: dict[str, TxMetadata] = {}
        self._next_pid: int | None = None
        self._block_end = -1
        self._loaded = False
        self._expire_task = None

    # ------------------------------------------------------------ lifecycle
    def start_expiry(self) -> None:
        import asyncio

        if self._expire_task is None or self._expire_task.done():
            self._expire_task = asyncio.create_task(self._expire_loop())

    async def stop(self) -> None:
        import asyncio

        if self._expire_task is not None:
            self._expire_task.cancel()
            try:
                await self._expire_task
            except asyncio.CancelledError:
                pass
            self._expire_task = None

    async def _expire_loop(self) -> None:
        import asyncio

        # load (and re-drive crashed prepare_* transactions) even if no
        # client ever issues a tx API call after restart
        try:
            await self._load()
        except Exception:
            logger.exception("tx state load failed")
        while True:
            await asyncio.sleep(self.expire_interval_s)
            try:
                await self.expire_stale()
            except Exception:
                logger.exception("tx expiry pass failed")

    # ------------------------------------------------------------ persistence
    def _kvs(self):
        return self.broker.storage.kvs

    async def _load(self) -> None:
        if self._loaded:
            return
        for key in self._kvs().keys(KeySpace.controller):
            if key.startswith(b"tx/"):
                d = json.loads(self._kvs().get(KeySpace.controller, key).decode())
                self._txs[d["tx_id"]] = TxMetadata.from_dict(d)
        self._loaded = True
        # resume transactions that crashed mid-commit/abort: re-drive the
        # marker fan-out (tm_stm replays prepared txs on recovery)
        for md in list(self._txs.values()):
            if md.state == TxState.prepare_commit:
                await self._finish(md, commit=True)
            elif md.state == TxState.prepare_abort:
                await self._finish(md, commit=False)

    def _persist_tx(self, md: TxMetadata) -> None:
        self._kvs().put(
            KeySpace.controller, b"tx/" + md.tx_id.encode(),
            json.dumps(md.to_dict()).encode(),
        )

    # ------------------------------------------------------------ pid allocation
    def _alloc_pid(self) -> int:
        """id_allocator_stm: claim a block in the durable store, hand out
        ids from memory — one write per _PID_BLOCK allocations."""
        if self._next_pid is None or self._next_pid > self._block_end:
            raw = self._kvs().get(KeySpace.controller, b"id_allocator/next_block")
            start = int(raw.decode()) if raw else 0
            self._kvs().put(
                KeySpace.controller, b"id_allocator/next_block",
                str(start + _PID_BLOCK).encode(),
            )
            self._next_pid, self._block_end = start, start + _PID_BLOCK - 1
        pid = self._next_pid
        self._next_pid += 1
        return pid

    # ------------------------------------------------------------ api
    async def init_producer_id(
        self, tx_id: str | None, timeout_ms: int
    ) -> tuple[E, int, int]:
        await self._load()
        if not tx_id:
            return E.none, self._alloc_pid(), 0
        md = self._txs.get(tx_id)
        if md is None:
            md = TxMetadata(tx_id, self._alloc_pid(), 0, timeout_ms)
        else:
            # fence the previous incarnation: finish whatever it left open
            # BEFORE handing out a new epoch — clearing partitions with
            # markers unwritten would pin those partitions' LSO forever
            pending = {
                TxState.ongoing: False,
                TxState.prepare_abort: False,
                TxState.prepare_commit: True,
            }
            if md.state in pending:
                code = await self._finish(md, commit=pending[md.state])
                if code != E.none:
                    return E.concurrent_transactions, -1, -1  # retriable
            md.epoch += 1
            md.timeout_ms = timeout_ms
            if md.epoch > 0x7FFF - 1:
                md = TxMetadata(tx_id, self._alloc_pid(), 0, timeout_ms)
        md.state = TxState.empty
        md.partitions.clear()
        md.staged_offsets.clear()
        md.last_update = time.monotonic()
        self._txs[tx_id] = md
        self._persist_tx(md)
        return E.none, md.pid, md.epoch

    async def _check(self, tx_id: str, pid: int, epoch: int) -> tuple[E, TxMetadata | None]:
        await self._load()
        md = self._txs.get(tx_id)
        if md is None:
            return E.invalid_producer_id_mapping, None
        if md.pid != pid:
            return E.invalid_producer_id_mapping, None
        if md.epoch != epoch:
            return E.invalid_producer_epoch, None
        return E.none, md

    async def add_partitions(
        self, tx_id: str, pid: int, epoch: int, parts: list[tuple[str, int]]
    ) -> dict[tuple[str, int], E]:
        code, md = await self._check(tx_id, pid, epoch)
        if code != E.none:
            return {tp: code for tp in parts}
        out: dict[tuple[str, int], E] = {}
        for topic, p in parts:
            md_t = self.broker.topic_table.get(topic)
            if md_t is None or p not in md_t.assignments:
                out[(topic, p)] = E.unknown_topic_or_partition
                continue
            # begin on the partition LEADER via the tx gateway (local rm_stm
            # fast path when this broker leads it)
            try:
                out[(topic, p)] = E(await self.router.begin_tx(topic, p, pid, epoch))
            except Exception:
                logger.exception("tx %s: begin failed on %s/%d", tx_id, topic, p)
                out[(topic, p)] = E.coordinator_not_available
            if out[(topic, p)] == E.none:
                md.partitions.add((topic, p))
        if any(c == E.none for c in out.values()):
            md.state = TxState.ongoing
            md.last_update = time.monotonic()
            self._persist_tx(md)
        return out

    async def add_offsets(self, tx_id: str, pid: int, epoch: int, group_id: str) -> E:
        code, md = await self._check(tx_id, pid, epoch)
        if code != E.none:
            return code
        md.staged_offsets.setdefault(group_id, {})
        md.state = TxState.ongoing
        self._persist_tx(md)
        return E.none

    async def txn_offset_commit(
        self, tx_id: str, pid: int, epoch: int, group_id: str,
        commits: dict[tuple[str, int], OffsetCommit],
    ) -> E:
        code, md = await self._check(tx_id, pid, epoch)
        if code != E.none:
            return code
        if group_id not in md.staged_offsets:
            return E.invalid_txn_state  # AddOffsetsToTxn must come first
        md.staged_offsets[group_id].update(commits)
        # durable BEFORE the ack: a crash between this ack and EndTxn must
        # not lose offsets the app was told are part of the transaction
        self._persist_tx(md)
        return E.none

    async def end_txn(self, tx_id: str, pid: int, epoch: int, commit: bool) -> E:
        code, md = await self._check(tx_id, pid, epoch)
        if code != E.none:
            return code
        # retrying EndTxn after a failed/interrupted finish is legal as long
        # as the direction matches the prepared one
        if md.state == TxState.prepare_commit and not commit:
            return E.invalid_txn_state
        if md.state == TxState.prepare_abort and commit:
            return E.invalid_txn_state
        if md.state in (TxState.complete_commit, TxState.complete_abort):
            return E.invalid_txn_state
        if md.state == TxState.empty and not md.partitions and not md.staged_offsets:
            return E.none  # nothing to do; kafka allows the no-op commit
        return await self._finish(md, commit)

    async def _finish(self, md: TxMetadata, commit: bool, *, redrive: bool = False) -> E:
        # Serialized per tx: the 1 Hz re-drive (expire_stale) must never
        # overlap the client's own EndTxn attempt — a duplicate marker RPC
        # landing AFTER completion could commit/abort the producer's NEXT
        # transaction's open data (same pid/epoch spans transactions).
        async with md.finish_lock:
            if md.state in (TxState.complete_commit, TxState.complete_abort):
                return E.none  # the other driver already completed it
            return await self._finish_locked(md, commit, redrive)

    async def _finish_locked(self, md: TxMetadata, commit: bool, redrive: bool) -> E:
        md.state = TxState.prepare_commit if commit else TxState.prepare_abort
        self._persist_tx(md)
        # Partitions whose TOPIC no longer exists can never take a marker —
        # their rm_stm state died with the topic; keeping them would brick
        # this transactional id in an unfinishable prepare_* loop.
        for topic, p in list(md.partitions):
            tmd = self.broker.topic_table.get(topic)
            if tmd is None or p not in tmd.assignments:
                logger.warning(
                    "tx %s: dropping marker for deleted %s/%d", md.tx_id, topic, p
                )
                md.partitions.discard((topic, p))
        # 1. control markers on every touched partition (tx_gateway fan-out).
        #    Any failure leaves the tx in prepare_* so EndTxn/recovery can
        #    re-drive it — claiming success with a marker missing would pin
        #    that partition's LSO forever.
        failed = False
        retriable = {
            int(E.not_leader_for_partition),
            int(E.coordinator_not_available),
            int(E.unknown_server_error),
            int(E.unknown_topic_or_partition),
        }

        # markers route through the tx gateway: local rm_stm when this
        # broker leads the partition, internal RPC to the leader otherwise
        # (cluster/tx_gateway.py). Independent partitions fan out
        # CONCURRENTLY so one attempt is bounded by the slowest single RPC,
        # not their sum (the reference's parallel tx_gateway fan-out).
        import asyncio

        parts = sorted(md.partitions)

        async def one_marker(topic: str, p: int) -> int:
            try:
                return await self.router.write_marker(
                    topic, p, md.pid, md.epoch, commit
                )
            except Exception:
                logger.exception(
                    "tx %s: marker write failed on %s/%d", md.tx_id, topic, p
                )
                return int(E.unknown_server_error)

        codes = await asyncio.gather(*(one_marker(t, p) for t, p in parts))
        for (topic, p), code in zip(parts, codes):
            if code in retriable:
                logger.warning(
                    "tx %s: partition %s/%d unavailable during end_txn "
                    "(errc %d); will retry", md.tx_id, topic, p, code,
                )
                failed = True
                continue
            if code != 0:
                if redrive:
                    # A fence during RE-DRIVE means a newer epoch already
                    # superseded this tx on that partition — its markers are
                    # moot; complete as aborted so the 1 Hz loop terminates
                    # instead of re-driving a dead tx forever.
                    logger.warning(
                        "tx %s: fenced during re-drive (errc %d); "
                        "completing as aborted", md.tx_id, code,
                    )
                    md.partitions.clear()
                    md.staged_offsets.clear()
                    md.state = TxState.complete_abort
                    md.last_update = time.monotonic()
                    self._persist_tx(md)
                    return E.none
                return E(code)  # epoch fence: not retriable, must re-init
        if failed:
            return E.coordinator_not_available  # retriable; state stays prepare_*
        # 2. staged group offsets become visible only on commit
        #    (group_commit_tx / group_abort_tx batches in the reference),
        #    routed to the group coordinator node
        if commit:
            for group_id, commits in md.staged_offsets.items():
                if commits:
                    try:
                        code = await self.router.commit_group_offsets(
                            group_id, commits
                        )
                    except Exception:
                        logger.exception(
                            "tx %s: offset fold failed for group %s",
                            md.tx_id, group_id,
                        )
                        return E.coordinator_not_available
                    if code != 0:
                        return E.coordinator_not_available
        md.partitions.clear()
        md.staged_offsets.clear()
        md.state = TxState.complete_commit if commit else TxState.complete_abort
        md.last_update = time.monotonic()
        self._persist_tx(md)
        return E.none

    async def expire_stale(self) -> None:
        """Abort timed-out transactions AND re-drive interrupted finishes
        (tm_stm expiry + re-drive). A tx stuck in prepare_* — the client
        gave up while a remote partition leader was down — pins every begun
        partition's LSO until its markers land; the coordinator, not the
        client, owns completing it."""
        now = time.monotonic()
        for md in list(self._txs.values()):
            if (
                md.state == TxState.ongoing
                and now - md.last_update > md.timeout_ms / 1000.0
            ):
                logger.info("aborting expired tx %s", md.tx_id)
                await self._finish(md, commit=False)
            elif md.state in (TxState.prepare_commit, TxState.prepare_abort):
                # exponential backoff (1s..60s): a partition that stays
                # unreachable shouldn't be hammered at 1 Hz forever; the
                # per-tx finish_lock keeps this from overlapping a client
                # retry, and skip entirely while one is in flight
                if md.finish_lock.locked() or now < md.next_redrive:
                    continue
                code = await self._finish(
                    md, commit=md.state == TxState.prepare_commit, redrive=True
                )
                if code == E.none:
                    logger.info("re-drove interrupted tx %s", md.tx_id)
                    md.redrive_attempts = 0
                else:
                    md.redrive_attempts += 1
                    md.next_redrive = time.monotonic() + min(
                        2.0 ** md.redrive_attempts, 60.0
                    )
