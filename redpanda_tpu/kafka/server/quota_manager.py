"""Client quotas: per-client-id token buckets -> throttle_time_ms.

Parity with kafka/server/quota_manager.h: the reference tracks per-client
produce/fetch byte rates and tells clients to back off via the
throttle_time_ms field every Kafka response carries. Token buckets refill
continuously; when a client overdraws, the deficit converts into the
throttle duration. Idle clients are garbage-collected.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class _Bucket:
    rate: float  # bytes/s
    burst: float  # bucket capacity
    tokens: float = 0.0
    last_refill: float = field(default_factory=time.monotonic)

    def __post_init__(self):
        self.tokens = self.burst

    def record(self, n: int) -> float:
        """Consume n bytes; returns throttle seconds (0 when within rate)."""
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens + (now - self.last_refill) * self.rate)
        self.last_refill = now
        self.tokens -= n
        if self.tokens >= 0:
            return 0.0
        return -self.tokens / self.rate


class QuotaManager:
    """quota_manager.h equivalent over client-id keyed buckets."""

    MAX_THROTTLE_MS = 30_000
    GC_AGE_S = 120.0

    def __init__(
        self,
        *,
        produce_rate: int | None = None,  # bytes/s per client, None = unlimited
        fetch_rate: int | None = None,
        burst_seconds: float = 1.0,
    ):
        self.produce_rate = produce_rate
        self.fetch_rate = fetch_rate
        self.burst_seconds = burst_seconds
        self._produce: dict[str, _Bucket] = {}
        self._fetch: dict[str, _Bucket] = {}
        self._last_gc = time.monotonic()

    def _bucket(self, table: dict, client_id: str, rate: int) -> _Bucket:
        b = table.get(client_id)
        if b is None or b.rate != rate:
            b = table[client_id] = _Bucket(rate=rate, burst=rate * self.burst_seconds)
        return b

    def record_produce(self, client_id: str | None, n_bytes: int) -> int:
        """Returns throttle_time_ms for the produce response."""
        if self.produce_rate is None:
            return 0
        b = self._bucket(self._produce, client_id or "", self.produce_rate)
        self._maybe_gc()
        return min(int(b.record(n_bytes) * 1000), self.MAX_THROTTLE_MS)

    def record_fetch(self, client_id: str | None, n_bytes: int) -> int:
        if self.fetch_rate is None:
            return 0
        b = self._bucket(self._fetch, client_id or "", self.fetch_rate)
        self._maybe_gc()
        return min(int(b.record(n_bytes) * 1000), self.MAX_THROTTLE_MS)

    def _maybe_gc(self) -> None:
        now = time.monotonic()
        if now - self._last_gc < self.GC_AGE_S:
            return
        self._last_gc = now
        for table in (self._produce, self._fetch):
            stale = [k for k, b in table.items() if now - b.last_refill > self.GC_AGE_S]
            for k in stale:
                del table[k]
