"""Queue-depth latency control (qdc).

Parity with the reference's kafka queue-depth monitor (qdc wiring in
application.cc:1002-1016, `kafka_qdc_*` configuration): an AIMD controller
bounds how many requests may execute concurrently server-wide so observed
handler latency tracks a target. When the latency EWMA runs past the
target the window shrinks multiplicatively (shedding queue depth is the
only way an overloaded broker can bound tail latency); while latency is
healthy the window creeps back up additively. Disabled by default, like
the reference's kafka_qdc_enable.
"""

from __future__ import annotations

import asyncio
import time


class QdcMonitor:
    def __init__(
        self,
        *,
        enabled: bool = False,
        target_latency_ms: float = 80.0,
        window_s: float = 1.0,
        min_depth: int = 1,
        max_depth: int = 100,
        alpha: float = 0.2,
        decrease_factor: float = 0.8,
    ) -> None:
        self.enabled = enabled
        self.target_latency_ms = target_latency_ms
        self.window_s = window_s
        self.min_depth = min_depth
        self.max_depth = max_depth
        self.alpha = alpha
        self.decrease_factor = decrease_factor
        self.depth = max_depth  # optimistic start; AIMD finds the level
        self.inflight = 0
        self.ewma_ms = 0.0
        self._cond = asyncio.Condition()
        self._window_started = time.monotonic()

    async def acquire(self) -> None:
        if not self.enabled:
            return
        async with self._cond:
            while self.inflight >= self.depth:
                await self._cond.wait()
            self.inflight += 1

    async def release(self, latency_s: float) -> None:
        if not self.enabled:
            return
        lat_ms = latency_s * 1e3
        self.ewma_ms = (
            lat_ms
            if self.ewma_ms == 0.0
            else self.alpha * lat_ms + (1 - self.alpha) * self.ewma_ms
        )
        now = time.monotonic()
        if now - self._window_started >= self.window_s:
            self._window_started = now
            if self.ewma_ms > self.target_latency_ms:
                self.depth = max(self.min_depth, int(self.depth * self.decrease_factor))
            else:
                self.depth = min(self.max_depth, self.depth + 1)
        async with self._cond:
            self.inflight = max(0, self.inflight - 1)
            self._cond.notify_all()

    def stats(self) -> dict[str, float]:
        return {
            "depth": self.depth,
            "inflight": self.inflight,
            "ewma_ms": round(self.ewma_ms, 3),
        }
