"""Kafka transaction API handlers.

Parity with kafka/server/handlers/{init_producer_id, add_partitions_to_txn,
add_offsets_to_txn, end_txn, txn_offset_commit}.cc, dispatching into the
broker's TxCoordinator (tm_stm + tx_gateway_frontend + id_allocator).
"""

from __future__ import annotations

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.errors import ErrorCode as E
from redpanda_tpu.kafka.server.group import OffsetCommit
from redpanda_tpu.kafka.server.security_handlers import authorize
from redpanda_tpu.security.acl import AclOperation, ResourceType


def _txn_authorized(ctx, tx_id: str | None) -> bool:
    if not tx_id:
        # plain idempotence needs IDEMPOTENT_WRITE on the cluster
        from redpanda_tpu.security.acl import DEFAULT_CLUSTER_NAME

        return authorize(
            ctx, ResourceType.cluster, DEFAULT_CLUSTER_NAME, AclOperation.idempotent_write
        )
    return authorize(ctx, ResourceType.transactional_id, tx_id, AclOperation.write)


async def handle_init_producer_id(ctx) -> dict:
    r = ctx.request
    tx_id = r.get("transactional_id")
    if not _txn_authorized(ctx, tx_id):
        code = (
            E.transactional_id_authorization_failed
            if tx_id
            else E.cluster_authorization_failed
        )
        return {"throttle_time_ms": 0, "error_code": int(code),
                "producer_id": -1, "producer_epoch": -1}
    timeout = r.get("transaction_timeout_ms", 60_000)
    if tx_id and timeout <= 0:
        return {"throttle_time_ms": 0, "error_code": int(E.invalid_transaction_timeout),
                "producer_id": -1, "producer_epoch": -1}
    code, pid, epoch = await ctx.broker.tx_coordinator.init_producer_id(tx_id, timeout)
    return {"throttle_time_ms": 0, "error_code": int(code),
            "producer_id": pid, "producer_epoch": epoch}


async def handle_add_partitions_to_txn(ctx) -> dict:
    r = ctx.request
    parts = [(t["name"], p) for t in r["topics"] for p in t["partitions"]]
    if not _txn_authorized(ctx, r["transactional_id"]):
        results = {tp: E.transactional_id_authorization_failed for tp in parts}
    else:
        results = {}
        allowed = []
        for topic, p in parts:
            if not authorize(ctx, ResourceType.topic, topic, AclOperation.write):
                results[(topic, p)] = E.topic_authorization_failed
            else:
                allowed.append((topic, p))
        results.update(
            await ctx.broker.tx_coordinator.add_partitions(
                r["transactional_id"], r["producer_id"], r["producer_epoch"], allowed
            )
        )
    return {
        "throttle_time_ms": 0,
        "results": [
            {
                "name": t["name"],
                "results": [
                    {"partition_index": p, "error_code": int(results.get((t["name"], p), E.none))}
                    for p in t["partitions"]
                ],
            }
            for t in r["topics"]
        ],
    }


async def handle_add_offsets_to_txn(ctx) -> dict:
    r = ctx.request
    if not _txn_authorized(ctx, r["transactional_id"]):
        return {"throttle_time_ms": 0, "error_code": int(E.transactional_id_authorization_failed)}
    if not authorize(ctx, ResourceType.group, r["group_id"], AclOperation.read):
        return {"throttle_time_ms": 0, "error_code": int(E.group_authorization_failed)}
    code = await ctx.broker.tx_coordinator.add_offsets(
        r["transactional_id"], r["producer_id"], r["producer_epoch"], r["group_id"]
    )
    return {"throttle_time_ms": 0, "error_code": int(code)}


async def handle_txn_offset_commit(ctx) -> dict:
    r = ctx.request
    commits: dict[tuple[str, int], OffsetCommit] = {}
    for t in r.get("topics") or []:
        for p in t["partitions"]:
            commits[(t["name"], p["partition_index"])] = OffsetCommit(
                p["committed_offset"], p.get("committed_leader_epoch", -1),
                p.get("committed_metadata"),
            )
    if not _txn_authorized(ctx, r["transactional_id"]):
        code = E.transactional_id_authorization_failed
    elif not authorize(ctx, ResourceType.group, r["group_id"], AclOperation.read):
        code = E.group_authorization_failed
    else:
        code = await ctx.broker.tx_coordinator.txn_offset_commit(
            r["transactional_id"], r["producer_id"], r["producer_epoch"],
            r["group_id"], commits,
        )
    return {
        "throttle_time_ms": 0,
        "topics": [
            {
                "name": t["name"],
                "partitions": [
                    {"partition_index": p["partition_index"], "error_code": int(code)}
                    for p in t["partitions"]
                ],
            }
            for t in r.get("topics") or []
        ],
    }


async def handle_end_txn(ctx) -> dict:
    r = ctx.request
    if not _txn_authorized(ctx, r["transactional_id"]):
        return {"throttle_time_ms": 0, "error_code": int(E.transactional_id_authorization_failed)}
    code = await ctx.broker.tx_coordinator.end_txn(
        r["transactional_id"], r["producer_id"], r["producer_epoch"], r["committed"]
    )
    return {"throttle_time_ms": 0, "error_code": int(code)}


def register_tx_handlers(handlers: dict) -> None:
    handlers[m.INIT_PRODUCER_ID] = handle_init_producer_id
    handlers[m.ADD_PARTITIONS_TO_TXN] = handle_add_partitions_to_txn
    handlers[m.ADD_OFFSETS_TO_TXN] = handle_add_offsets_to_txn
    handlers[m.TXN_OFFSET_COMMIT] = handle_txn_offset_commit
    handlers[m.END_TXN] = handle_end_txn
