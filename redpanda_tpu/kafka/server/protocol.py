"""Kafka protocol server loop.

Parity with kafka::protocol + connection_context (kafka/server/protocol.cc:81
apply loop; connection_context.cc:32 process_one_request, :215
dispatch_method_once): size-prefixed frames, per-connection **staged
pipelining** — each request's handler runs as its own task so handlers
overlap, while a writer fiber drains responses strictly in request order —
with pipeline depth bounded per connection (the reference gates on
size-based memory units; here the response queue is bounded, so one
connection can hold at most MAX_PIPELINE frames in flight).
"""

from __future__ import annotations

import asyncio
import logging
import struct

from redpanda_tpu.kafka.protocol.errors import ErrorCode, KafkaError
from redpanda_tpu.kafka.protocol.messages import (
    API_VERSIONS,
    APIS,
    FETCH,
    JOIN_GROUP,
    PRODUCE,
    SASL_AUTHENTICATE,
    SASL_HANDSHAKE,
    SYNC_GROUP,
)
from redpanda_tpu.kafka.protocol.primitives import Reader
from redpanda_tpu.kafka.protocol.schema import (
    RequestHeader,
    decode_message,
    encode_message,
    encode_response_header,
)

logger = logging.getLogger("rptpu.kafka")

MAX_REQUEST_SIZE = 100 * 1024 * 1024
MAX_PIPELINE = 64  # max in-flight requests per connection

# HDR latency probes for the two hot APIs (kafka/latency_probe.h:33-43:
# the reference histograms produce and fetch specifically), exported at
# /metrics with cumulative buckets + sum/count for quantile queries.
# Defined once in observability/probes.py; recorded ONLY here at the
# dispatch layer so decode/encode are covered and nothing double-counts.
from redpanda_tpu.observability.probes import (  # noqa: E402
    kafka_fetch_hist as _fetch_latency,
    kafka_produce_hist as _produce_latency,
    record_us as _record_us,
)


class RequestContext:
    """Per-request context handed to handlers (kafka::request_context)."""

    __slots__ = ("broker", "header", "request", "connection", "trace_id")

    def __init__(self, broker, header: RequestHeader, request: dict, connection):
        self.broker = broker
        self.header = header
        self.request = request
        self.connection = connection
        # stamped by the handler's root span (handlers.handle_produce/
        # handle_fetch): the dispatch layer records the latency histogram
        # AFTER the span closed, so exemplar capture needs the id carried
        # out-of-band (observability/probes.py trace exemplars)
        self.trace_id = None

    @property
    def api_version(self) -> int:
        return self.header.api_version


class Connection:
    def __init__(self, server: "KafkaServer", reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.sasl_state = None  # set by the sasl handlers
        self.authenticated_principal: str | None = None
        peer = writer.get_extra_info("peername")
        self.client_host: str = peer[0] if peer else "*"
        # Bounded: `await put` backpressures the read loop once MAX_PIPELINE
        # requests are in flight on this connection.
        self._responses: asyncio.Queue[asyncio.Task | None] = asyncio.Queue(maxsize=MAX_PIPELINE)
        self._handler_tasks: set[asyncio.Task] = set()
        # memory-gate reservations held by in-flight requests
        self._reserved: dict[object, int] = {}

    async def run(self) -> None:
        writer_task = asyncio.create_task(self._drain_responses())
        cancelled = False
        try:
            while True:
                frame, reserved = await self._read_frame()
                if frame is None:
                    break
                # Staged pipelining: decode synchronously here so wire order
                # and the sasl state machine are preserved, then dispatch the
                # handler as a task so handlers overlap while the writer
                # fiber drains responses strictly in request order.
                decoded = self._decode_frame(frame)
                if decoded is None:
                    self._release(reserved)
                    break  # fatal protocol error: close the connection
                if isinstance(decoded, bytes):
                    done: asyncio.Future = asyncio.get_running_loop().create_future()
                    done.set_result(decoded)
                    self._reserved[done] = reserved
                    await self._responses.put(done)
                else:
                    task = asyncio.create_task(self._dispatch(*decoded))
                    self._handler_tasks.add(task)
                    task.add_done_callback(self._handler_tasks.discard)
                    self._reserved[task] = reserved
                    await self._responses.put(task)
        except asyncio.CancelledError:
            cancelled = True
            raise
        finally:
            if cancelled:
                # Server shutdown: stop in-flight handlers (they may be
                # long-polling fetches) before tearing down the writer.
                for t in list(self._handler_tasks):
                    t.cancel()
                writer_task.cancel()
            else:
                # Normal close: let queued handlers finish and drain.
                self._responses.put_nowait(None)
                await writer_task
            if self._handler_tasks:
                await asyncio.gather(*self._handler_tasks, return_exceptions=True)
            for reserved in self._reserved.values():
                self._release(reserved)
            self._reserved.clear()
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionError, OSError, asyncio.CancelledError):
                pass

    async def _read_frame(self) -> tuple[bytes | None, int]:
        try:
            size_buf = await self.reader.readexactly(4)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None, 0
        (size,) = struct.unpack(">i", size_buf)
        if size < 0 or size > MAX_REQUEST_SIZE:
            raise ValueError(f"invalid frame size {size}")
        # Memory gate (connection_context.cc:32): reserve the frame size
        # BEFORE reading the body; a flood of large requests backpressures
        # here instead of ballooning the heap. Released when the response
        # drains (or the connection dies).
        reserved = await self.server.memory.acquire(size)
        try:
            frame = await self.reader.readexactly(size)
        except (asyncio.IncompleteReadError, ConnectionError):
            self._release(reserved)
            return None, 0
        except BaseException:
            # Cancellation (connection teardown racing a slow body read)
            # must give the bytes back: this reservation is not yet in
            # self._reserved, so the close path can't see it.
            self._release(reserved)
            raise
        return frame, reserved

    def _release(self, reserved: int) -> None:
        if reserved:
            self.server.memory.release(reserved)

    def _decode_frame(self, frame: bytes):
        """Synchronous decode: returns a prebuilt error response (bytes) or
        (header, api, request) for dispatch."""
        r = Reader(frame)
        header = RequestHeader.decode(r, flexible=False)
        api = APIS.get(header.api_key)
        # Range-check BEFORE the flexible re-decode: an out-of-range version
        # (e.g. a KIP-511 ApiVersions probe from the future) may not carry
        # the tagged-field header byte our flexible table would expect, and
        # the v0 error response only needs the fixed-offset correlation id.
        if api is None or not (api.min_version <= header.api_version <= api.max_version):
            return self._unsupported_version_response(header)
        if api.is_flexible(header.api_version):
            # re-decode with the flexible header (v2: + tagged fields)
            r = Reader(frame)
            header = RequestHeader.decode(r, flexible=True)
        if self.server.handlers.get(header.api_key) is None:
            return self._unsupported_version_response(header)
        try:
            request = decode_message(api, "request", frame[r.pos :], header.api_version)
        except Exception:
            # A frame we can't parse at a version we claim to support is a
            # broken client; close rather than answer with garbage.
            logger.exception("decode failed for %s v%d", api.name, header.api_version)
            return None
        return header, api, request

    async def _dispatch(self, header: RequestHeader, api, request: dict) -> bytes | None:
        ctx = RequestContext(self.server.broker, header, request, self)
        handler = self.server.handlers[header.api_key]
        # SASL gate: with authentication enabled, only the handshake dance
        # and ApiVersions may run unauthenticated (requests.cc:99-160).
        if (
            getattr(self.server.broker, "sasl_enabled", False)
            and self.authenticated_principal is None
            and header.api_key not in (API_VERSIONS, SASL_HANDSHAKE, SASL_AUTHENTICATE)
        ):
            resp = self.server.error_response(
                api, header.api_version, ctx, ErrorCode.sasl_authentication_failed
            )
            if resp:
                return self._encode_response(header, api, resp)
            # No expressible error shape for this API (no error_code field,
            # no maker): a success-shaped empty body would read as a healthy
            # empty cluster, so close the connection like real brokers do.
            logger.warning(
                "closing unauthenticated connection on api %s", api.name
            )
            self.writer.close()
            return None
        # qdc gate: bound concurrent execution so latency tracks the target
        # (no-op unless kafka_qdc_enable). APIs that PARK inside their
        # handler are exempt — a long-poll fetch waits for data and a
        # join/sync waits for the rest of the group, not queue pressure;
        # gating them would let one parked request hold the window's slots
        # and starve produces (or deadlock a rebalance at depth 1), while
        # their multi-second waits would poison the latency EWMA.
        gated = header.api_key not in (FETCH, JOIN_GROUP, SYNC_GROUP)
        # t0 BEFORE acquire: the HISTOGRAMS must include queue-wait, or an
        # overloaded-but-queueing broker reads as healthy to operators.
        # The qdc control signal is sampled from t_svc (AFTER acquire):
        # feeding queue-wait back into the controller would make the
        # measured latency depend inversely on the depth being controlled —
        # a positive feedback loop that pins depth at the floor.
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        if gated:
            await self.server.qdc.acquire()
        t_svc = loop.time()
        try:
            response = await handler(ctx)
        except KafkaError as e:
            response = self.server.error_response(api, header.api_version, ctx, e.code)
        except Exception:
            logger.exception("handler %s failed", api.name)
            response = self.server.error_response(
                api, header.api_version, ctx, ErrorCode.unknown_server_error
            )
        finally:
            if gated:
                await self.server.qdc.release(loop.time() - t_svc)
        # exemplar-aware record: over-threshold observations keep the
        # request's trace id so an SLO breach links to /v1/trace/slow.
        # Fetch records WITHOUT a trace id on purpose: its root span is
        # no_slow (a long poll's duration is intentional waiting, never in
        # the slow ring), so a fetch exemplar could only ever be a dead
        # link — fetch objectives are judged on their error budget instead.
        if header.api_key == PRODUCE:
            _record_us(
                _produce_latency, int((loop.time() - t0) * 1e6),
                trace_id=ctx.trace_id,
            )
        elif header.api_key == FETCH:
            _fetch_latency.record(int((loop.time() - t0) * 1e6))
        return self._encode_response(header, api, response)

    def _encode_response(self, header: RequestHeader, api, response: dict | None) -> bytes | None:
        if response is None:
            return None  # e.g. acks=0 produce: no response on the wire
        # ApiVersions responses always use the v0 response header.
        flexible_hdr = api.is_flexible(header.api_version) and header.api_key != API_VERSIONS
        body = encode_message(api, "response", response, header.api_version)
        return encode_response_header(header.correlation_id, flexible_hdr) + body

    def _unsupported_version_response(self, header: RequestHeader) -> bytes | None:
        """Per KIP-511, an unsupported ApiVersions request gets a v0 response
        with the supported ranges so the client downgrades. For any other API
        we cannot encode a response the client will parse at its requested
        version, so close the connection (what real brokers do) by returning
        the close sentinel."""
        if header.api_key == API_VERSIONS:
            api = APIS.get(API_VERSIONS)
            body = encode_message(
                api,
                "response",
                {
                    "error_code": int(ErrorCode.unsupported_version),
                    "api_keys": [
                        {
                            "api_key": a.key,
                            "min_version": a.min_version,
                            "max_version": a.max_version,
                        }
                        for a in sorted(APIS.values(), key=lambda a: a.key)
                    ],
                    "throttle_time_ms": 0,
                },
                0,
            )
            return encode_response_header(header.correlation_id, False) + body
        logger.warning(
            "unsupported api key %d v%d from client; closing connection",
            header.api_key,
            header.api_version,
        )
        return None

    async def _drain_responses(self) -> None:
        while True:
            task = await self._responses.get()
            if task is None:
                return
            try:
                payload = await task
            except asyncio.CancelledError:
                if isinstance(task, asyncio.Task) and task.cancelled():
                    self._release(self._reserved.pop(task, 0))
                    continue  # the handler was cancelled, not this fiber
                raise
            except Exception:
                logger.exception("response task failed")
                self._release(self._reserved.pop(task, 0))
                continue
            self._release(self._reserved.pop(task, 0))
            if payload is None:
                continue
            try:
                self.writer.write(struct.pack(">i", len(payload)) + payload)
                await self.writer.drain()
            except (ConnectionError, OSError):
                return


class KafkaServer:
    """Accept loop + handler registry (rpc::server with kafka::protocol)."""

    def __init__(self, broker, host: str = "127.0.0.1", port: int = 9092, tls=None):
        from redpanda_tpu.kafka.server import handlers as h
        from redpanda_tpu.kafka.server import security_handlers as sh

        self.broker = broker
        self.host = host
        self.port = port
        self.tls = tls  # security.tls.ReloadableTlsContext | None
        self.handlers = h.build_dispatch_table()
        sh.register_security_handlers(self.handlers)
        from redpanda_tpu.kafka.server import group_handlers as gh
        from redpanda_tpu.kafka.server import tx_handlers as th

        gh.register_group_handlers(self.handlers)
        th.register_tx_handlers(self.handlers)
        from redpanda_tpu.coproc import leakwatch
        from redpanda_tpu.resource_mgmt import MemoryBudget

        # leakwatch: the request-memory budget is THE account the
        # _read_frame cancellation path reserves from — with
        # coproc_leakwatch on, a torn connection leaking its frame
        # reservation shows up as nonzero outstanding balance
        self.memory = leakwatch.wrap(
            MemoryBudget(broker.config.kafka_request_max_memory),
            "kafka.request_memory",
        )
        from redpanda_tpu.kafka.server.qdc import QdcMonitor

        cfg = broker.config
        self.qdc = QdcMonitor(
            enabled=cfg.kafka_qdc_enable,
            target_latency_ms=cfg.kafka_qdc_max_latency_ms,
            window_s=cfg.kafka_qdc_window_s,
            min_depth=cfg.kafka_qdc_min_depth,
            max_depth=cfg.kafka_qdc_max_depth,
        )
        self._server: asyncio.AbstractServer | None = None
        self._conn_tasks: set[asyncio.Task] = set()

    async def start(self) -> "KafkaServer":
        # single-node mode: rediscover topics from disk before serving
        # (cluster mode repopulates the table via controller replay instead)
        if getattr(self.broker, "controller_dispatcher", None) is None:
            await self.broker.recover_topics()
        tx = getattr(self.broker, "tx_coordinator", None)
        if tx is not None:
            tx.start_expiry()
        ssl_ctx = self.tls.server_context if self.tls is not None else None
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, ssl=ssl_ctx
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logger.info("kafka api listening on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Force-close surviving connections rather than waiting: 3.12's
            # Server.wait_closed() blocks until every handler returns, which
            # would hang on clients that keep their sockets open.
            for task in list(self._conn_tasks):
                task.cancel()
            if self._conn_tasks:
                await asyncio.gather(*self._conn_tasks, return_exceptions=True)
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
        # AFTER connections are torn down: an in-flight group request could
        # otherwise restart the manager and leak its expiry fiber
        gm = getattr(self.broker, "group_coordinator", None)
        if gm is not None:
            await gm.stop()
        tx = getattr(self.broker, "tx_coordinator", None)
        if tx is not None:
            await tx.stop()

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        conn = Connection(self, reader, writer)
        try:
            await conn.run()
        except asyncio.CancelledError:
            try:
                writer.close()
            except Exception:
                pass
        except Exception:
            logger.exception("connection failed")
            try:
                writer.close()
            except Exception:
                pass
        finally:
            self._conn_tasks.discard(task)

    # ------------------------------------------------------------ errors
    def error_response(self, api, version: int, ctx: RequestContext, code: ErrorCode) -> dict:
        """Best-effort structured error response echoing request topology."""
        from redpanda_tpu.kafka.server import handlers as h

        maker = h.ERROR_RESPONSE_MAKERS.get(api.key)
        if maker is not None:
            return maker(ctx, code)
        return self.minimal_error_body(api, code)

    @staticmethod
    def minimal_error_body(api, code: ErrorCode) -> dict:
        body: dict = {}
        for f in api.response:
            if f.name == "error_code":
                body[f.name] = int(code)
        return body
