"""Consumer group state machine.

Parity with kafka/server/group.h + group.cc (2,254 LoC in the reference):
states {Empty, PreparingRebalance, CompletingRebalance, Stable, Dead}, the
join/sync rebalance barrier with deferred responses, heartbeat-driven
liveness, protocol selection, and the per-group committed-offset map.
Persistence hooks (group metadata + offset commits into the group topic)
are injected by the GroupManager so this stays a pure state machine.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
import uuid
from dataclasses import dataclass, field

from redpanda_tpu.kafka.protocol.errors import ErrorCode as E

logger = logging.getLogger("rptpu.kafka.group")


class GroupState(enum.Enum):
    empty = "Empty"
    preparing_rebalance = "PreparingRebalance"
    completing_rebalance = "CompletingRebalance"
    stable = "Stable"
    dead = "Dead"


@dataclass
class Member:
    member_id: str
    group_instance_id: str | None
    client_id: str
    client_host: str
    session_timeout_ms: int
    rebalance_timeout_ms: int
    protocol_type: str
    protocols: list[tuple[str, bytes]]
    assignment: bytes = b""
    last_heartbeat: float = field(default_factory=time.monotonic)
    # deferred response futures (group.cc join/sync response callbacks)
    join_future: asyncio.Future | None = None
    sync_future: asyncio.Future | None = None

    def protocol_names(self) -> set[str]:
        return {name for name, _ in self.protocols}

    def metadata_for(self, protocol: str) -> bytes:
        for name, md in self.protocols:
            if name == protocol:
                return md
        return b""


@dataclass
class OffsetCommit:
    offset: int
    leader_epoch: int = -1
    metadata: str | None = None
    commit_ts: float = field(default_factory=time.time)


class Group:
    def __init__(
        self, group_id: str, on_change=None, initial_rebalance_delay_s: float = 0.2
    ) -> None:
        """initial_rebalance_delay_s mirrors group.initial.rebalance.delay.ms
        (3s in upstream kafka, shortened here): a brand-new group lingers in
        PreparingRebalance so a burst of founding members lands in one
        generation instead of N."""
        self.group_id = group_id
        self.initial_rebalance_delay_s = initial_rebalance_delay_s
        self.state = GroupState.empty
        self.generation = 0
        self.protocol_type: str | None = None
        self.protocol: str | None = None
        self.leader: str | None = None
        self.members: dict[str, Member] = {}
        self.offsets: dict[tuple[str, int], OffsetCommit] = {}
        self._rebalance_task: asyncio.Task | None = None
        self._on_change = on_change  # async callable(group) -> persist hook
        # members that joined the CURRENT rebalance round
        self._joined: set[str] = set()

    # ------------------------------------------------------------ helpers
    def _new_member_id(self, client_id: str) -> str:
        return f"{client_id or 'member'}-{uuid.uuid4()}"

    def in_states(self, *states: GroupState) -> bool:
        return self.state in states

    def _select_protocol(self) -> str:
        """Pick the protocol every member supports (vote by join order)."""
        if not self.members:
            return ""
        common = set.intersection(*(m.protocol_names() for m in self.members.values()))
        if not common:
            return ""
        # first listed preference of the leader-ish first member that's common
        for name, _ in next(iter(self.members.values())).protocols:
            if name in common:
                return name
        return sorted(common)[0]

    async def _notify_change(self) -> None:
        if self._on_change is not None:
            try:
                await self._on_change(self)
            except Exception:
                logger.exception("group %s persistence hook failed", self.group_id)

    # ------------------------------------------------------------ join
    async def join(
        self,
        member_id: str,
        group_instance_id: str | None,
        client_id: str,
        client_host: str,
        session_timeout_ms: int,
        rebalance_timeout_ms: int,
        protocol_type: str,
        protocols: list[tuple[str, bytes]],
    ) -> dict:
        if self.state == GroupState.dead:
            return self._join_error(member_id, E.coordinator_not_available)
        if self.protocol_type is not None and self.members and protocol_type != self.protocol_type:
            return self._join_error(member_id, E.inconsistent_group_protocol)
        if member_id and member_id not in self.members:
            return self._join_error(member_id, E.unknown_member_id)

        if not member_id:
            member_id = self._new_member_id(client_id)
            member = Member(
                member_id, group_instance_id, client_id, client_host,
                session_timeout_ms, rebalance_timeout_ms if rebalance_timeout_ms > 0 else session_timeout_ms,
                protocol_type, protocols,
            )
            self.members[member_id] = member
            self.protocol_type = protocol_type
        else:
            member = self.members[member_id]
            member.protocols = protocols
            member.session_timeout_ms = session_timeout_ms
            member.rebalance_timeout_ms = (
                rebalance_timeout_ms if rebalance_timeout_ms > 0 else session_timeout_ms
            )
            member.last_heartbeat = time.monotonic()

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        old = member.join_future
        if old is not None and not old.done():
            # superseded join (client retried): answer the old one
            old.set_result(self._join_error(member_id, E.unknown_member_id))
        member.join_future = fut
        self._joined.add(member_id)

        self._prepare_rebalance()
        return await fut

    def _join_error(self, member_id: str, code: E) -> dict:
        return {
            "error_code": int(code),
            "generation_id": -1,
            "protocol_name": "",
            "leader": "",
            "member_id": member_id,
            "members": [],
        }

    def _prepare_rebalance(self) -> None:
        if self.state == GroupState.preparing_rebalance:
            self._maybe_complete_join()
            return
        self.state = GroupState.preparing_rebalance
        # kick pending syncs back to re-join (rebalance interrupts them)
        for m in self.members.values():
            if m.sync_future is not None and not m.sync_future.done():
                m.sync_future.set_result(
                    {"error_code": int(E.rebalance_in_progress), "assignment": b""}
                )
                m.sync_future = None
        if self._rebalance_task is None or self._rebalance_task.done():
            self._rebalance_task = asyncio.create_task(self._rebalance_timer())
        self._maybe_complete_join()

    def _rebalance_timeout_s(self) -> float:
        if not self.members:
            return 0.3
        return max(m.rebalance_timeout_ms for m in self.members.values()) / 1000.0

    async def _rebalance_timer(self) -> None:
        """Completes the join phase when every member rejoined or the
        rebalance timeout expires (whichever first). New groups also wait
        out the initial rebalance delay."""
        now = time.monotonic()
        deadline = now + self._rebalance_timeout_s()
        earliest = now + (self.initial_rebalance_delay_s if self.generation == 0 else 0)
        try:
            while time.monotonic() < deadline:
                if self.state != GroupState.preparing_rebalance:
                    return
                if time.monotonic() >= earliest and self._all_joined():
                    break
                await asyncio.sleep(0.02)
            if self.state == GroupState.preparing_rebalance:
                self._complete_join(evict_stragglers=True)
        except asyncio.CancelledError:
            pass

    def _all_joined(self) -> bool:
        return bool(self.members) and all(
            m.join_future is not None and not m.join_future.done()
            for m in self.members.values()
        )

    def _maybe_complete_join(self) -> None:
        # brand-new groups (generation 0) ride out the initial rebalance
        # delay in the timer; established groups fast-complete on full rejoin
        if (
            self.state == GroupState.preparing_rebalance
            and self.generation > 0
            and self._all_joined()
        ):
            self._complete_join()

    def _complete_join(self, evict_stragglers: bool = False) -> None:
        if evict_stragglers:
            for mid in list(self.members):
                m = self.members[mid]
                if m.join_future is None or m.join_future.done():
                    del self.members[mid]
        if not self.members:
            self.state = GroupState.empty
            self.generation += 1
            self._joined.clear()
            return
        self.generation += 1
        self.protocol = self._select_protocol()
        if self.leader not in self.members:
            self.leader = next(iter(self.members))
        members_for_leader = [
            {
                "member_id": m.member_id,
                "group_instance_id": m.group_instance_id,
                "metadata": m.metadata_for(self.protocol),
            }
            for m in self.members.values()
        ]
        self.state = GroupState.completing_rebalance
        self._joined.clear()
        for m in self.members.values():
            fut, m.join_future = m.join_future, None
            if fut is None or fut.done():
                continue
            fut.set_result(
                {
                    "error_code": 0,
                    "generation_id": self.generation,
                    "protocol_name": self.protocol or "",
                    "leader": self.leader,
                    "member_id": m.member_id,
                    "members": members_for_leader if m.member_id == self.leader else [],
                }
            )

    # ------------------------------------------------------------ sync
    async def sync(
        self, member_id: str, generation_id: int, assignments: list[dict]
    ) -> dict:
        if self.state == GroupState.dead:
            return {"error_code": int(E.coordinator_not_available), "assignment": b""}
        if member_id not in self.members:
            return {"error_code": int(E.unknown_member_id), "assignment": b""}
        if generation_id != self.generation:
            return {"error_code": int(E.illegal_generation), "assignment": b""}
        if self.state == GroupState.preparing_rebalance:
            return {"error_code": int(E.rebalance_in_progress), "assignment": b""}
        member = self.members[member_id]
        member.last_heartbeat = time.monotonic()
        if self.state == GroupState.stable:
            return {"error_code": 0, "assignment": member.assignment}
        # completing_rebalance: leader's sync distributes the assignments
        if member_id == self.leader:
            by_member = {a["member_id"]: a["assignment"] for a in assignments}
            for mid, m in self.members.items():
                m.assignment = by_member.get(mid, b"")
            self.state = GroupState.stable
            await self._notify_change()
            for m in self.members.values():
                if m.sync_future is not None and not m.sync_future.done():
                    m.sync_future.set_result(
                        {"error_code": 0, "assignment": m.assignment}
                    )
                    m.sync_future = None
            return {"error_code": 0, "assignment": member.assignment}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        member.sync_future = fut
        return await fut

    # ------------------------------------------------------------ heartbeat / leave
    def heartbeat(self, member_id: str, generation_id: int) -> E:
        if self.state == GroupState.dead:
            return E.coordinator_not_available
        if member_id not in self.members:
            return E.unknown_member_id
        if generation_id != self.generation:
            return E.illegal_generation
        self.members[member_id].last_heartbeat = time.monotonic()
        if self.state == GroupState.preparing_rebalance:
            return E.rebalance_in_progress
        return E.none

    async def leave(self, member_ids: list[str]) -> list[tuple[str, E]]:
        out = []
        changed = False
        for mid in member_ids:
            if mid in self.members:
                self._remove_member(mid)
                changed = True
                out.append((mid, E.none))
            else:
                out.append((mid, E.unknown_member_id))
        if changed:
            if self.members:
                self._prepare_rebalance()
            else:
                self.state = GroupState.empty
                await self._notify_change()
        return out

    def _remove_member(self, member_id: str) -> None:
        m = self.members.pop(member_id, None)
        if m is None:
            return
        for fut in (m.join_future, m.sync_future):
            if fut is not None and not fut.done():
                fut.set_result(
                    {"error_code": int(E.unknown_member_id), "assignment": b"",
                     "generation_id": -1, "protocol_name": "", "leader": "",
                     "member_id": member_id, "members": []}
                )

    def expire_members(self) -> bool:
        """Session-timeout eviction; True when membership changed."""
        now = time.monotonic()
        expired = [
            mid
            for mid, m in self.members.items()
            if (m.join_future is None or m.join_future.done())
            and now - m.last_heartbeat > m.session_timeout_ms / 1000.0
        ]
        for mid in expired:
            logger.info("group %s: member %s session timed out", self.group_id, mid)
            self._remove_member(mid)
        if expired:
            if self.members:
                self._prepare_rebalance()
            else:
                self.state = GroupState.empty
        return bool(expired)

    # ------------------------------------------------------------ offsets
    def commit_offsets(
        self,
        member_id: str,
        generation_id: int,
        commits: dict[tuple[str, int], OffsetCommit],
        *,
        trusted: bool = False,
    ) -> E:
        if self.state == GroupState.dead:
            return E.coordinator_not_available
        if member_id == "" and generation_id < 0:
            # Simple (non-group) offset storage: only allowed while the
            # group is Empty (the reference rejects generation<0 commits
            # against a live group, group.cc:1920) — otherwise a stray
            # non-member client could overwrite a stable group's offsets.
            # `trusted` is the internal path (tx coordinator applying
            # staged offsets at commit time), which bypasses the check.
            if trusted or self.state == GroupState.empty:
                self.offsets.update(commits)
                return E.none
            return E.illegal_generation
        if member_id not in self.members:
            return E.unknown_member_id
        if generation_id != self.generation:
            return E.illegal_generation
        if self.state == GroupState.completing_rebalance:
            return E.rebalance_in_progress
        self.members[member_id].last_heartbeat = time.monotonic()
        self.offsets.update(commits)
        return E.none

    def fetch_offset(self, topic: str, partition: int) -> OffsetCommit | None:
        return self.offsets.get((topic, partition))

    # ------------------------------------------------------------ admin views
    def can_delete(self) -> bool:
        return self.state in (GroupState.empty, GroupState.dead)

    def shutdown(self) -> None:
        self.state = GroupState.dead
        if self._rebalance_task is not None:
            self._rebalance_task.cancel()
        for mid in list(self.members):
            self._remove_member(mid)

    def describe(self) -> dict:
        return {
            "error_code": 0,
            "group_id": self.group_id,
            "group_state": self.state.value,
            "protocol_type": self.protocol_type or "",
            "protocol_data": self.protocol or "",
            "members": [
                {
                    "member_id": m.member_id,
                    # v4+ exposes static membership; the encoder drops the
                    # key below that version
                    "group_instance_id": m.group_instance_id,
                    "client_id": m.client_id,
                    "client_host": m.client_host,
                    "member_metadata": m.metadata_for(self.protocol or ""),
                    "member_assignment": m.assignment,
                }
                for m in self.members.values()
            ],
        }
