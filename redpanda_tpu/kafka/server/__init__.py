"""Kafka protocol server (parity with src/v/kafka/server)."""

from redpanda_tpu.kafka.server.protocol import KafkaServer

__all__ = ["KafkaServer"]
