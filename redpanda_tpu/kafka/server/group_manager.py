"""Group coordinator: manager + persistence + coordinator mapping.

Parity with kafka/server/group_manager.h:126-140 (attach/detach groups to
the group-metadata topic partitions, recovery on leadership), group_router
(shard routing by group → coordinator partition) and coordinator_ntp_mapper
(hash(group) % N over ``__consumer_offsets``). Group metadata and offset
commits are appended to the group topic partition the group maps to, and
recovered from it on startup — members are ephemeral (like the reference,
only offsets + group existence survive restart).

Record format (documented deviation: JSON values instead of the reference's
binary group-topic codec): key = {"t": "md"|"off"|"tomb", "g": group, ...},
value = payload.
"""

from __future__ import annotations

import asyncio
import json
import logging

from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.kafka.protocol.errors import ErrorCode as E
from redpanda_tpu.kafka.server.group import Group, GroupState, OffsetCommit
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import Record, RecordBatch
from redpanda_tpu.cluster.partition import ConsistencyLevel
from redpanda_tpu.cluster.topic_table import TopicConfig

logger = logging.getLogger("rptpu.kafka.group_mgr")

GROUP_TOPIC = "__consumer_offsets"


class GroupManager:
    def __init__(self, broker, n_partitions: int = 16, expire_interval_s: float = 1.0):
        self.broker = broker
        self.n_partitions = n_partitions
        self.expire_interval_s = expire_interval_s
        self.groups: dict[str, Group] = {}
        self._expire_task: asyncio.Task | None = None
        self._started = False
        self._start_lock = asyncio.Lock()
        # group-topic partitions whose failover replay is in flight (the
        # coordinator_load_in_progress window): idx -> generation token, so
        # an older replay finishing cannot reopen the gate a newer replay
        # (re-gained leadership) still holds. Strong refs keep tasks alive.
        self._loading: dict[int, object] = {}
        self._recover_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "GroupManager":
        async with self._start_lock:
            if self._started:
                return self
            if not self.broker.topic_table.contains(GROUP_TOPIC):
                try:
                    await self.broker.create_topic(
                        TopicConfig(
                            GROUP_TOPIC,
                            self.n_partitions,
                            self.broker.config.default_replication,
                            cleanup_policy="compact",
                        )
                    )
                except ValueError:
                    pass  # concurrent create
            # the topic may predate us (restart recovery, another node's
            # create): group→partition hashing must follow its REAL count or
            # most coordinator lookups point at nonexistent partitions
            md = self.broker.topic_table.get(GROUP_TOPIC)
            if md is not None:
                self.n_partitions = md.config.partition_count
            await self._recover()
            self._expire_task = asyncio.create_task(self._expire_loop())
            self._started = True
            return self

    async def stop(self) -> None:
        if self._expire_task is not None:
            self._expire_task.cancel()
            try:
                await self._expire_task
            except asyncio.CancelledError:
                pass
            self._expire_task = None
        for g in self.groups.values():
            g.shutdown()
        self.groups.clear()
        self._started = False

    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(self.expire_interval_s)
            for g in list(self.groups.values()):
                try:
                    if g.expire_members() and g.state == GroupState.empty:
                        await self._persist_group(g)
                except Exception:
                    logger.exception("expiry failed for group %s", g.group_id)

    # ------------------------------------------------------------ mapping
    def partition_for(self, group_id: str) -> int:
        return xxhash64(group_id.encode()) % self.n_partitions

    def coordinator_ntp(self, group_id: str) -> NTP:
        return NTP.kafka(GROUP_TOPIC, self.partition_for(group_id))

    def is_coordinator(self, group_id: str) -> bool:
        idx = self.partition_for(group_id)
        if idx in self._loading:
            # Failover replay in flight: serving group requests now would
            # expose empty state and let live commits interleave with the
            # replay (the reference's coordinator_load_in_progress window —
            # clients re-discover and retry on not_coordinator).
            return False
        p = self.broker.get_partition(GROUP_TOPIC, idx)
        return p is not None and p.is_leader()

    # ------------------------------------------------------------ groups
    async def get_or_create(self, group_id: str) -> Group | None:
        """None when this broker is not the group's coordinator."""
        await self.start()
        if not self.is_coordinator(group_id):
            return None
        g = self.groups.get(group_id)
        if g is None or g.state == GroupState.dead:
            g = Group(group_id, on_change=self._persist_group)
            self.groups[group_id] = g
        return g

    def get(self, group_id: str) -> Group | None:
        return self.groups.get(group_id)

    async def delete_group(self, group_id: str) -> E:
        g = self.groups.get(group_id)
        if g is None:
            return E.invalid_group_id if not self.is_coordinator(group_id) else E.group_id_not_found
        if not g.can_delete():
            return E.non_empty_group
        g.shutdown()
        del self.groups[group_id]
        await self._append(group_id, [
            Record(key=self._key("tomb", group_id), value=None)
        ])
        return E.none

    # ------------------------------------------------------------ offsets api
    async def commit_offsets(
        self, group_id: str, member_id: str, generation_id: int,
        commits: dict[tuple[str, int], OffsetCommit],
        *, trusted: bool = False,
    ) -> E:
        g = await self.get_or_create(group_id)
        if g is None:
            return E.not_coordinator
        code = g.commit_offsets(member_id, generation_id, commits, trusted=trusted)
        if code == E.none and commits:
            records = [
                Record(
                    offset_delta=i,
                    key=self._key("off", group_id, topic=t, partition=p),
                    value=json.dumps(
                        {"o": oc.offset, "e": oc.leader_epoch, "m": oc.metadata}
                    ).encode(),
                )
                for i, ((t, p), oc) in enumerate(commits.items())
            ]
            await self._append(group_id, records)
        return code

    # ------------------------------------------------------------ persistence
    def _key(self, t: str, group: str, topic: str | None = None, partition: int | None = None) -> bytes:
        k: dict = {"t": t, "g": group}
        if topic is not None:
            k["topic"], k["partition"] = topic, partition
        return json.dumps(k, separators=(",", ":")).encode()

    async def _persist_group(self, g: Group) -> None:
        md = {
            "protocol_type": g.protocol_type,
            "generation": g.generation,
            "protocol": g.protocol,
            "leader": g.leader,
            "state": g.state.value,
        }
        await self._append(
            g.group_id,
            [Record(key=self._key("md", g.group_id), value=json.dumps(md).encode())],
        )

    async def _append(self, group_id: str, records: list[Record]) -> None:
        p = self.broker.get_partition(GROUP_TOPIC, self.partition_for(group_id))
        if p is None or not p.is_leader():
            raise RuntimeError(f"not coordinator for {group_id}")
        batch = RecordBatch.build(records)
        await p.replicate([batch], ConsistencyLevel.quorum_ack)

    async def _recover(self) -> None:
        """Rebuild group existence + offsets from the group topic
        (group_manager recovery on coordinator leadership)."""
        md = self.broker.topic_table.get(GROUP_TOPIC)
        if md is None:
            return
        for idx in md.assignments:
            await self.recover_partition(idx)
        if self.groups:
            logger.info("recovered %d groups", len(self.groups))

    async def recover_partition(self, idx: int) -> None:
        """Replay one group-topic partition into coordinator state.

        Called at start for every local partition AND whenever this node
        GAINS leadership of a group partition (the reference's
        group_manager handle_leader_change -> recovery, group_manager.cc):
        after a coordinator dies, the new leader must rebuild that
        partition's groups/offsets from the replicated log or committed
        offsets silently vanish for every group hashed onto it."""
        p = self.broker.get_partition(GROUP_TOPIC, idx)
        if p is None:
            return
        offset = p.start_offset
        hwm = p.high_watermark
        while offset < hwm:
            batches = await p.make_reader(offset, 1 << 20)
            if not batches:
                break
            for b in batches:
                for rec in b.records():
                    self._apply_recovered(rec)
                offset = b.last_offset + 1

    def on_leadership_gained(self, idx: int) -> None:
        """Sync notification hook (raft leadership callback): gate the
        partition and schedule the replay; no-op before start (start()
        replays everything anyway). Strong task refs are kept — a bare
        create_task result can be GC'd before it runs — and failures are
        retried, then surfaced in the log rather than swallowed."""
        if not self._started:
            return
        token = object()
        self._loading[idx] = token
        task = asyncio.create_task(self._recover_gated(idx, token))
        self._recover_tasks.add(task)
        task.add_done_callback(self._recover_tasks.discard)

    async def _recover_gated(self, idx: int, token: object) -> None:
        for attempt in (1, 2, 3):
            try:
                await self.recover_partition(idx)
                if self._loading.get(idx) is token:
                    del self._loading[idx]  # only OUR generation reopens
                return
            except Exception:
                logger.exception(
                    "group partition %d failover replay failed "
                    "(attempt %d/3)", idx, attempt,
                )
                await asyncio.sleep(0.5)
        # All attempts failed: STAY GATED — answering not_coordinator keeps
        # clients retrying elsewhere/later; serving empty state would
        # silently reset committed offsets.
        logger.error(
            "group partition %d replay failed permanently; coordinator "
            "stays unavailable for its groups", idx,
        )

    def _apply_recovered(self, rec: Record) -> None:
        try:
            k = json.loads(rec.key.decode())
        except Exception:
            return
        gid = k.get("g")
        if k.get("t") == "tomb":
            g = self.groups.pop(gid, None)
            if g is not None:
                g.shutdown()
            return
        g = self.groups.get(gid)
        if g is None:
            g = Group(gid, on_change=self._persist_group)
            self.groups[gid] = g
        if k["t"] == "off" and rec.value:
            v = json.loads(rec.value.decode())
            g.offsets[(k["topic"], k["partition"])] = OffsetCommit(
                v["o"], v.get("e", -1), v.get("m")
            )
        elif k["t"] == "md" and rec.value:
            v = json.loads(rec.value.decode())
            g.protocol_type = v.get("protocol_type")
            g.generation = v.get("generation", 0)
