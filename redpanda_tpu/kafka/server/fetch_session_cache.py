"""Incremental fetch sessions (KIP-227).

Parity with kafka/server/fetch_session_cache.h: a consumer establishes a
session (epoch 0), the broker remembers its partition set + positions, and
subsequent requests (epoch n) carry only CHANGES — added/updated partitions
in `topics`, removals in `forgotten_topics_data`. Responses include only
partitions with new data, errors, or moved watermarks. This turns the
steady-state many-partition fetch from O(partitions) request/response bytes
into O(changed).

Session ids are random int31s; the cache is LRU-bounded.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from redpanda_tpu.kafka.protocol.errors import ErrorCode as E

INVALID_SESSION_ID = 0
# session_epoch sentinels (fetch_session.h / KIP-227)
INITIAL_EPOCH = 0
FINAL_EPOCH = -1


@dataclass
class FetchPartition:
    fetch_offset: int
    max_bytes: int
    # last values sent to the client, for change detection
    last_hwm: int = -1
    last_lso: int = -1
    last_start: int = -1


@dataclass
class FetchSession:
    session_id: int
    epoch: int = 1
    # insertion-ordered (topic, partition) -> FetchPartition
    partitions: dict[tuple[str, int], FetchPartition] = field(default_factory=dict)
    last_used: float = field(default_factory=time.monotonic)

    def apply_request(self, topics: list[dict], forgotten: list[dict]) -> None:
        for t in forgotten or []:
            for p in t.get("partitions") or []:
                self.partitions.pop((t["name"], p), None)
        for t in topics or []:
            for p in t.get("partitions") or []:
                key = (t["name"], p["partition_index"])
                cur = self.partitions.get(key)
                fp = FetchPartition(
                    fetch_offset=p["fetch_offset"],
                    max_bytes=p.get("partition_max_bytes", 1 << 20),
                )
                if cur is not None:
                    fp.last_hwm = cur.last_hwm
                    fp.last_lso = cur.last_lso
                    fp.last_start = cur.last_start
                self.partitions[key] = fp

    def to_topics(self) -> list[dict]:
        """The session's full partition set in fetch-request `topics` shape."""
        by_topic: dict[str, list[dict]] = {}
        for (topic, index), fp in self.partitions.items():
            by_topic.setdefault(topic, []).append(
                {
                    "partition_index": index,
                    "current_leader_epoch": -1,
                    "fetch_offset": fp.fetch_offset,
                    "log_start_offset": -1,
                    "partition_max_bytes": fp.max_bytes,
                }
            )
        return [{"name": t, "partitions": ps} for t, ps in by_topic.items()]

    def prune_response(self, responses: list[dict]) -> list[dict]:
        """Incremental response: keep only partitions with records, errors,
        or changed watermarks; remember what the client now knows."""
        out = []
        for t in responses:
            kept = []
            for p in t["partitions"]:
                key = (t["name"], p["partition_index"])
                fp = self.partitions.get(key)
                changed = (
                    p.get("error_code", 0) != 0
                    or p.get("records")
                    or fp is None
                    or p.get("high_watermark", -1) != fp.last_hwm
                    or p.get("last_stable_offset", -1) != fp.last_lso
                    or p.get("log_start_offset", -1) != fp.last_start
                )
                if fp is not None:
                    fp.last_hwm = p.get("high_watermark", -1)
                    fp.last_lso = p.get("last_stable_offset", -1)
                    fp.last_start = p.get("log_start_offset", -1)
                if changed:
                    kept.append(p)
            if kept:
                out.append({"name": t["name"], "partitions": kept})
        return out


class FetchSessionCache:
    def __init__(self, max_sessions: int = 1000):
        self.max_sessions = max_sessions
        self._sessions: dict[int, FetchSession] = {}

    def get(self, session_id: int) -> FetchSession | None:
        s = self._sessions.get(session_id)
        if s is not None:
            s.last_used = time.monotonic()
        return s

    def create(self) -> FetchSession:
        if len(self._sessions) >= self.max_sessions:
            victim = min(self._sessions.values(), key=lambda s: s.last_used)
            del self._sessions[victim.session_id]
        while True:
            sid = random.randint(1, 0x7FFFFFFF)
            if sid not in self._sessions:
                break
        s = FetchSession(sid)
        self._sessions[sid] = s
        return s

    def remove(self, session_id: int) -> None:
        self._sessions.pop(session_id, None)

    def __len__(self) -> int:
        return len(self._sessions)


def resolve_session(
    cache: FetchSessionCache, req: dict
) -> tuple[FetchSession | None, list[dict], E]:
    """Maps a fetch request onto its session (fetch_session_cache.h's
    maybe_get_session). Returns (session, effective_topics, error).

    - epoch -1: sessionless full fetch (also closes an existing session).
    - epoch  0: full fetch establishing a new session.
    - epoch >0: incremental fetch against an existing session.
    """
    epoch = req.get("session_epoch", FINAL_EPOCH)
    session_id = req.get("session_id", INVALID_SESSION_ID)
    topics = req.get("topics") or []
    if epoch == FINAL_EPOCH:
        if session_id != INVALID_SESSION_ID:
            cache.remove(session_id)
        return None, topics, E.none
    if epoch == INITIAL_EPOCH:
        if session_id != INVALID_SESSION_ID:
            cache.remove(session_id)
        session = cache.create()
        session.apply_request(topics, req.get("forgotten_topics_data") or [])
        return session, session.to_topics(), E.none
    session = cache.get(session_id)
    if session is None:
        return None, [], E.fetch_session_id_not_found
    if epoch != session.epoch:
        return None, [], E.invalid_fetch_session_epoch
    session.apply_request(topics, req.get("forgotten_topics_data") or [])
    session.epoch += 1
    return session, session.to_topics(), E.none
