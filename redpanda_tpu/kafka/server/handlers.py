"""Kafka API handlers.

Parity with kafka/server/handlers/ (one file per API in the reference; here
one function per API, registered in ``build_dispatch_table`` — the analogue
of process_request's dispatch table, requests.cc:216).

Group/txn/sasl handlers are registered by their subsystems when those are
wired onto the broker (group coordinator, tx coordinator, security), so this
module only covers the data-plane + topic-admin APIs.
"""

from __future__ import annotations

import asyncio
import time

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.batch import decode_wire_batches, encode_wire_batches
from redpanda_tpu.kafka.protocol.errors import ErrorCode
from redpanda_tpu.cluster.partition import ConsistencyLevel
from redpanda_tpu.cluster.topic_table import TopicConfig
from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.security.acl import AclOperation, ResourceType

E = ErrorCode


def _authorized(ctx, op: AclOperation, topic: str) -> bool:
    from redpanda_tpu.kafka.server.security_handlers import authorize

    return authorize(ctx, ResourceType.topic, topic, op)


# KIP-430: ops enumerable per resource type in authorized_operations
# bitfields (bit index = the AclOperation wire code).
_TOPIC_OPS = (
    AclOperation.read, AclOperation.write, AclOperation.create,
    AclOperation.delete, AclOperation.alter, AclOperation.describe,
    AclOperation.describe_configs, AclOperation.alter_configs,
)
_CLUSTER_OPS = (
    AclOperation.create, AclOperation.cluster_action, AclOperation.alter,
    AclOperation.describe, AclOperation.describe_configs,
    AclOperation.alter_configs, AclOperation.idempotent_write,
)
_GROUP_OPS = (AclOperation.read, AclOperation.delete, AclOperation.describe)


def authorized_operations(ctx, resource_type: ResourceType, name: str) -> int:
    """Bitfield of operations the connection's principal may perform on
    the resource (KIP-430; metadata v8+, describe_groups v3+)."""
    from redpanda_tpu.kafka.server.security_handlers import authorize

    ops = {
        ResourceType.topic: _TOPIC_OPS,
        ResourceType.cluster: _CLUSTER_OPS,
        ResourceType.group: _GROUP_OPS,
    }[resource_type]
    bits = 0
    for op in ops:
        if authorize(ctx, resource_type, name, op):
            bits |= 1 << int(op)
    return bits


def build_dispatch_table() -> dict:
    return {
        m.API_VERSIONS: handle_api_versions,
        m.METADATA: handle_metadata,
        m.PRODUCE: handle_produce,
        m.FETCH: handle_fetch,
        m.LIST_OFFSETS: handle_list_offsets,
        m.CREATE_TOPICS: handle_create_topics,
        m.DELETE_TOPICS: handle_delete_topics,
        m.CREATE_PARTITIONS: handle_create_partitions,
        m.DELETE_RECORDS: handle_delete_records,
        m.DESCRIBE_CONFIGS: handle_describe_configs,
        m.ALTER_CONFIGS: handle_alter_configs,
        m.INCREMENTAL_ALTER_CONFIGS: handle_incremental_alter_configs,
        m.DESCRIBE_LOG_DIRS: handle_describe_log_dirs,
    }


# ---------------------------------------------------------------- api_versions
async def handle_api_versions(ctx) -> dict:
    return {
        "error_code": 0,
        "api_keys": [
            {"api_key": a.key, "min_version": a.min_version, "max_version": a.max_version}
            for a in sorted(m.APIS.values(), key=lambda a: a.key)
        ],
        "throttle_time_ms": 0,
    }


# ---------------------------------------------------------------- metadata
async def handle_metadata(ctx) -> dict:
    broker = ctx.broker
    cfg = broker.config
    requested = ctx.request.get("topics")
    names: list[str]
    if requested is None or (ctx.api_version == 0 and not requested):
        # full listing is filtered to what the principal may describe
        # (metadata.cc filters unauthorized topics out, no error entries)
        names = sorted(
            n for n in broker.topic_table.topics()
            if _authorized(ctx, AclOperation.describe, n)
        )
    else:
        names = [t["name"] for t in requested]
        allow_auto = ctx.request.get("allow_auto_topic_creation", True)
        if cfg.auto_create_topics and allow_auto:
            for name in names:
                if (
                    not broker.topic_table.contains(name)
                    and _valid_topic_name(name)
                    and not broker.is_internal_topic(name)
                    # auto-create honors the same create ACL as CreateTopics
                    and _authorized(ctx, AclOperation.create, name)
                ):
                    try:
                        await broker.create_topic(
                            TopicConfig(
                                name,
                                cfg.default_partitions,
                                cfg.default_replication,
                            )
                        )
                    except ValueError:
                        pass  # concurrent create
    topics = []
    for name in names:
        if not _authorized(ctx, AclOperation.describe, name):
            topics.append({
                "error_code": int(E.topic_authorization_failed),
                "name": name,
                "partitions": [],
            })
            continue
        md = broker.topic_table.get(name)
        if md is None:
            code = (
                E.invalid_topic_exception
                if not _valid_topic_name(name)
                else E.unknown_topic_or_partition
            )
            topics.append({"error_code": int(code), "name": name, "partitions": []})
            continue
        mdc = getattr(broker, "metadata_cache", None)
        partitions = []
        for idx in sorted(md.assignments):
            pa = md.assignments[idx]
            # Clustered: leadership lives in the leaders table fed by raft
            # notifications + dissemination gossip (metadata_cache.h
            # aggregation); pa.leader only covers the standalone path.
            leader = mdc.get_leader(pa.ntp) if mdc is not None else pa.leader
            partitions.append(
                {
                    "error_code": 0,
                    "partition_index": idx,
                    "leader_id": leader if leader is not None else -1,
                    "replica_nodes": list(pa.replicas),
                    "isr_nodes": list(pa.replicas),
                    "offline_replicas": [],
                }
            )
        entry = {
            "error_code": 0,
            "name": name,
            "is_internal": broker.is_internal_topic(name),
            "partitions": partitions,
        }
        if ctx.api_version >= 8 and ctx.request.get("include_topic_authorized_operations"):
            entry["topic_authorized_operations"] = authorized_operations(
                ctx, ResourceType.topic, name
            )
        topics.append(entry)
    if getattr(broker, "metadata_cache", None) is not None and broker.metadata_cache.all_brokers():
        brokers = [
            {
                "node_id": b.node_id,
                "host": b.kafka_host,
                "port": b.kafka_port,
                "rack": None,
            }
            for b in broker.metadata_cache.all_brokers()
        ]
    else:
        brokers = [
            {
                "node_id": cfg.node_id,
                "host": cfg.advertised_host,
                "port": cfg.advertised_port,
                "rack": None,
            }
        ]
    # Clustered: report the REAL controller leader (admin clients route
    # CreateTopics there); only the standalone broker is its own controller.
    controller_id = cfg.node_id
    fn = getattr(broker, "controller_leader_fn", None)
    if fn is not None:
        leader = fn()
        controller_id = leader if leader is not None else -1
    resp = {
        "brokers": brokers,
        "cluster_id": cfg.cluster_id,
        "controller_id": controller_id,
        "topics": topics,
    }
    if ctx.api_version >= 8 and ctx.request.get("include_cluster_authorized_operations"):
        from redpanda_tpu.kafka.server.security_handlers import DEFAULT_CLUSTER_NAME

        resp["cluster_authorized_operations"] = authorized_operations(
            ctx, ResourceType.cluster, DEFAULT_CLUSTER_NAME
        )
    return resp


def _valid_topic_name(name: str) -> bool:
    return (
        0 < len(name) <= 249
        and name not in (".", "..")
        and all(c.isalnum() or c in "._-" for c in name)
    )


# ---------------------------------------------------------------- produce
async def handle_produce(ctx) -> dict | None:
    # Request entry point: a fresh trace per produce; raft.replicate /
    # storage.append spans below join it via the ambient id. The latency
    # HISTOGRAM is recorded once at the dispatch layer (protocol._dispatch
    # → probes.kafka_produce_hist), which also covers decode/encode.
    with tracer.span(
        "kafka.produce", root=True, node=ctx.broker.config.node_id
    ) as sp:
        # carried out to the dispatch layer so the histogram record there
        # can attach a trace exemplar when this request breaches
        ctx.trace_id = sp.trace_id
        return await _do_handle_produce(ctx)


async def _do_handle_produce(ctx) -> dict | None:
    acks = ctx.request["acks"]
    if acks not in (-1, 0, 1):
        responses = [
            {
                "name": t["name"],
                "partitions": [
                    _produce_partition_error(p["partition_index"], E.invalid_required_acks)
                    for p in t["partitions"]
                ],
            }
            for t in ctx.request["topics"]
        ]
        return {"responses": responses}
    level = {
        -1: ConsistencyLevel.quorum_ack,
        0: ConsistencyLevel.no_ack,
        1: ConsistencyLevel.leader_ack,
    }[acks]
    if level == ConsistencyLevel.quorum_ack and ctx.broker.config.unsafe_relaxed_acks:
        # Consistency-testing knob ONLY (tools/consistency, chaostest
        # posture): deliberately break the acks=-1 contract so the
        # linearizability checker can prove it detects lost acked writes.
        level = ConsistencyLevel.leader_ack
    n_bytes = sum(
        len(p.get("records") or b"")
        for t in ctx.request["topics"]
        for p in t["partitions"]
    )
    # Admission (resource_mgmt budget plane): reserve the record bytes
    # from the kafka_produce account BEFORE anything replicates —
    # shed-before-ack means a shed request's records never reach a log
    # and can never be read; the client sees the retriable KIP-599
    # throttling code plus the occupancy-ramped throttle hint. Bytes
    # release when the replicate round (and so the inflight copy) is done.
    ctrl = getattr(ctx.broker, "produce_admission", None)
    reserved = 0
    if ctrl is not None:
        reserved, retry_ms = ctrl.try_admit(n_bytes)
        if n_bytes > 0 and reserved == 0:
            if acks == 0:
                return None  # no response on the wire, shed still counted
            responses = [
                {
                    "name": t["name"],
                    "partitions": [
                        _produce_partition_error(
                            p["partition_index"], E.throttling_quota_exceeded
                        )
                        for p in t["partitions"]
                    ],
                }
                for t in ctx.request["topics"]
            ]
            return {"responses": responses, "throttle_time_ms": retry_ms}
    try:
        responses = []
        for t in ctx.request["topics"]:
            if not _authorized(ctx, AclOperation.write, t["name"]):
                responses.append({
                    "name": t["name"],
                    "partitions": [
                        _produce_partition_error(p["partition_index"], E.topic_authorization_failed)
                        for p in t["partitions"]
                    ],
                })
                continue
            parts = await asyncio.gather(
                *(
                    _produce_one(ctx.broker, t["name"], p, level, ctx.api_version)
                    for p in t["partitions"]
                )
            )
            responses.append({"name": t["name"], "partitions": list(parts)})
    finally:
        if ctrl is not None:
            ctrl.release(reserved)
    throttle = ctx.broker.quota_manager.record_produce(ctx.header.client_id, n_bytes)
    if acks == 0:
        return None
    return {"responses": responses, "throttle_time_ms": throttle}


def _produce_partition_error(index: int, code: ErrorCode) -> dict:
    return {
        "partition_index": index,
        "error_code": int(code),
        "base_offset": -1,
        "log_append_time_ms": -1,
        "log_start_offset": -1,
    }


async def _produce_one(broker, topic: str, p: dict, level: int, api_version: int = 3) -> dict:
    index = p["partition_index"]
    partition = broker.get_partition(topic, index)
    if partition is None:
        return _produce_partition_error(index, E.unknown_topic_or_partition)
    if not partition.is_leader():
        return _produce_partition_error(index, E.not_leader_for_partition)
    records = p.get("records")
    if not records:
        return _produce_partition_error(index, E.invalid_record)
    if api_version < 3:
        # produce v0-2 carries a legacy magic-0/1 MessageSet: up-convert to
        # ONE v2 batch so the rest of the pipeline only sees modern batches
        # (kafka_batch_adapter.cc adapt_with_version; crc32 verified inside)
        from redpanda_tpu.kafka.protocol.legacy import (
            LegacyBatchError,
            LegacyUnsupportedError,
            convert_message_set,
        )

        try:
            batches = [convert_message_set(records)]
        except LegacyUnsupportedError:
            return _produce_partition_error(index, E.unsupported_for_message_format)
        except LegacyBatchError:
            return _produce_partition_error(index, E.corrupt_message)
    else:
        try:
            # CRC validation goes through the measured adapter boundary
            # (ops/crc_backend.py): batched host SSE4.2 or device kernel,
            # whichever the process-wide probe picked.
            adapted = decode_wire_batches(records, verify_crc=False)
        except EOFError:
            return _produce_partition_error(index, E.corrupt_message)
        from redpanda_tpu.ops.crc_backend import default_backend_async

        v2 = [a for a in adapted if a.v2_format]
        ok = (await default_backend_async()).validate(
            [a.batch.crc_region() for a in v2],
            [a.batch.header.crc for a in v2],
        )
        ok_iter = iter(ok)
        for a in adapted:
            # kafka_batch_adapter.cc:93-121: per batch IN ORDER, reject legacy
            # magic first, then a bad CRC — the first offending batch decides
            # the error (validation itself is batched through the backend).
            if not a.v2_format:
                return _produce_partition_error(index, E.unsupported_for_message_format)
            if not next(ok_iter):
                return _produce_partition_error(index, E.corrupt_message)
        batches = [a.batch for a in adapted]
    if not batches:
        return _produce_partition_error(index, E.invalid_record)
    # idempotence / transaction gate (rm_stm on the produce path,
    # produce_topic_partition → rm_stm path in produce.cc:196): check +
    # append run atomically inside the stm
    if any(b.header.producer_id >= 0 for b in batches):
        stm = await broker.recovered_rm_stm(partition)
        code, result = await stm.replicate(batches, level)
        if code != E.none:
            return _produce_partition_error(index, code)
        if result is None:
            # every batch was an idempotent duplicate: ack, nothing appended
            return {
                "partition_index": index,
                "error_code": 0,
                "base_offset": -1,
                "log_append_time_ms": -1,
                "log_start_offset": partition.start_offset,
            }
    else:
        result = await partition.replicate(batches, level)
    return {
        "partition_index": index,
        "error_code": 0,
        "base_offset": result.base_offset,
        "log_append_time_ms": -1,
        "log_start_offset": partition.start_offset,
    }


# ---------------------------------------------------------------- fetch
async def handle_fetch(ctx) -> dict:
    # The span deliberately includes the long-poll wait (that IS the op's
    # latency) but is exempt from the slow-request log: an empty long poll
    # hitting max_wait_ms is intentional waiting, and would otherwise bury
    # genuinely slow work in the slow ring. Histogram: protocol._dispatch.
    with tracer.span(
        "kafka.fetch", root=True, no_slow=True,
        node=ctx.broker.config.node_id,
    ) as sp:
        ctx.trace_id = sp.trace_id
        return await _do_handle_fetch(ctx)


async def _do_handle_fetch(ctx) -> dict:
    from redpanda_tpu.kafka.server.fetch_session_cache import resolve_session

    req = ctx.request
    # Incremental fetch sessions (KIP-227): the session supplies the full
    # partition set when the request only carries changes.
    session, topics, sess_err = resolve_session(ctx.broker.fetch_sessions, req)
    if sess_err != E.none:
        return {
            "throttle_time_ms": 0,
            "error_code": int(sess_err),
            "session_id": 0,
            "responses": [],
        }
    max_wait_ms = req.get("max_wait_ms", 0)
    min_bytes = max(req.get("min_bytes", 0), 0)
    max_bytes = req.get("max_bytes", 0x7FFFFFFF)
    deadline = time.monotonic() + max(max_wait_ms, 0) / 1000.0
    poll = ctx.broker.config.fetch_poll_interval_s
    while True:
        responses, total, any_error = await _fetch_once(ctx, topics, max_bytes)
        # respond immediately on any partition error (kafka semantics) or
        # once min_bytes is satisfied / the wait budget is spent
        if any_error or total >= min_bytes or time.monotonic() >= deadline:
            break
        # Long-poll gate: re-reading and re-encoding every poll tick is
        # wasted work — only rerun _fetch_once after some requested
        # partition's high watermark advances.
        hwms = _fetch_hwm_snapshot(ctx, topics)
        while time.monotonic() < deadline:
            await asyncio.sleep(min(poll, max(deadline - time.monotonic(), 0)))
            if _fetch_hwm_snapshot(ctx, topics) != hwms:
                break
    throttle = ctx.broker.quota_manager.record_fetch(ctx.header.client_id, total)
    if session is not None:
        responses = session.prune_response(responses)
    out = {"responses": responses, "throttle_time_ms": throttle}
    if ctx.api_version >= 7:
        out["error_code"] = 0
        out["session_id"] = session.session_id if session is not None else 0
    return out


def _fetch_hwm_snapshot(ctx, topics) -> tuple:
    out = []
    for t in topics:
        for p in t["partitions"]:
            part = ctx.broker.get_partition(t["name"], p["partition_index"])
            out.append(part.high_watermark if part is not None else -1)
    return tuple(out)


async def _fetch_once(ctx, topics, max_bytes: int) -> tuple[list, int, bool]:
    broker = ctx.broker
    responses = []
    total = 0
    any_error = False
    budget = max_bytes
    for t in topics:
        parts = []
        if not _authorized(ctx, AclOperation.read, t["name"]):
            responses.append({
                "name": t["name"],
                "partitions": [
                    _fetch_partition_error(p["partition_index"], E.topic_authorization_failed)
                    for p in t["partitions"]
                ],
            })
            any_error = True
            continue
        for p in t["partitions"]:
            index = p["partition_index"]
            partition = broker.get_partition(t["name"], index)
            if partition is None:
                parts.append(_fetch_partition_error(index, E.unknown_topic_or_partition))
                any_error = True
                continue
            if not partition.is_leader() or (
                hasattr(partition, "ready_for_reads") and not partition.ready_for_reads()
            ):
                # unsettled new leader: serving now could show a hw BELOW
                # data an earlier leader acked (raft §8 read barrier;
                # clients refresh metadata and retry)
                parts.append(_fetch_partition_error(index, E.not_leader_for_partition))
                any_error = True
                continue
            hwm = partition.high_watermark
            fetch_offset = p["fetch_offset"]
            if fetch_offset < partition.start_offset or fetch_offset > hwm:
                parts.append(_fetch_partition_error(index, E.offset_out_of_range, hwm=hwm))
                any_error = True
                continue
            # read_committed: clamp to the LSO and surface aborted ranges so
            # clients drop aborted records (rm_stm LSO + tx_range snapshots)
            read_committed = ctx.request.get("isolation_level", 0) == 1
            lso = partition.last_stable_offset
            max_read = hwm - 1
            aborted = None
            if read_committed:
                stm = await ctx.broker.recovered_rm_stm(partition)
                lso = stm.last_stable_offset
                max_read = lso - 1
            take = min(p.get("partition_max_bytes", budget), max(budget, 0))
            batches = (
                await partition.make_reader(fetch_offset, take, max_offset=max_read)
                if take > 0 and fetch_offset <= max_read
                else []
            )
            if read_committed and batches:
                aborted = [
                    {"producer_id": a.producer_id, "first_offset": a.first_offset}
                    for a in stm.aborted_ranges(fetch_offset, batches[-1].last_offset)
                ] or None
            # data policy: per-topic transform view on the fetch path
            # (v8_engine's seat, application.cc:597,1037)
            policy = broker.data_policies.get(t["name"])
            if policy is not None and batches:
                batches = broker.policy_engine.transform_batches(
                    policy.spec_json, batches
                )
            records = encode_wire_batches(batches) if batches else b""
            total += len(records)
            budget -= len(records)
            parts.append(
                {
                    "partition_index": index,
                    "error_code": 0,
                    "high_watermark": hwm,
                    "last_stable_offset": lso,
                    "log_start_offset": partition.start_offset,
                    "aborted_transactions": aborted,
                    "preferred_read_replica": -1,
                    "records": records or None,
                }
            )
        responses.append({"name": t["name"], "partitions": parts})
    return responses, total, any_error


def _fetch_partition_error(index: int, code: ErrorCode, hwm: int = -1) -> dict:
    return {
        "partition_index": index,
        "error_code": int(code),
        "high_watermark": hwm,
        "last_stable_offset": -1,
        "log_start_offset": -1,
        "aborted_transactions": None,
        "preferred_read_replica": -1,
        "records": None,
    }


# ---------------------------------------------------------------- list_offsets
async def handle_list_offsets(ctx) -> dict:
    broker = ctx.broker
    topics = []
    for t in ctx.request.get("topics") or []:
        parts = []
        if not _authorized(ctx, AclOperation.describe, t["name"]):
            topics.append({
                "name": t["name"],
                "partitions": [
                    {
                        "partition_index": p["partition_index"],
                        "error_code": int(E.topic_authorization_failed),
                        "timestamp": -1,
                        "offset": -1,
                    }
                    for p in t["partitions"]
                ],
            })
            continue
        for p in t["partitions"]:
            index = p["partition_index"]
            partition = broker.get_partition(t["name"], index)
            if partition is None:
                parts.append(
                    {
                        "partition_index": index,
                        "error_code": int(E.unknown_topic_or_partition),
                        "timestamp": -1,
                        "offset": -1,
                        "old_style_offsets": [],
                    }
                )
                continue
            if hasattr(partition, "ready_for_reads") and not partition.ready_for_reads():
                parts.append(
                    {
                        "partition_index": index,
                        "error_code": int(E.not_leader_for_partition),
                        "timestamp": -1,
                        "offset": -1,
                        "old_style_offsets": [],
                    }
                )
                continue
            ts = p["timestamp"]
            if ts == -1:  # latest
                offset = partition.high_watermark
            elif ts == -2:  # earliest
                offset = partition.start_offset
            else:
                q = await partition.timequery(ts)
                offset = q if q is not None else -1
            parts.append(
                {
                    "partition_index": index,
                    "error_code": 0,
                    "timestamp": -1,
                    "offset": offset,
                    "old_style_offsets": [offset] if offset >= 0 else [],
                }
            )
        topics.append({"name": t["name"], "partitions": parts})
    return {"topics": topics}


# ---------------------------------------------------------------- topic admin
async def handle_create_topics(ctx) -> dict:
    broker = ctx.broker
    validate_only = ctx.request.get("validate_only", False)
    results = []
    for t in ctx.request.get("topics") or []:
        name = t["name"]
        if not _authorized(ctx, AclOperation.create, name):
            results.append(_topic_result(name, E.topic_authorization_failed))
            continue
        if not _valid_topic_name(name):
            results.append(_topic_result(name, E.invalid_topic_exception))
            continue
        if broker.is_internal_topic(name):
            results.append(
                _topic_result(name, E.invalid_topic_exception, "reserved internal name")
            )
            continue
        if broker.topic_table.contains(name):
            results.append(_topic_result(name, E.topic_already_exists))
            continue
        num_partitions = t.get("num_partitions", -1)
        if num_partitions == -1:
            num_partitions = broker.config.default_partitions
        if num_partitions <= 0:
            results.append(_topic_result(name, E.invalid_partitions))
            continue
        replication = t.get("replication_factor", -1)
        if replication == -1:
            replication = broker.config.default_replication
        cfg = TopicConfig(name, num_partitions, replication)
        for c in t.get("configs") or []:
            _apply_topic_config(cfg, c["name"], c["value"])
        if not validate_only:
            try:
                await broker.create_topic(cfg)
            except ValueError:
                # lost a cross-broker create race after the contains() check
                results.append(_topic_result(name, E.topic_already_exists))
                continue
            except Exception as e:
                code = (
                    E.invalid_replication_factor
                    if "replication factor" in str(e)
                    else E.unknown_server_error
                )
                results.append(_topic_result(name, code, str(e)))
                continue
        results.append(_topic_result(name, E.none))
    return {"topics": results}


def _topic_result(name: str, code: ErrorCode, msg: str | None = None) -> dict:
    return {"name": name, "error_code": int(code), "error_message": msg}


def _apply_topic_config(cfg: TopicConfig, key: str, value: str | None) -> None:
    cfg.apply_override(key, value)


async def handle_delete_topics(ctx) -> dict:
    broker = ctx.broker
    responses = []
    for name in ctx.request.get("topic_names") or []:
        if not _authorized(ctx, AclOperation.delete, name):
            responses.append({"name": name, "error_code": int(E.topic_authorization_failed)})
            continue
        if not broker.topic_table.contains(name):
            responses.append({"name": name, "error_code": int(E.unknown_topic_or_partition)})
            continue
        await broker.delete_topic(name)
        responses.append({"name": name, "error_code": 0})
    return {"responses": responses}


async def handle_create_partitions(ctx) -> dict:
    broker = ctx.broker
    results = []
    for t in ctx.request.get("topics") or []:
        name = t["name"]
        if not _authorized(ctx, AclOperation.alter, name):
            results.append(_topic_result(name, E.topic_authorization_failed))
            continue
        md = broker.topic_table.get(name)
        if md is None:
            results.append(_topic_result(name, E.unknown_topic_or_partition))
            continue
        if t["count"] <= md.config.partition_count:
            results.append(
                _topic_result(
                    name, E.invalid_partitions, "partition count can only grow"
                )
            )
            continue
        if not ctx.request.get("validate_only", False):
            await broker.create_partitions(name, t["count"])
        results.append(_topic_result(name, E.none))
    return {"results": results}


async def handle_delete_records(ctx) -> dict:
    broker = ctx.broker
    topics = []
    for t in ctx.request.get("topics") or []:
        parts = []
        if not _authorized(ctx, AclOperation.delete, t["name"]):
            topics.append({
                "name": t["name"],
                "partitions": [
                    {
                        "partition_index": p["partition_index"],
                        "low_watermark": -1,
                        "error_code": int(E.topic_authorization_failed),
                    }
                    for p in t["partitions"]
                ],
            })
            continue
        for p in t["partitions"]:
            index = p["partition_index"]
            partition = broker.get_partition(t["name"], index)
            if partition is None:
                parts.append(
                    {
                        "partition_index": index,
                        "low_watermark": -1,
                        "error_code": int(E.unknown_topic_or_partition),
                    }
                )
                continue
            offset = p["offset"]
            if offset == -1:
                offset = partition.high_watermark
            if offset > partition.high_watermark:
                parts.append(
                    {
                        "partition_index": index,
                        "low_watermark": -1,
                        "error_code": int(E.offset_out_of_range),
                    }
                )
                continue
            await partition.prefix_truncate(offset)
            parts.append(
                {
                    "partition_index": index,
                    "low_watermark": partition.start_offset,
                    "error_code": 0,
                }
            )
        topics.append({"name": t["name"], "partitions": parts})
    return {"topics": topics}


# ---------------------------------------------------------------- configs
_RESOURCE_TOPIC = 2
_RESOURCE_BROKER = 4


async def handle_describe_configs(ctx) -> dict:
    broker = ctx.broker
    results = []
    for res in ctx.request.get("resources") or []:
        rtype, rname = res["resource_type"], res["resource_name"]
        keys = res.get("configuration_keys")
        if rtype == _RESOURCE_TOPIC and not _authorized(
            ctx, AclOperation.describe_configs, rname
        ):
            results.append(
                {
                    "error_code": int(E.topic_authorization_failed),
                    "error_message": "describe configs denied",
                    "resource_type": rtype,
                    "resource_name": rname,
                    "configs": [],
                }
            )
            continue
        if rtype == _RESOURCE_TOPIC:
            md = broker.topic_table.get(rname)
            if md is None:
                results.append(
                    {
                        "error_code": int(E.unknown_topic_or_partition),
                        "error_message": None,
                        "resource_type": rtype,
                        "resource_name": rname,
                        "configs": [],
                    }
                )
                continue
            cfg_map = md.config.config_map()
        elif rtype == _RESOURCE_BROKER:
            cfg_map = {
                "auto.create.topics.enable": str(broker.config.auto_create_topics).lower(),
                "num.partitions": str(broker.config.default_partitions),
                "default.replication.factor": str(broker.config.default_replication),
            }
        else:
            results.append(
                {
                    "error_code": int(E.invalid_request),
                    "error_message": "unsupported resource type",
                    "resource_type": rtype,
                    "resource_name": rname,
                    "configs": [],
                }
            )
            continue
        configs = [
            {
                "name": k,
                "value": v,
                "read_only": False,
                "is_default": True,
                "config_source": 5,  # DEFAULT_CONFIG
                "is_sensitive": False,
                "synonyms": [],
            }
            for k, v in cfg_map.items()
            if keys is None or k in keys
        ]
        results.append(
            {
                "error_code": 0,
                "error_message": None,
                "resource_type": rtype,
                "resource_name": rname,
                "configs": configs,
            }
        )
    return {"results": results}


async def handle_alter_configs(ctx) -> dict:
    broker = ctx.broker
    responses = []
    for res in ctx.request.get("resources") or []:
        rtype, rname = res["resource_type"], res["resource_name"]
        code = E.none
        if rtype == _RESOURCE_TOPIC and not _authorized(ctx, AclOperation.alter_configs, rname):
            code = E.topic_authorization_failed
        elif rtype == _RESOURCE_TOPIC:
            md = broker.topic_table.get(rname)
            if md is None:
                code = E.unknown_topic_or_partition
            elif not ctx.request.get("validate_only", False):
                for c in res.get("configs") or []:
                    _apply_topic_config(md.config, c["name"], c["value"])
                broker._persist_topic_config(md.config)
                broker.update_log_configs(rname)
        else:
            code = E.invalid_request
        responses.append(
            {
                "error_code": int(code),
                "error_message": None,
                "resource_type": rtype,
                "resource_name": rname,
            }
        )
    return {"responses": responses}


async def handle_incremental_alter_configs(ctx) -> dict:
    broker = ctx.broker
    responses = []
    for res in ctx.request.get("resources") or []:
        rtype, rname = res["resource_type"], res["resource_name"]
        code = E.none
        if rtype == _RESOURCE_TOPIC and not _authorized(ctx, AclOperation.alter_configs, rname):
            code = E.topic_authorization_failed
        elif rtype == _RESOURCE_TOPIC:
            md = broker.topic_table.get(rname)
            if md is None:
                code = E.unknown_topic_or_partition
            elif not ctx.request.get("validate_only", False):
                for c in res.get("configs") or []:
                    op = c.get("config_operation", 0)
                    if op == 0:  # SET
                        _apply_topic_config(md.config, c["name"], c["value"])
                    elif op == 1:  # DELETE
                        md.config.extra.pop(c["name"], None)
                broker._persist_topic_config(md.config)
                broker.update_log_configs(rname)
        else:
            code = E.invalid_request
        responses.append(
            {
                "error_code": int(code),
                "error_message": None,
                "resource_type": rtype,
                "resource_name": rname,
            }
        )
    return {"responses": responses}


async def handle_describe_log_dirs(ctx) -> dict:
    broker = ctx.broker
    requested = ctx.request.get("topics")
    wanted: dict[str, set[int]] | None = None
    if requested is not None:
        wanted = {t["topic"]: set(t["partitions"]) for t in requested}
    by_topic: dict[str, list[dict]] = {}
    for ntp, partition in broker.partition_manager.partitions().items():
        if wanted is not None and (
            ntp.topic not in wanted or ntp.partition not in wanted[ntp.topic]
        ):
            continue
        size = sum(seg.size_bytes for seg in partition.log.segments)
        by_topic.setdefault(ntp.topic, []).append(
            {
                "partition_index": ntp.partition,
                "partition_size": size,
                "offset_lag": 0,
                "is_future_key": False,
            }
        )
    return {
        "results": [
            {
                "error_code": 0,
                "log_dir": broker.config.data_dir,
                "topics": [
                    {"name": name, "partitions": parts}
                    for name, parts in sorted(by_topic.items())
                ],
            }
        ]
    }


# ---------------------------------------------------------------- coordinator
# ---------------------------------------------------------------- error makers
def _produce_error_maker(ctx, code: ErrorCode) -> dict:
    return {
        "responses": [
            {
                "name": t["name"],
                "partitions": [
                    _produce_partition_error(p["partition_index"], code)
                    for p in t["partitions"]
                ],
            }
            for t in ctx.request.get("topics") or []
        ]
    }


def _fetch_error_maker(ctx, code: ErrorCode) -> dict:
    return {
        "error_code": int(code),
        "responses": [
            {
                "name": t["name"],
                "partitions": [
                    _fetch_partition_error(p["partition_index"], code)
                    for p in t["partitions"]
                ],
            }
            for t in ctx.request.get("topics") or []
        ],
    }


def _create_topics_error_maker(ctx, code: ErrorCode) -> dict:
    return {
        "topics": [
            _topic_result(t["name"], code) for t in ctx.request.get("topics") or []
        ]
    }


def _delete_topics_error_maker(ctx, code: ErrorCode) -> dict:
    return {
        "responses": [
            {"name": n, "error_code": int(code)}
            for n in ctx.request.get("topic_names") or []
        ]
    }


def _metadata_error_maker(ctx, code: ErrorCode) -> dict:
    return {
        "brokers": [],
        "cluster_id": None,
        "controller_id": -1,
        "topics": [
            {"error_code": int(code), "name": t["name"], "partitions": []}
            for t in ctx.request.get("topics") or []
        ],
    }


def _list_offsets_error_maker(ctx, code: ErrorCode) -> dict:
    return {
        "topics": [
            {
                "name": t["name"],
                "partitions": [
                    {
                        "partition_index": p["partition_index"],
                        "error_code": int(code),
                        "timestamp": -1,
                        "offset": -1,
                    }
                    for p in t["partitions"]
                ],
            }
            for t in ctx.request.get("topics") or []
        ]
    }


ERROR_RESPONSE_MAKERS = {
    m.PRODUCE: _produce_error_maker,
    m.FETCH: _fetch_error_maker,
    m.CREATE_TOPICS: _create_topics_error_maker,
    m.DELETE_TOPICS: _delete_topics_error_maker,
    m.METADATA: _metadata_error_maker,
    m.LIST_OFFSETS: _list_offsets_error_maker,
}
