"""Kafka wire protocol server + embedded client (parity with src/v/kafka)."""
