"""Async Kafka client.

Parity surface (kafka/client/client.h): broker connections with correlated
in-flight requests, metadata-driven topic routing, produce/fetch/offsets,
topic admin, and group membership calls (used by the group-aware consumer).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.batch import decode_wire_batches, encode_wire_batches
from redpanda_tpu.kafka.protocol.errors import ErrorCode, KafkaError
from redpanda_tpu.kafka.protocol.primitives import Reader
from redpanda_tpu.kafka.protocol.schema import RequestHeader, decode_message, encode_message
from redpanda_tpu.models.record import Record, RecordBatch

logger = logging.getLogger("rptpu.kafka.client")


class BrokerConnection:
    """One TCP connection with correlation-id request/response matching
    (kafka/client/broker.h + transport)."""

    def __init__(
        self,
        host: str,
        port: int,
        client_id: str = "rptpu-client",
        sasl: tuple[str, str] | None = None,
        sasl_mechanism: str = "SCRAM-SHA-256",
        ssl_context=None,
    ):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.sasl = sasl  # (username, password) enables the SCRAM dance
        self.sasl_mechanism = sasl_mechanism
        self.ssl_context = ssl_context
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._correlation = itertools.count(1)
        self._inflight: dict[int, tuple] = {}  # corr -> (future, api, version)
        self._recv_task: asyncio.Task | None = None
        self._versions: dict[int, tuple[int, int]] = {}
        self._lock = asyncio.Lock()

    async def connect(self) -> "BrokerConnection":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self.ssl_context
        )
        self._recv_task = asyncio.create_task(self._recv_loop())
        vs = await self.request(m.API_VERSIONS, {}, version=0)
        if vs["error_code"] == 0:
            self._versions = {
                e["api_key"]: (e["min_version"], e["max_version"]) for e in vs["api_keys"]
            }
        if self.sasl is not None:
            await self._authenticate()
        return self

    async def _authenticate(self) -> None:
        """SCRAM over SaslHandshake/SaslAuthenticate (client/sasl_client)."""
        import base64
        import os

        from redpanda_tpu.security.scram import (
            MECHANISMS,
            ScramError,
            scram_client_final,
            scram_client_first,
        )

        username, password = self.sasl
        algo = MECHANISMS[self.sasl_mechanism]
        hs = await self.request(m.SASL_HANDSHAKE, {"mechanism": algo.name})
        if hs["error_code"] != 0:
            raise KafkaError(
                ErrorCode(hs["error_code"]),
                f"mechanism {algo.name} rejected; server offers {hs['mechanisms']}",
            )
        nonce = base64.b64encode(os.urandom(18)).decode()
        first = scram_client_first(username, nonce)
        r1 = await self.request(m.SASL_AUTHENTICATE, {"auth_bytes": first})
        if r1["error_code"] != 0:
            raise KafkaError(ErrorCode(r1["error_code"]), r1.get("error_message") or "")
        final, expected_sig = scram_client_final(
            username, password, nonce, first, r1["auth_bytes"], algo
        )
        r2 = await self.request(m.SASL_AUTHENTICATE, {"auth_bytes": final})
        if r2["error_code"] != 0:
            raise KafkaError(ErrorCode(r2["error_code"]), r2.get("error_message") or "")
        attrs = r2["auth_bytes"].decode()
        if not attrs.startswith("v=") or base64.b64decode(attrs[2:]) != expected_sig:
            raise ScramError("server signature mismatch (not the real broker?)")

    async def close(self) -> None:
        if self._recv_task:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except asyncio.CancelledError:
                pass
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        for fut, _api, _v in self._inflight.values():
            if not fut.done():
                fut.set_exception(ConnectionError("connection closed"))
        self._inflight.clear()

    def negotiated_version(self, api_key: int, preferred: int | None = None) -> int:
        api = m.APIS[api_key]
        lo, hi = self._versions.get(api_key, (api.min_version, api.max_version))
        v = min(api.max_version, hi) if preferred is None else min(preferred, hi, api.max_version)
        if v < max(api.min_version, lo):
            raise KafkaError(ErrorCode.unsupported_version, f"api {api_key}")
        return v

    async def request(self, api_key: int, body: dict, version: int | None = None) -> dict:
        api = m.APIS[api_key]
        v = self.negotiated_version(api_key) if version is None else version
        corr = next(self._correlation)
        header = RequestHeader(api_key, v, corr, self.client_id)
        payload = header.encode(api.is_flexible(v)) + encode_message(api, "request", body, v)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[corr] = (fut, api, v)
        async with self._lock:
            self._writer.write(struct.pack(">i", len(payload)) + payload)
            await self._writer.drain()
        return await fut

    async def oneway(self, api_key: int, body: dict, version: int | None = None) -> None:
        """Fire-and-forget (acks=0 produce has no response frame)."""
        api = m.APIS[api_key]
        v = self.negotiated_version(api_key) if version is None else version
        corr = next(self._correlation)
        header = RequestHeader(api_key, v, corr, self.client_id)
        payload = header.encode(api.is_flexible(v)) + encode_message(api, "request", body, v)
        async with self._lock:
            self._writer.write(struct.pack(">i", len(payload)) + payload)
            await self._writer.drain()

    async def _recv_loop(self) -> None:
        try:
            while True:
                size_buf = await self._reader.readexactly(4)
                (size,) = struct.unpack(">i", size_buf)
                frame = await self._reader.readexactly(size)
                r = Reader(frame)
                corr = r.int32()
                entry = self._inflight.pop(corr, None)
                if entry is None:
                    continue
                fut, api, v = entry
                if api.is_flexible(v) and api.key != m.API_VERSIONS:
                    r.tagged_fields()
                try:
                    resp = decode_message(api, "response", frame[r.pos :], v)
                    if not fut.done():
                        fut.set_result(resp)
                except Exception as e:  # noqa: BLE001
                    if not fut.done():
                        fut.set_exception(e)
        except asyncio.CancelledError:
            pass
        except Exception:  # noqa: BLE001 — any framing error kills the connection
            logger.exception("broker connection receive loop failed")
        finally:
            # Whatever ended the loop, nothing will ever complete these.
            for entry in self._inflight.values():
                fut = entry[0]
                if not fut.done():
                    fut.set_exception(ConnectionError("connection lost"))
            self._inflight.clear()


class KafkaClient:
    """Metadata-routed multi-broker client (kafka/client/client.h)."""

    def __init__(
        self,
        bootstrap: list[tuple[str, int]],
        client_id: str = "rptpu-client",
        sasl: tuple[str, str] | None = None,
        sasl_mechanism: str = "SCRAM-SHA-256",
        ssl_context=None,
    ):
        self.bootstrap = bootstrap
        self.client_id = client_id
        self.sasl = sasl
        self.sasl_mechanism = sasl_mechanism
        self.ssl_context = ssl_context
        self._conns: dict[int, BrokerConnection] = {}
        self._brokers: dict[int, tuple[str, int]] = {}
        self._leaders: dict[tuple[str, int], int] = {}
        self._bootstrap_conn: BrokerConnection | None = None
        self._conn_lock = asyncio.Lock()

    def _new_conn(self, host: str, port: int) -> BrokerConnection:
        return BrokerConnection(
            host, port, self.client_id, sasl=self.sasl,
            sasl_mechanism=self.sasl_mechanism, ssl_context=self.ssl_context,
        )

    async def connect(self) -> "KafkaClient":
        host, port = self.bootstrap[0]
        self._bootstrap_conn = await self._new_conn(host, port).connect()
        await self.refresh_metadata()
        return self

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()
        if self._bootstrap_conn:
            await self._bootstrap_conn.close()

    # ------------------------------------------------------------ metadata
    async def refresh_metadata(
        self, topics: list[str] | None = None, *, auto_create: bool = True
    ) -> dict:
        body = {
            "topics": None if topics is None else [{"name": t} for t in topics],
            "allow_auto_topic_creation": auto_create,
        }
        md = await self._bootstrap_conn.request(m.METADATA, body)
        for b in md["brokers"]:
            self._brokers[b["node_id"]] = (b["host"], b["port"])
        for t in md["topics"]:
            for p in t.get("partitions") or []:
                if p["leader_id"] >= 0:
                    self._leaders[(t["name"], p["partition_index"])] = p["leader_id"]
        return md

    async def connection_for(self, node_id: int) -> BrokerConnection:
        async with self._conn_lock:
            if node_id not in self._conns:
                host, port = self._brokers[node_id]
                self._conns[node_id] = await self._new_conn(host, port).connect()
            return self._conns[node_id]

    async def leader_connection(self, topic: str, partition: int) -> BrokerConnection:
        key = (topic, partition)
        if key not in self._leaders:
            # A just-created partition is mid-election (leader_id -1 in
            # metadata); standard client behavior polls metadata rather
            # than failing the first produce after create_topic.
            deadline = asyncio.get_event_loop().time() + 10.0
            while True:
                await self.refresh_metadata([topic])
                if key in self._leaders:
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise KafkaError(
                        ErrorCode.unknown_topic_or_partition, f"{topic}/{partition}"
                    )
                await asyncio.sleep(0.25)
        return await self.connection_for(self._leaders[key])

    async def any_connection(self) -> BrokerConnection:
        return self._bootstrap_conn

    # ------------------------------------------------------------ produce
    async def produce(
        self,
        topic: str,
        partition: int,
        records: list[tuple[bytes | None, bytes | None]] | list[bytes],
        *,
        acks: int = -1,
        timeout_ms: int = 30000,
    ) -> int:
        """Produce one batch; returns the assigned base offset."""
        recs = []
        for i, r in enumerate(records):
            key, value = r if isinstance(r, tuple) else (None, r)
            recs.append(Record(offset_delta=i, key=key, value=value))
        batch = RecordBatch.build(recs)
        return await self.produce_batches(
            topic, partition, [batch], acks=acks, timeout_ms=timeout_ms
        )

    async def produce_batches(
        self,
        topic: str,
        partition: int,
        batches: list[RecordBatch],
        *,
        acks: int = -1,
        timeout_ms: int = 30000,
    ) -> int:
        conn = await self.leader_connection(topic, partition)
        body = {
            "transactional_id": None,
            "acks": acks,
            "timeout_ms": timeout_ms,
            "topics": [
                {
                    "name": topic,
                    "partitions": [
                        {
                            "partition_index": partition,
                            "records": encode_wire_batches(batches),
                        }
                    ],
                }
            ],
        }
        if acks == 0:
            await conn.oneway(m.PRODUCE, body)
            return -1
        resp = await conn.request(m.PRODUCE, body)
        presp = resp["responses"][0]["partitions"][0]
        if presp["error_code"] != 0:
            raise KafkaError(ErrorCode(presp["error_code"]), f"produce {topic}/{partition}")
        return presp["base_offset"]

    # ------------------------------------------------------------ fetch
    async def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        *,
        max_bytes: int = 1 << 20,
        max_wait_ms: int = 100,
        min_bytes: int = 1,
        isolation_level: int = 0,
    ) -> tuple[list[RecordBatch], int]:
        """Returns (batches, high_watermark). isolation_level=1 =
        read_committed (server clamps to LSO; aborted batches filtered
        client-side via the aborted_transactions ranges)."""
        conn = await self.leader_connection(topic, partition)
        body = {
            "replica_id": -1,
            "max_wait_ms": max_wait_ms,
            "min_bytes": min_bytes,
            "max_bytes": max_bytes,
            "isolation_level": isolation_level,
            "session_id": 0,
            "session_epoch": -1,
            "topics": [
                {
                    "name": topic,
                    "partitions": [
                        {
                            "partition_index": partition,
                            "current_leader_epoch": -1,
                            "fetch_offset": offset,
                            "log_start_offset": -1,
                            "partition_max_bytes": max_bytes,
                        }
                    ],
                }
            ],
            "forgotten_topics_data": [],
            "rack_id": "",
        }
        resp = await conn.request(m.FETCH, body)
        presp = resp["responses"][0]["partitions"][0]
        if presp["error_code"] != 0:
            raise KafkaError(ErrorCode(presp["error_code"]), f"fetch {topic}/{partition}")
        records = presp.get("records")
        batches = []
        if records:
            batches = [a.batch for a in decode_wire_batches(records) if a.batch is not None]
        if isolation_level != 1:
            # control batches (tx markers) are transport metadata, never
            # application records — skipped at EVERY isolation level
            batches = [b for b in batches if not b.header.is_control]
        if isolation_level == 1:
            # Standard read_committed consumer: a pid becomes "aborted" at
            # its advertised first_offset and stops being aborted at its
            # control marker — offsets after the marker are a NEW tx.
            pending = sorted(
                (a["first_offset"], a["producer_id"])
                for a in presp.get("aborted_transactions") or []
            )
            aborted_active: set[int] = set()
            visible = []
            for b in batches:
                while pending and pending[0][0] <= b.header.base_offset:
                    aborted_active.add(pending.pop(0)[1])
                if b.header.is_control:
                    aborted_active.discard(b.header.producer_id)
                    continue
                if b.header.is_transactional and b.header.producer_id in aborted_active:
                    continue
                visible.append(b)
            batches = visible
        return batches, presp["high_watermark"]

    # ------------------------------------------------------------ offsets
    async def list_offset(self, topic: str, partition: int, timestamp: int) -> int:
        conn = await self.leader_connection(topic, partition)
        body = {
            "replica_id": -1,
            "isolation_level": 0,
            "topics": [
                {
                    "name": topic,
                    "partitions": [
                        {
                            "partition_index": partition,
                            "current_leader_epoch": -1,
                            "timestamp": timestamp,
                        }
                    ],
                }
            ],
        }
        resp = await conn.request(m.LIST_OFFSETS, body)
        presp = resp["topics"][0]["partitions"][0]
        if presp["error_code"] != 0:
            raise KafkaError(ErrorCode(presp["error_code"]), f"list_offsets {topic}")
        return presp["offset"]

    async def earliest_offset(self, topic: str, partition: int) -> int:
        return await self.list_offset(topic, partition, -2)

    async def latest_offset(self, topic: str, partition: int) -> int:
        return await self.list_offset(topic, partition, -1)

    # ------------------------------------------------------------ admin
    async def create_topic(
        self,
        name: str,
        partitions: int = 1,
        replication: int = 1,
        configs: dict[str, str] | None = None,
    ) -> None:
        conn = await self.any_connection()
        body = {
            "topics": [
                {
                    "name": name,
                    "num_partitions": partitions,
                    "replication_factor": replication,
                    "assignments": [],
                    "configs": [
                        {"name": k, "value": v} for k, v in (configs or {}).items()
                    ],
                }
            ],
            "timeout_ms": 30000,
            "validate_only": False,
        }
        resp = await conn.request(m.CREATE_TOPICS, body)
        tr = resp["topics"][0]
        if tr["error_code"] != 0:
            raise KafkaError(ErrorCode(tr["error_code"]), f"create_topic {name}")
        await self.refresh_metadata([name])

    async def delete_topic(self, name: str) -> None:
        conn = await self.any_connection()
        resp = await conn.request(
            m.DELETE_TOPICS, {"topic_names": [name], "timeout_ms": 30000}
        )
        tr = resp["responses"][0]
        if tr["error_code"] != 0:
            raise KafkaError(ErrorCode(tr["error_code"]), f"delete_topic {name}")
        for key in [k for k in self._leaders if k[0] == name]:
            del self._leaders[key]
