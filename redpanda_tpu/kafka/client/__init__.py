"""Embedded async Kafka client (parity with src/v/kafka/client).

Used in-process by the REST proxy, schema registry, and the coproc event
listener, exactly as the reference's kafka::client is (client/client.h);
also the primary test client since the framework is its own ecosystem.
"""

from redpanda_tpu.kafka.client.client import KafkaClient, BrokerConnection

__all__ = ["KafkaClient", "BrokerConnection"]
