"""Group-aware consumer.

Parity with kafka/client/consumer.h + assignment_plans (the reference's
embedded client implements the full join/sync/heartbeat/offset loop so
pandaproxy can expose group consumption). ConsumerProtocol metadata and
assignment blobs follow the standard Kafka "consumer" protocol encoding
(version, topic list, user-data / partition assignments) so third-party
members could interoperate.
"""

from __future__ import annotations

import asyncio
import logging
import struct

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.errors import ErrorCode, KafkaError

logger = logging.getLogger("rptpu.kafka.consumer")


# ---------------------------------------------------------------- protocol blobs
def encode_subscription(topics: list[str], user_data: bytes = b"") -> bytes:
    out = struct.pack(">hi", 0, len(topics))
    for t in topics:
        tb = t.encode()
        out += struct.pack(">h", len(tb)) + tb
    out += struct.pack(">i", len(user_data)) + user_data
    return out


def decode_subscription(blob: bytes) -> list[str]:
    (_version, n) = struct.unpack_from(">hi", blob, 0)
    pos = 6
    topics = []
    for _ in range(n):
        (ln,) = struct.unpack_from(">h", blob, pos)
        pos += 2
        topics.append(blob[pos : pos + ln].decode())
        pos += ln
    return topics


def encode_assignment(assignment: dict[str, list[int]]) -> bytes:
    out = struct.pack(">hi", 0, len(assignment))
    for t, parts in assignment.items():
        tb = t.encode()
        out += struct.pack(">h", len(tb)) + tb
        out += struct.pack(">i", len(parts))
        for p in parts:
            out += struct.pack(">i", p)
    out += struct.pack(">i", 0)  # user data
    return out


def decode_assignment(blob: bytes) -> dict[str, list[int]]:
    if not blob:
        return {}
    (_version, n) = struct.unpack_from(">hi", blob, 0)
    pos = 6
    out: dict[str, list[int]] = {}
    for _ in range(n):
        (ln,) = struct.unpack_from(">h", blob, pos)
        pos += 2
        t = blob[pos : pos + ln].decode()
        pos += ln
        (np,) = struct.unpack_from(">i", blob, pos)
        pos += 4
        parts = list(struct.unpack_from(f">{np}i", blob, pos))
        pos += 4 * np
        out[t] = parts
    return out


def range_assign(
    members: list[tuple[str, list[str]]], partitions_by_topic: dict[str, int]
) -> dict[str, dict[str, list[int]]]:
    """Range assignor (assignment_plans.cc range strategy): per topic,
    contiguous chunks to subscribed members sorted by member id."""
    out: dict[str, dict[str, list[int]]] = {mid: {} for mid, _ in members}
    for topic, n_parts in partitions_by_topic.items():
        subscribed = sorted(mid for mid, topics in members if topic in topics)
        if not subscribed:
            continue
        per = n_parts // len(subscribed)
        extra = n_parts % len(subscribed)
        at = 0
        for i, mid in enumerate(subscribed):
            take = per + (1 if i < extra else 0)
            if take:
                out[mid].setdefault(topic, []).extend(range(at, at + take))
            at += take
    return out


class GroupConsumer:
    """join → (leader assigns) → sync → heartbeat fiber → fetch/commit."""

    def __init__(
        self,
        client,  # KafkaClient
        group_id: str,
        topics: list[str],
        session_timeout_ms: int = 10_000,
        heartbeat_interval_s: float = 1.0,
    ) -> None:
        self.client = client
        self.group_id = group_id
        self.topics = topics
        self.session_timeout_ms = session_timeout_ms
        self.heartbeat_interval_s = heartbeat_interval_s
        self.member_id = ""
        self.generation = -1
        self.assignment: dict[str, list[int]] = {}
        self._coord = None  # BrokerConnection
        self._hb_task: asyncio.Task | None = None
        self._positions: dict[tuple[str, int], int] = {}
        self.rejoin_needed = False

    # ------------------------------------------------------------ membership
    async def _coordinator(self):
        if self._coord is None:
            # coordinator_not_available is a POLL signal, not a failure:
            # right after the group topic's creation (or a coordinator
            # node's death) the partition is mid-election. Standard client
            # behavior is retry-with-backoff until the deadline.
            deadline = asyncio.get_event_loop().time() + 15.0
            while True:
                conn = await self.client.any_connection()
                resp = await conn.request(
                    m.FIND_COORDINATOR, {"key": self.group_id, "key_type": 0}
                )
                code = resp["error_code"]
                if code == 0:
                    break
                if (
                    code != int(ErrorCode.coordinator_not_available)
                    or asyncio.get_event_loop().time() > deadline
                ):
                    raise KafkaError(ErrorCode(code), "find_coordinator")
                await asyncio.sleep(0.25)
            await self.client.refresh_metadata()
            if resp["node_id"] in self.client._brokers:
                self._coord = await self.client.connection_for(resp["node_id"])
            else:
                self._coord = conn
        return self._coord

    async def join(self) -> "GroupConsumer":
        coord = await self._coordinator()
        sub = encode_subscription(self.topics)
        while True:
            resp = await coord.request(m.JOIN_GROUP, {
                "group_id": self.group_id,
                "session_timeout_ms": self.session_timeout_ms,
                "rebalance_timeout_ms": self.session_timeout_ms,
                "member_id": self.member_id,
                "group_instance_id": None,
                "protocol_type": "consumer",
                "protocols": [{"name": "range", "metadata": sub}],
            })
            code = ErrorCode(resp["error_code"])
            if code == ErrorCode.unknown_member_id and self.member_id:
                self.member_id = ""
                continue
            if code != ErrorCode.none:
                raise KafkaError(code, "join_group")
            break
        self.member_id = resp["member_id"]
        self.generation = resp["generation_id"]
        assignments = []
        if resp["leader"] == self.member_id:
            member_subs = [
                (mm["member_id"], decode_subscription(mm["metadata"]))
                for mm in resp["members"]
            ]
            all_topics = sorted({t for _, ts in member_subs for t in ts})
            md = await self.client.refresh_metadata(all_topics)
            parts = {
                t["name"]: len(t.get("partitions") or [])
                for t in md["topics"]
                if t["error_code"] == 0
            }
            plan = range_assign(member_subs, parts)
            assignments = [
                {"member_id": mid, "assignment": encode_assignment(a)}
                for mid, a in plan.items()
            ]
        sresp = await coord.request(m.SYNC_GROUP, {
            "group_id": self.group_id,
            "generation_id": self.generation,
            "member_id": self.member_id,
            "group_instance_id": None,
            "assignments": assignments,
        })
        if sresp["error_code"] != 0:
            raise KafkaError(ErrorCode(sresp["error_code"]), "sync_group")
        self.assignment = decode_assignment(sresp["assignment"])
        self.rejoin_needed = False
        if self._hb_task is None or self._hb_task.done():
            self._hb_task = asyncio.create_task(self._heartbeat_loop())
        # restore committed positions (-1 = no commit yet → start at 0)
        for topic, plist in self.assignment.items():
            fetched = await self.fetch_committed(topic, plist)
            for p, off in fetched.items():
                self._positions[(topic, p)] = max(off, 0)
        return self

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_interval_s)
            try:
                coord = await self._coordinator()
                resp = await coord.request(m.HEARTBEAT, {
                    "group_id": self.group_id,
                    "generation_id": self.generation,
                    "member_id": self.member_id,
                    "group_instance_id": None,
                })
                code = ErrorCode(resp["error_code"])
                if code == ErrorCode.rebalance_in_progress:
                    self.rejoin_needed = True
                elif code in (ErrorCode.unknown_member_id, ErrorCode.illegal_generation):
                    self.rejoin_needed = True
                    return
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("heartbeat failed", exc_info=True)

    async def poll(self, max_records: int = 500) -> dict[tuple[str, int], list]:
        """Fetch from every assigned partition at the current position."""
        if self.rejoin_needed:
            await self.join()
        out: dict[tuple[str, int], list] = {}
        for topic, plist in self.assignment.items():
            for p in plist:
                pos = self._positions.get((topic, p), 0)
                batches, hwm = await self.client.fetch(topic, p, pos, max_wait_ms=10)
                records = []
                for b in batches:
                    for i, r in enumerate(b.records()):
                        off = b.header.base_offset + r.offset_delta
                        if off >= pos:
                            records.append((off, r))
                if records:
                    out[(topic, p)] = records
                    self._positions[(topic, p)] = records[-1][0] + 1
        return out

    # ------------------------------------------------------------ offsets
    async def commit(self) -> None:
        topics: dict[str, list] = {}
        for (topic, p), pos in self._positions.items():
            topics.setdefault(topic, []).append({
                "partition_index": p,
                "committed_offset": pos,
                "committed_leader_epoch": -1,
                "committed_metadata": None,
            })
        if not topics:
            return
        coord = await self._coordinator()
        resp = await coord.request(m.OFFSET_COMMIT, {
            "group_id": self.group_id,
            "generation_id": self.generation,
            "member_id": self.member_id,
            "group_instance_id": None,
            "retention_time_ms": -1,
            "topics": [{"name": t, "partitions": ps} for t, ps in topics.items()],
        })
        for t in resp["topics"]:
            for p in t["partitions"]:
                if p["error_code"] != 0:
                    raise KafkaError(ErrorCode(p["error_code"]), f"offset_commit {t['name']}")

    async def fetch_committed(self, topic: str, partitions: list[int]) -> dict[int, int]:
        coord = await self._coordinator()
        resp = await coord.request(m.OFFSET_FETCH, {
            "group_id": self.group_id,
            "topics": [{"name": topic, "partition_indexes": partitions}],
        })
        out = {}
        for t in resp.get("topics") or []:
            for p in t["partitions"]:
                out[p["partition_index"]] = p["committed_offset"]
        return out

    async def leave(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except asyncio.CancelledError:
                pass
            self._hb_task = None
        if self.member_id:
            coord = await self._coordinator()
            await coord.request(m.LEAVE_GROUP, {
                "group_id": self.group_id,
                "member_id": self.member_id,
                "members": [{"member_id": self.member_id, "group_instance_id": None}],
            })
            self.member_id = ""
