"""Idempotent + transactional producer.

Parity with kafka/client produce_batcher + the reference ducktape
tx-verifier's client behavior: InitProducerId, per-partition sequence
numbering, AddPartitionsToTxn before first transactional send, EndTxn
commit/abort, and send_offsets for consume-transform-produce EOS.
"""

from __future__ import annotations

import asyncio

from redpanda_tpu.kafka.protocol import messages as m
from redpanda_tpu.kafka.protocol.errors import ErrorCode, KafkaError
from redpanda_tpu.models.record import Record, RecordBatch


class TransactionalProducer:
    def __init__(self, client, transactional_id: str | None = None, timeout_ms: int = 60_000):
        self.client = client
        self.transactional_id = transactional_id
        self.timeout_ms = timeout_ms
        self.producer_id = -1
        self.epoch = -1
        self._seqs: dict[tuple[str, int], int] = {}
        self._in_tx_partitions: set[tuple[str, int]] = set()
        self._tx_open = False

    # Transient coordination states (elections, dissemination lag, an
    # in-flight previous transaction) are POLL signals on every tx RPC:
    _RETRIABLE = frozenset({
        int(ErrorCode.coordinator_not_available),
        int(ErrorCode.not_leader_for_partition),
        int(ErrorCode.concurrent_transactions),
    })

    async def _tx_request(self, api, body: dict, what: str, get_code) -> dict:
        deadline = asyncio.get_event_loop().time() + 30.0
        while True:
            conn = await self.client.any_connection()
            resp = await conn.request(api, body)
            code = get_code(resp)
            if code == 0:
                return resp
            if (
                code not in self._RETRIABLE
                or asyncio.get_event_loop().time() > deadline
            ):
                raise KafkaError(ErrorCode(code), what)
            await asyncio.sleep(0.3)

    async def init(self) -> "TransactionalProducer":
        resp = await self._tx_request(
            m.INIT_PRODUCER_ID,
            {
                "transactional_id": self.transactional_id,
                "transaction_timeout_ms": self.timeout_ms,
            },
            "init_producer_id",
            lambda r: r["error_code"],
        )
        self.producer_id = resp["producer_id"]
        self.epoch = resp["producer_epoch"]
        return self

    # ------------------------------------------------------------ transactional
    def begin(self) -> None:
        if self.transactional_id is None:
            raise RuntimeError("begin() requires a transactional_id")
        self._tx_open = True
        self._in_tx_partitions.clear()

    async def _ensure_partition(self, topic: str, partition: int) -> None:
        if (topic, partition) in self._in_tx_partitions:
            return
        await self._tx_request(
            m.ADD_PARTITIONS_TO_TXN,
            {
                "transactional_id": self.transactional_id,
                "producer_id": self.producer_id,
                "producer_epoch": self.epoch,
                "topics": [{"name": topic, "partitions": [partition]}],
            },
            "add_partitions_to_txn",
            lambda r: r["results"][0]["results"][0]["error_code"],
        )
        self._in_tx_partitions.add((topic, partition))

    async def send(self, topic: str, partition: int, values: list[bytes]) -> int:
        transactional = self._tx_open
        if transactional:
            await self._ensure_partition(topic, partition)
        seq = self._seqs.get((topic, partition), 0)
        batch = RecordBatch.build(
            [Record(value=v, offset_delta=i) for i, v in enumerate(values)],
            producer_id=self.producer_id,
            producer_epoch=self.epoch,
            base_sequence=seq,
            transactional=transactional,
        )
        base = await self.client.produce_batches(topic, partition, [batch])
        self._seqs[(topic, partition)] = seq + len(values)
        return base

    async def send_offsets(
        self, group_id: str, offsets: dict[tuple[str, int], int]
    ) -> None:
        """EOS consume-transform-produce: stage group offsets inside the tx."""
        conn = await self.client.any_connection()
        resp = await conn.request(m.ADD_OFFSETS_TO_TXN, {
            "transactional_id": self.transactional_id,
            "producer_id": self.producer_id,
            "producer_epoch": self.epoch,
            "group_id": group_id,
        })
        if resp["error_code"] != 0:
            raise KafkaError(ErrorCode(resp["error_code"]), "add_offsets_to_txn")
        topics: dict[str, list] = {}
        for (topic, p), off in offsets.items():
            topics.setdefault(topic, []).append({
                "partition_index": p,
                "committed_offset": off,
                "committed_leader_epoch": -1,
                "committed_metadata": None,
            })
        resp = await conn.request(m.TXN_OFFSET_COMMIT, {
            "transactional_id": self.transactional_id,
            "group_id": group_id,
            "producer_id": self.producer_id,
            "producer_epoch": self.epoch,
            "topics": [{"name": t, "partitions": ps} for t, ps in topics.items()],
        })
        for t in resp["topics"]:
            for p in t["partitions"]:
                if p["error_code"] != 0:
                    raise KafkaError(ErrorCode(p["error_code"]), "txn_offset_commit")

    async def _end(self, commit: bool) -> None:
        # Retriable while the coordinator re-drives marker/offset fan-out
        # (state stays prepare_*): "again later", not failure.
        await self._tx_request(
            m.END_TXN,
            {
                "transactional_id": self.transactional_id,
                "producer_id": self.producer_id,
                "producer_epoch": self.epoch,
                "committed": commit,
            },
            "end_txn",
            lambda r: r["error_code"],
        )
        self._tx_open = False
        self._in_tx_partitions.clear()

    async def commit(self) -> None:
        await self._end(True)

    async def abort(self) -> None:
        await self._end(False)
