"""Kafka API message definitions.

Version-gated field tables for every API the broker serves — the runtime
analogue of the reference's kafka/protocol/schemata/*.json. Version ranges
match the reference snapshot's supported ranges where practical; flexible
versions are kept below the advertised max except where noted, since modern
clients negotiate down via ApiVersions.
"""

from __future__ import annotations

from redpanda_tpu.kafka.protocol.schema import Api, Array, F, T

# ------------------------------------------------------------------ api keys
PRODUCE = 0
FETCH = 1
LIST_OFFSETS = 2
METADATA = 3
OFFSET_COMMIT = 8
OFFSET_FETCH = 9
FIND_COORDINATOR = 10
JOIN_GROUP = 11
HEARTBEAT = 12
LEAVE_GROUP = 13
SYNC_GROUP = 14
DESCRIBE_GROUPS = 15
LIST_GROUPS = 16
SASL_HANDSHAKE = 17
API_VERSIONS = 18
CREATE_TOPICS = 19
DELETE_TOPICS = 20
DELETE_RECORDS = 21
INIT_PRODUCER_ID = 22
ADD_PARTITIONS_TO_TXN = 24
ADD_OFFSETS_TO_TXN = 25
END_TXN = 26
TXN_OFFSET_COMMIT = 28
DESCRIBE_ACLS = 29
CREATE_ACLS = 30
DELETE_ACLS = 31
DESCRIBE_CONFIGS = 32
ALTER_CONFIGS = 33
DESCRIBE_LOG_DIRS = 35
SASL_AUTHENTICATE = 36
CREATE_PARTITIONS = 37
DELETE_GROUPS = 42
INCREMENTAL_ALTER_CONFIGS = 44


def _api(key, name, min_v, max_v, request, response, flexible_since=None) -> Api:
    return Api(key, name, min_v, max_v, tuple(request), tuple(response), flexible_since)


APIS: dict[int, Api] = {}


def _register(api: Api) -> Api:
    APIS[api.key] = api
    return APIS[api.key]


# ------------------------------------------------------------------ produce
produce = _register(_api(
    PRODUCE, "produce", 0, 8,
    request=[
        F("transactional_id", T.NULLABLE_STRING, min_v=3),
        F("acks", T.INT16),
        F("timeout_ms", T.INT32),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("records", T.RECORDS),
            ))),
        ))),
    ],
    response=[
        F("responses", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("error_code", T.INT16),
                F("base_offset", T.INT64),
                F("log_append_time_ms", T.INT64, min_v=2, default=-1),
                F("log_start_offset", T.INT64, min_v=5),
                F("record_errors", Array((
                    F("batch_index", T.INT32),
                    F("batch_index_error_message", T.NULLABLE_STRING),
                )), min_v=8),
                F("error_message", T.NULLABLE_STRING, min_v=8),
            ))),
        ))),
        F("throttle_time_ms", T.INT32, min_v=1),
    ],
))

# ------------------------------------------------------------------ fetch
fetch = _register(_api(
    FETCH, "fetch", 0, 11,
    request=[
        F("replica_id", T.INT32, default=-1),
        F("max_wait_ms", T.INT32),
        F("min_bytes", T.INT32),
        F("max_bytes", T.INT32, min_v=3, default=0x7FFFFFFF),
        F("isolation_level", T.INT8, min_v=4),
        F("session_id", T.INT32, min_v=7),
        F("session_epoch", T.INT32, min_v=7, default=-1),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("current_leader_epoch", T.INT32, min_v=9, default=-1),
                F("fetch_offset", T.INT64),
                F("log_start_offset", T.INT64, min_v=5, default=-1),
                F("partition_max_bytes", T.INT32),
            ))),
        ))),
        F("forgotten_topics_data", Array((
            F("name", T.STRING),
            F("partitions", Array(T.INT32)),
        )), min_v=7),
        F("rack_id", T.STRING, min_v=11, default=""),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("error_code", T.INT16, min_v=7),
        F("session_id", T.INT32, min_v=7),
        F("responses", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("error_code", T.INT16),
                F("high_watermark", T.INT64),
                F("last_stable_offset", T.INT64, min_v=4, default=-1),
                F("log_start_offset", T.INT64, min_v=5, default=-1),
                F("aborted_transactions", Array((
                    F("producer_id", T.INT64),
                    F("first_offset", T.INT64),
                ), nullable=True), min_v=4),
                F("preferred_read_replica", T.INT32, min_v=11, default=-1),
                F("records", T.RECORDS),
            ))),
        ))),
    ],
))

# ------------------------------------------------------------------ list_offsets
list_offsets = _register(_api(
    LIST_OFFSETS, "list_offsets", 0, 5,
    request=[
        F("replica_id", T.INT32, default=-1),
        F("isolation_level", T.INT8, min_v=2),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("current_leader_epoch", T.INT32, min_v=4, default=-1),
                F("timestamp", T.INT64),
                F("max_num_offsets", T.INT32, max_v=0, default=1),
            ))),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=2),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("error_code", T.INT16),
                F("old_style_offsets", Array(T.INT64), max_v=0),
                F("timestamp", T.INT64, min_v=1, default=-1),
                F("offset", T.INT64, min_v=1, default=-1),
                F("leader_epoch", T.INT32, min_v=4, default=-1),
            ))),
        ))),
    ],
))

# ------------------------------------------------------------------ metadata
metadata = _register(_api(
    METADATA, "metadata", 0, 9, flexible_since=9,
    request=[
        F("topics", Array((
            F("name", T.STRING),
        ), nullable=True)),
        F("allow_auto_topic_creation", T.BOOL, min_v=4, default=True),
        F("include_cluster_authorized_operations", T.BOOL, min_v=8),
        F("include_topic_authorized_operations", T.BOOL, min_v=8),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=3),
        F("brokers", Array((
            F("node_id", T.INT32),
            F("host", T.STRING),
            F("port", T.INT32),
            F("rack", T.NULLABLE_STRING, min_v=1),
        ))),
        F("cluster_id", T.NULLABLE_STRING, min_v=2),
        F("controller_id", T.INT32, min_v=1, default=-1),
        F("topics", Array((
            F("error_code", T.INT16),
            F("name", T.STRING),
            F("is_internal", T.BOOL, min_v=1),
            F("partitions", Array((
                F("error_code", T.INT16),
                F("partition_index", T.INT32),
                F("leader_id", T.INT32),
                F("leader_epoch", T.INT32, min_v=7, default=-1),
                F("replica_nodes", Array(T.INT32)),
                F("isr_nodes", Array(T.INT32)),
                F("offline_replicas", Array(T.INT32), min_v=5),
            ))),
            F("topic_authorized_operations", T.INT32, min_v=8, default=-2147483648),
        ))),
        F("cluster_authorized_operations", T.INT32, min_v=8, default=-2147483648),
    ],
))

# ------------------------------------------------------------------ offset_commit
offset_commit = _register(_api(
    OFFSET_COMMIT, "offset_commit", 0, 8, flexible_since=8,
    request=[
        F("group_id", T.STRING),
        F("generation_id", T.INT32, min_v=1, default=-1),
        F("member_id", T.STRING, min_v=1, default=""),
        F("group_instance_id", T.NULLABLE_STRING, min_v=7),
        F("retention_time_ms", T.INT64, min_v=2, max_v=4, default=-1),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("committed_offset", T.INT64),
                F("commit_timestamp", T.INT64, min_v=1, max_v=1, default=-1),
                F("committed_leader_epoch", T.INT32, min_v=6, default=-1),
                F("committed_metadata", T.NULLABLE_STRING),
            ))),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=3),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("error_code", T.INT16),
            ))),
        ))),
    ],
))

# ------------------------------------------------------------------ offset_fetch
offset_fetch = _register(_api(
    OFFSET_FETCH, "offset_fetch", 0, 6, flexible_since=6,
    request=[
        F("group_id", T.STRING),
        F("topics", Array((
            F("name", T.STRING),
            F("partition_indexes", Array(T.INT32)),
        ), nullable=True)),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=3),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("committed_offset", T.INT64),
                F("committed_leader_epoch", T.INT32, min_v=5, default=-1),
                F("metadata", T.NULLABLE_STRING),
                F("error_code", T.INT16),
            ))),
        ))),
        F("error_code", T.INT16, min_v=2),
    ],
))

# ------------------------------------------------------------------ find_coordinator
find_coordinator = _register(_api(
    FIND_COORDINATOR, "find_coordinator", 0, 3, flexible_since=3,
    request=[
        F("key", T.STRING),
        F("key_type", T.INT8, min_v=1),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("error_code", T.INT16),
        F("error_message", T.NULLABLE_STRING, min_v=1),
        F("node_id", T.INT32),
        F("host", T.STRING),
        F("port", T.INT32),
    ],
))

# ------------------------------------------------------------------ group membership
join_group = _register(_api(
    JOIN_GROUP, "join_group", 0, 6, flexible_since=6,
    request=[
        F("group_id", T.STRING),
        F("session_timeout_ms", T.INT32),
        F("rebalance_timeout_ms", T.INT32, min_v=1, default=-1),
        F("member_id", T.STRING),
        F("group_instance_id", T.NULLABLE_STRING, min_v=5),
        F("protocol_type", T.STRING),
        F("protocols", Array((
            F("name", T.STRING),
            F("metadata", T.BYTES),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=2),
        F("error_code", T.INT16),
        F("generation_id", T.INT32, default=-1),
        F("protocol_name", T.STRING),
        F("leader", T.STRING),
        F("member_id", T.STRING),
        F("members", Array((
            F("member_id", T.STRING),
            F("group_instance_id", T.NULLABLE_STRING, min_v=5),
            F("metadata", T.BYTES),
        ))),
    ],
))

heartbeat = _register(_api(
    HEARTBEAT, "heartbeat", 0, 4, flexible_since=4,
    request=[
        F("group_id", T.STRING),
        F("generation_id", T.INT32),
        F("member_id", T.STRING),
        F("group_instance_id", T.NULLABLE_STRING, min_v=3),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("error_code", T.INT16),
    ],
))

leave_group = _register(_api(
    LEAVE_GROUP, "leave_group", 0, 4, flexible_since=4,
    request=[
        F("group_id", T.STRING),
        F("member_id", T.STRING, max_v=2),
        F("members", Array((
            F("member_id", T.STRING),
            F("group_instance_id", T.NULLABLE_STRING),
        )), min_v=3),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("error_code", T.INT16),
        F("members", Array((
            F("member_id", T.STRING),
            F("group_instance_id", T.NULLABLE_STRING),
            F("error_code", T.INT16),
        )), min_v=3),
    ],
))

sync_group = _register(_api(
    SYNC_GROUP, "sync_group", 0, 4, flexible_since=4,
    request=[
        F("group_id", T.STRING),
        F("generation_id", T.INT32),
        F("member_id", T.STRING),
        F("group_instance_id", T.NULLABLE_STRING, min_v=3),
        F("assignments", Array((
            F("member_id", T.STRING),
            F("assignment", T.BYTES),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("error_code", T.INT16),
        F("assignment", T.BYTES),
    ],
))

describe_groups = _register(_api(
    DESCRIBE_GROUPS, "describe_groups", 0, 5, flexible_since=5,
    request=[
        F("groups", Array(T.STRING)),
        F("include_authorized_operations", T.BOOL, min_v=3),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("groups", Array((
            F("error_code", T.INT16),
            F("group_id", T.STRING),
            F("group_state", T.STRING),
            F("protocol_type", T.STRING),
            F("protocol_data", T.STRING),
            F("members", Array((
                F("member_id", T.STRING),
                F("group_instance_id", T.NULLABLE_STRING, min_v=4),
                F("client_id", T.STRING),
                F("client_host", T.STRING),
                F("member_metadata", T.BYTES),
                F("member_assignment", T.BYTES),
            ))),
            F("authorized_operations", T.INT32, min_v=3, default=-2147483648),
        ))),
    ],
))

list_groups = _register(_api(
    LIST_GROUPS, "list_groups", 0, 3, flexible_since=3,
    request=[],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("error_code", T.INT16),
        F("groups", Array((
            F("group_id", T.STRING),
            F("protocol_type", T.STRING),
        ))),
    ],
))

delete_groups = _register(_api(
    DELETE_GROUPS, "delete_groups", 0, 2, flexible_since=2,
    request=[
        F("groups_names", Array(T.STRING)),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("results", Array((
            F("group_id", T.STRING),
            F("error_code", T.INT16),
        ))),
    ],
))

# ------------------------------------------------------------------ sasl
sasl_handshake = _register(_api(
    SASL_HANDSHAKE, "sasl_handshake", 0, 1,
    request=[F("mechanism", T.STRING)],
    response=[
        F("error_code", T.INT16),
        F("mechanisms", Array(T.STRING)),
    ],
))

sasl_authenticate = _register(_api(
    SASL_AUTHENTICATE, "sasl_authenticate", 0, 1,
    request=[F("auth_bytes", T.BYTES)],
    response=[
        F("error_code", T.INT16),
        F("error_message", T.NULLABLE_STRING),
        F("auth_bytes", T.BYTES),
        F("session_lifetime_ms", T.INT64, min_v=1),
    ],
))

# ------------------------------------------------------------------ api_versions
api_versions = _register(_api(
    API_VERSIONS, "api_versions", 0, 3, flexible_since=3,
    request=[
        F("client_software_name", T.STRING, min_v=3),
        F("client_software_version", T.STRING, min_v=3),
    ],
    response=[
        F("error_code", T.INT16),
        F("api_keys", Array((
            F("api_key", T.INT16),
            F("min_version", T.INT16),
            F("max_version", T.INT16),
        ))),
        F("throttle_time_ms", T.INT32, min_v=1),
    ],
))

# ------------------------------------------------------------------ topic admin
create_topics = _register(_api(
    CREATE_TOPICS, "create_topics", 0, 5, flexible_since=5,
    request=[
        F("topics", Array((
            F("name", T.STRING),
            F("num_partitions", T.INT32),
            F("replication_factor", T.INT16),
            F("assignments", Array((
                F("partition_index", T.INT32),
                F("broker_ids", Array(T.INT32)),
            ))),
            F("configs", Array((
                F("name", T.STRING),
                F("value", T.NULLABLE_STRING),
            ))),
        ))),
        F("timeout_ms", T.INT32),
        F("validate_only", T.BOOL, min_v=1),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=2),
        F("topics", Array((
            F("name", T.STRING),
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING, min_v=1),
            F("topic_config_error_code", T.INT16, min_v=5, tag=0),
            F("num_partitions", T.INT32, min_v=5, default=-1),
            F("replication_factor", T.INT16, min_v=5, default=-1),
            F("configs", Array((
                F("name", T.STRING),
                F("value", T.NULLABLE_STRING),
                F("read_only", T.BOOL),
                F("config_source", T.INT8, default=-1),
                F("is_sensitive", T.BOOL),
            ), nullable=True), min_v=5),
        ))),
    ],
))

delete_topics = _register(_api(
    DELETE_TOPICS, "delete_topics", 0, 4, flexible_since=4,
    request=[
        F("topic_names", Array(T.STRING)),
        F("timeout_ms", T.INT32),
    ],
    response=[
        F("throttle_time_ms", T.INT32, min_v=1),
        F("responses", Array((
            F("name", T.STRING),
            F("error_code", T.INT16),
        ))),
    ],
))

delete_records = _register(_api(
    DELETE_RECORDS, "delete_records", 0, 1,
    request=[
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("offset", T.INT64),
            ))),
        ))),
        F("timeout_ms", T.INT32),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("low_watermark", T.INT64),
                F("error_code", T.INT16),
            ))),
        ))),
    ],
))

create_partitions = _register(_api(
    CREATE_PARTITIONS, "create_partitions", 0, 3, flexible_since=2,
    request=[
        F("topics", Array((
            F("name", T.STRING),
            F("count", T.INT32),
            F("assignments", Array((
                F("broker_ids", Array(T.INT32)),
            ), nullable=True)),
        ))),
        F("timeout_ms", T.INT32),
        F("validate_only", T.BOOL),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("results", Array((
            F("name", T.STRING),
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING),
        ))),
    ],
))

# ------------------------------------------------------------------ configs
describe_configs = _register(_api(
    DESCRIBE_CONFIGS, "describe_configs", 0, 2,
    request=[
        F("resources", Array((
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
            F("configuration_keys", Array(T.STRING, nullable=True)),
        ))),
        F("include_synonyms", T.BOOL, min_v=1),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("results", Array((
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING),
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
            F("configs", Array((
                F("name", T.STRING),
                F("value", T.NULLABLE_STRING),
                F("read_only", T.BOOL),
                F("is_default", T.BOOL, max_v=0),
                F("config_source", T.INT8, min_v=1, default=-1),
                F("is_sensitive", T.BOOL),
                F("synonyms", Array((
                    F("name", T.STRING),
                    F("value", T.NULLABLE_STRING),
                    F("source", T.INT8),
                )), min_v=1),
            ))),
        ))),
    ],
))

alter_configs = _register(_api(
    ALTER_CONFIGS, "alter_configs", 0, 1,
    request=[
        F("resources", Array((
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
            F("configs", Array((
                F("name", T.STRING),
                F("value", T.NULLABLE_STRING),
            ))),
        ))),
        F("validate_only", T.BOOL),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("responses", Array((
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING),
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
        ))),
    ],
))

incremental_alter_configs = _register(_api(
    INCREMENTAL_ALTER_CONFIGS, "incremental_alter_configs", 0, 1, flexible_since=1,
    request=[
        F("resources", Array((
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
            F("configs", Array((
                F("name", T.STRING),
                F("config_operation", T.INT8),
                F("value", T.NULLABLE_STRING),
            ))),
        ))),
        F("validate_only", T.BOOL),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("responses", Array((
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING),
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
        ))),
    ],
))

describe_log_dirs = _register(_api(
    DESCRIBE_LOG_DIRS, "describe_log_dirs", 0, 1,
    request=[
        F("topics", Array((
            F("topic", T.STRING),
            F("partitions", Array(T.INT32)),
        ), nullable=True)),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("results", Array((
            F("error_code", T.INT16),
            F("log_dir", T.STRING),
            F("topics", Array((
                F("name", T.STRING),
                F("partitions", Array((
                    F("partition_index", T.INT32),
                    F("partition_size", T.INT64),
                    F("offset_lag", T.INT64),
                    F("is_future_key", T.BOOL),
                ))),
            ))),
        ))),
    ],
))

# ------------------------------------------------------------------ acls
_ACL_FILTER_REQ = [
    F("resource_type_filter", T.INT8),
    F("resource_name_filter", T.NULLABLE_STRING),
    F("pattern_type_filter", T.INT8, min_v=1, default=3),
    F("principal_filter", T.NULLABLE_STRING),
    F("host_filter", T.NULLABLE_STRING),
    F("operation", T.INT8),
    F("permission_type", T.INT8),
]

describe_acls = _register(_api(
    DESCRIBE_ACLS, "describe_acls", 0, 1,
    request=list(_ACL_FILTER_REQ),
    response=[
        F("throttle_time_ms", T.INT32),
        F("error_code", T.INT16),
        F("error_message", T.NULLABLE_STRING),
        F("resources", Array((
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
            F("pattern_type", T.INT8, min_v=1, default=3),
            F("acls", Array((
                F("principal", T.STRING),
                F("host", T.STRING),
                F("operation", T.INT8),
                F("permission_type", T.INT8),
            ))),
        ))),
    ],
))

create_acls = _register(_api(
    CREATE_ACLS, "create_acls", 0, 1,
    request=[
        F("creations", Array((
            F("resource_type", T.INT8),
            F("resource_name", T.STRING),
            F("resource_pattern_type", T.INT8, min_v=1, default=3),
            F("principal", T.STRING),
            F("host", T.STRING),
            F("operation", T.INT8),
            F("permission_type", T.INT8),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("results", Array((
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING),
        ))),
    ],
))

delete_acls = _register(_api(
    DELETE_ACLS, "delete_acls", 0, 1,
    request=[
        F("filters", Array((
            F("resource_type_filter", T.INT8),
            F("resource_name_filter", T.NULLABLE_STRING),
            F("pattern_type_filter", T.INT8, min_v=1, default=3),
            F("principal_filter", T.NULLABLE_STRING),
            F("host_filter", T.NULLABLE_STRING),
            F("operation", T.INT8),
            F("permission_type", T.INT8),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("filter_results", Array((
            F("error_code", T.INT16),
            F("error_message", T.NULLABLE_STRING),
            F("matching_acls", Array((
                F("error_code", T.INT16),
                F("error_message", T.NULLABLE_STRING),
                F("resource_type", T.INT8),
                F("resource_name", T.STRING),
                F("pattern_type", T.INT8, min_v=1, default=3),
                F("principal", T.STRING),
                F("host", T.STRING),
                F("operation", T.INT8),
                F("permission_type", T.INT8),
            ))),
        ))),
    ],
))

# ------------------------------------------------------------------ transactions
init_producer_id = _register(_api(
    INIT_PRODUCER_ID, "init_producer_id", 0, 2, flexible_since=2,
    request=[
        F("transactional_id", T.NULLABLE_STRING),
        F("transaction_timeout_ms", T.INT32),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("error_code", T.INT16),
        F("producer_id", T.INT64, default=-1),
        F("producer_epoch", T.INT16, default=-1),
    ],
))

add_partitions_to_txn = _register(_api(
    ADD_PARTITIONS_TO_TXN, "add_partitions_to_txn", 0, 3, flexible_since=3,
    request=[
        F("transactional_id", T.STRING),
        F("producer_id", T.INT64),
        F("producer_epoch", T.INT16),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array(T.INT32)),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("results", Array((
            F("name", T.STRING),
            F("results", Array((
                F("partition_index", T.INT32),
                F("error_code", T.INT16),
            ))),
        ))),
    ],
))

add_offsets_to_txn = _register(_api(
    ADD_OFFSETS_TO_TXN, "add_offsets_to_txn", 0, 1,
    request=[
        F("transactional_id", T.STRING),
        F("producer_id", T.INT64),
        F("producer_epoch", T.INT16),
        F("group_id", T.STRING),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("error_code", T.INT16),
    ],
))

end_txn = _register(_api(
    END_TXN, "end_txn", 0, 3, flexible_since=3,
    request=[
        F("transactional_id", T.STRING),
        F("producer_id", T.INT64),
        F("producer_epoch", T.INT16),
        F("committed", T.BOOL),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("error_code", T.INT16),
    ],
))

txn_offset_commit = _register(_api(
    TXN_OFFSET_COMMIT, "txn_offset_commit", 0, 2,
    request=[
        F("transactional_id", T.STRING),
        F("group_id", T.STRING),
        F("producer_id", T.INT64),
        F("producer_epoch", T.INT16),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("committed_offset", T.INT64),
                F("committed_leader_epoch", T.INT32, min_v=2, default=-1),
                F("committed_metadata", T.NULLABLE_STRING),
            ))),
        ))),
    ],
    response=[
        F("throttle_time_ms", T.INT32),
        F("topics", Array((
            F("name", T.STRING),
            F("partitions", Array((
                F("partition_index", T.INT32),
                F("error_code", T.INT16),
            ))),
        ))),
    ],
))
