"""Declarative Kafka message schemas.

The reference generates C++ request/response structs from 64 JSON message
schemas (kafka/protocol/schemata/generator.py). Here the same information is
expressed as Python field tables interpreted at runtime: each API declares a
list of version-gated fields; ``encode``/``decode`` walk the table for a
concrete api_version, handling both classic and flexible (KIP-482 compact +
tagged-field) encodings. Messages travel as plain dicts, so handlers and the
embedded client share one representation.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from redpanda_tpu.kafka.protocol.primitives import Reader, Writer


# ------------------------------------------------------------------ types
class T:
    """Scalar wire types."""

    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT32 = "uint32"
    FLOAT64 = "float64"
    BOOL = "bool"
    VARINT = "varint"
    UUID = "uuid"
    STRING = "string"
    NULLABLE_STRING = "nullable_string"
    BYTES = "bytes"
    NULLABLE_BYTES = "nullable_bytes"
    # Record batches travel as NULLABLE_BYTES on the wire; kept distinct so
    # the server can route them through the batch adapter / device CRC kernel.
    RECORDS = "records"


@dataclass(frozen=True)
class Array:
    inner: object  # scalar T.* or tuple[Field, ...]
    nullable: bool = False


@dataclass(frozen=True)
class Field:
    name: str
    typ: object  # T.* | Array
    versions: tuple[int, int | None] = (0, None)  # inclusive; None = open
    default: object = None
    tag: int | None = None  # tagged field number (flexible versions only)

    def present(self, v: int) -> bool:
        lo, hi = self.versions
        return v >= lo and (hi is None or v <= hi)


def F(name, typ, min_v=0, max_v=None, default=None, tag=None) -> Field:
    return Field(name, typ, (min_v, max_v), default, tag)


@dataclass(frozen=True)
class Api:
    key: int
    name: str
    min_version: int
    max_version: int
    request: tuple[Field, ...]
    response: tuple[Field, ...]
    flexible_since: int | None = None  # first flexible version, or None

    def is_flexible(self, v: int) -> bool:
        return self.flexible_since is not None and v >= self.flexible_since


# ------------------------------------------------------------------ encode
_SCALAR_WRITERS = {
    T.INT8: Writer.int8,
    T.INT16: Writer.int16,
    T.INT32: Writer.int32,
    T.INT64: Writer.int64,
    T.UINT32: Writer.uint32,
    T.FLOAT64: Writer.float64,
    T.BOOL: Writer.boolean,
    T.VARINT: Writer.varint,
    T.UUID: Writer.uuid,
}

_SCALAR_DEFAULTS = {
    T.INT8: 0,
    T.INT16: 0,
    T.INT32: 0,
    T.INT64: 0,
    T.UINT32: 0,
    T.FLOAT64: 0.0,
    T.BOOL: False,
    T.VARINT: 0,
    T.STRING: "",
    T.NULLABLE_STRING: None,
    T.BYTES: b"",
    T.NULLABLE_BYTES: None,
    T.RECORDS: None,
    T.UUID: b"\x00" * 16,
}


def _write_value(w: Writer, typ, value, v: int, flexible: bool) -> None:
    if isinstance(typ, Array):
        if isinstance(typ.inner, tuple):
            fn = lambda wr, item: _write_struct(wr, typ.inner, item, v, flexible)
        else:
            sw = _scalar_writer_for(typ.inner, flexible)
            fn = lambda wr, item: sw(wr, item)
        if flexible:
            w.compact_array(value, fn)
        else:
            w.array(value, fn)
        return
    sw = _scalar_writer_for(typ, flexible)
    sw(w, value)


def _scalar_writer_for(typ, flexible: bool):
    if typ == T.STRING:
        return Writer.compact_string if flexible else Writer.string
    if typ == T.NULLABLE_STRING:
        return Writer.compact_nullable_string if flexible else Writer.nullable_string
    if typ == T.BYTES:
        return Writer.compact_bytes if flexible else Writer.bytes_
    if typ in (T.NULLABLE_BYTES, T.RECORDS):
        return Writer.compact_nullable_bytes if flexible else Writer.nullable_bytes
    return _SCALAR_WRITERS[typ]


def _default_for(f: Field):
    if f.default is not None:
        return f.default
    typ = f.typ
    if isinstance(typ, Array):
        return None if typ.nullable else []
    return _SCALAR_DEFAULTS[typ]


def _write_struct(w: Writer, fields: tuple[Field, ...], msg: dict, v: int, flexible: bool) -> None:
    tagged: list[Field] = []
    for f in fields:
        if not f.present(v):
            continue
        if f.tag is not None and flexible:
            tagged.append(f)
            continue
        value = msg.get(f.name, _default_for(f))
        _write_value(w, f.typ, value, v, flexible)
    if flexible:
        tf: dict[int, bytes] = {}
        for f in tagged:
            if f.name in msg and msg[f.name] != _default_for(f):
                inner = Writer()
                _write_value(inner, f.typ, msg[f.name], v, flexible)
                tf[f.tag] = inner.build()
        w.tagged_fields(tf)


# ------------------------------------------------------------------ decode
_SCALAR_READERS = {
    T.INT8: Reader.int8,
    T.INT16: Reader.int16,
    T.INT32: Reader.int32,
    T.INT64: Reader.int64,
    T.UINT32: Reader.uint32,
    T.FLOAT64: Reader.float64,
    T.BOOL: Reader.boolean,
    T.VARINT: Reader.varint,
    T.UUID: Reader.uuid,
}


def _scalar_reader_for(typ, flexible: bool):
    if typ == T.STRING:
        return Reader.compact_string if flexible else Reader.string
    if typ == T.NULLABLE_STRING:
        return Reader.compact_nullable_string if flexible else Reader.nullable_string
    if typ == T.BYTES:
        return Reader.compact_bytes if flexible else Reader.bytes_
    if typ in (T.NULLABLE_BYTES, T.RECORDS):
        return Reader.compact_nullable_bytes if flexible else Reader.nullable_bytes
    return _SCALAR_READERS[typ]


def _read_value(r: Reader, typ, v: int, flexible: bool):
    if isinstance(typ, Array):
        if isinstance(typ.inner, tuple):
            fn = lambda rd: _read_struct(rd, typ.inner, v, flexible)
        else:
            sr = _scalar_reader_for(typ.inner, flexible)
            fn = lambda rd: sr(rd)
        return r.compact_array(fn) if flexible else r.array(fn)
    return _scalar_reader_for(typ, flexible)(r)


def _read_struct(r: Reader, fields: tuple[Field, ...], v: int, flexible: bool) -> dict:
    msg: dict = {}
    tagged_by_num: dict[int, Field] = {}
    for f in fields:
        if not f.present(v):
            continue
        if f.tag is not None and flexible:
            tagged_by_num[f.tag] = f
            msg[f.name] = _default_for(f)
            continue
        msg[f.name] = _read_value(r, f.typ, v, flexible)
    if flexible:
        for tag, raw in r.tagged_fields().items():
            f = tagged_by_num.get(tag)
            if f is not None:
                msg[f.name] = _read_value(Reader(raw), f.typ, v, flexible)
            else:
                msg.setdefault("_unknown_tags", {})[tag] = raw
    return msg


# ------------------------------------------------------------------ api surface
def encode_message(api: Api, which: str, msg: dict, version: int) -> bytes:
    fields = api.request if which == "request" else api.response
    w = Writer()
    _write_struct(w, fields, msg, version, api.is_flexible(version))
    return w.build()


def decode_message(api: Api, which: str, buf, version: int) -> dict:
    fields = api.request if which == "request" else api.response
    return _read_struct(Reader(buf), fields, version, api.is_flexible(version))


# ------------------------------------------------------------------ headers
@dataclass
class RequestHeader:
    api_key: int
    api_version: int
    correlation_id: int
    client_id: str | None = None

    def encode(self, flexible: bool) -> bytes:
        w = Writer()
        w.int16(self.api_key).int16(self.api_version).int32(self.correlation_id)
        w.nullable_string(self.client_id)
        if flexible:
            w.tagged_fields()
        return w.build()

    @staticmethod
    def decode(r: Reader, flexible: bool) -> "RequestHeader":
        h = RequestHeader(r.int16(), r.int16(), r.int32(), r.nullable_string())
        if flexible:
            r.tagged_fields()
        return h


def encode_response_header(correlation_id: int, flexible: bool) -> bytes:
    w = Writer()
    w.int32(correlation_id)
    if flexible:
        w.tagged_fields()
    return w.build()
