"""Kafka wire encoding: primitives, record-batch adapter, message schemas.

Parity with the reference's src/v/kafka/protocol — request_reader /
response_writer primitives, kafka_batch_adapter, and the request/response
structs codegenned from protocol/schemata/*.json (here: declarative Python
schemas interpreted at runtime instead of generated C++).
"""

from redpanda_tpu.kafka.protocol.primitives import Reader, Writer
from redpanda_tpu.kafka.protocol.errors import ErrorCode

__all__ = ["Reader", "Writer", "ErrorCode"]
