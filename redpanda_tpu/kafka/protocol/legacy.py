"""Legacy (magic 0/1) MessageSet up-conversion for old produce versions.

Parity with the reference's legacy path (kafka/protocol/legacy_message.h:40
decode_legacy_batch, kafka/protocol/kafka_batch_adapter.cc
convert_message_set/adapt_with_version): produce v0-2 carries a MessageSet —
a packed sequence of

    offset      int64 BE
    length      int32 BE   (bytes after this field)
    crc         int32 BE   (CRC-32 — zlib crc32, NOT crc32c — over magic..value)
    magic       int8       (0 or 1)
    attributes  int8       (low 3 bits: compression codec)
    [timestamp  int64 BE]  (magic 1 only)
    key         int32-prefixed bytes (-1 = null)
    value       int32-prefixed bytes (-1 = null)

A compressed message's value wraps a nested MessageSet (one level deep).
The whole set converts into ONE v2/internal RecordBatch so the rest of the
produce path (raft, storage, fetch) only ever sees modern batches.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass

from redpanda_tpu.models.record import Compression, Record, RecordBatch

# attributes bits 0-2 select the codec (legacy_message.h compression_mask)
_COMPRESSION_MASK = 0x07
_LEGACY_CODECS = {
    0: Compression.none,
    1: Compression.gzip,
    2: Compression.snappy,
    3: Compression.lz4,
}


class LegacyBatchError(Exception):
    """Malformed/unverifiable legacy message set (answers corrupt_message)."""


class LegacyUnsupportedError(Exception):
    """Valid but unsupported legacy form (magic-0 + lz4: Kafka's magic-0 lz4
    framing was buggy and clients themselves refuse it)."""


@dataclass
class _LegacyMessage:
    magic: int
    attributes: int
    timestamp: int | None
    key: bytes | None
    value: bytes | None

    @property
    def compression(self) -> Compression:
        codec = self.attributes & _COMPRESSION_MASK
        if codec not in _LEGACY_CODECS:
            raise LegacyBatchError(f"unknown legacy compression {codec}")
        return _LEGACY_CODECS[codec]


def _decode_one(buf: memoryview, pos: int) -> tuple[_LegacyMessage, int]:
    if len(buf) - pos < 12:
        raise LegacyBatchError("short legacy message header")
    _offset, length = struct.unpack_from(">qi", buf, pos)
    pos += 12
    if length < 6 or pos + length > len(buf):
        raise LegacyBatchError(f"bad legacy message length {length}")
    end = pos + length
    (expected_crc,) = struct.unpack_from(">i", buf, pos)
    # the crc covers everything after the crc field, magic through value
    computed = zlib.crc32(buf[pos + 4 : end]) & 0xFFFFFFFF
    if computed != expected_crc & 0xFFFFFFFF:
        raise LegacyBatchError(
            f"legacy crc mismatch: expected {expected_crc & 0xFFFFFFFF:#x},"
            f" computed {computed:#x}"
        )
    pos += 4
    magic, attributes = struct.unpack_from(">bb", buf, pos)
    pos += 2
    if magic not in (0, 1):
        raise LegacyBatchError(f"expected magic 0 or 1, got {magic}")
    timestamp = None
    if magic == 1:
        if pos + 8 > end:
            raise LegacyBatchError("legacy message too short for timestamp")
        (timestamp,) = struct.unpack_from(">q", buf, pos)
        pos += 8

    def sized(p: int) -> tuple[bytes | None, int]:
        if p + 4 > end:
            raise LegacyBatchError("legacy message too short for kv size")
        (n,) = struct.unpack_from(">i", buf, p)
        p += 4
        if n == -1:
            return None, p
        if n < 0 or p + n > end:
            raise LegacyBatchError(f"bad legacy kv size {n}")
        return bytes(buf[p : p + n]), p + n

    key, pos = sized(pos)
    value, pos = sized(pos)
    if pos != end:
        raise LegacyBatchError("legacy message trailing bytes")
    return _LegacyMessage(magic, attributes, timestamp, key, value), end


def _walk(buf: memoryview, kvs: list, state: dict, nested: bool) -> None:
    pos = 0
    while pos < len(buf):
        msg, pos = _decode_one(buf, pos)
        if msg.timestamp is not None:
            # the LAST message's timestamp stamps the converted batch
            # (kafka_batch_adapter.cc convert_message_set)
            state["timestamp"] = msg.timestamp
        if msg.compression == Compression.none:
            kvs.append((msg.key, msg.value))
            continue
        if msg.magic == 0 and msg.compression == Compression.lz4:
            raise LegacyUnsupportedError(
                "magic=0 lz4 framing is not supported (known-broken in Kafka)"
            )
        if nested:
            raise LegacyBatchError("MessageSet nests more than one level")
        if msg.value is None:
            raise LegacyBatchError("compressed legacy message without value")
        from redpanda_tpu.compression import uncompress

        try:
            inner = uncompress(msg.value, msg.compression)
        except Exception as e:
            # codec-native errors (zlib.error, BadGzipFile, ...) are wire
            # corruption, not server faults: same taxonomy as a bad CRC
            raise LegacyBatchError(f"corrupt compressed legacy value: {e}") from e
        _walk(memoryview(inner), kvs, state, nested=True)


def convert_message_set(buf: bytes | memoryview) -> RecordBatch:
    """MessageSet -> one internal v2 RecordBatch (decompressed: legacy codec
    choice is a transport detail of the dead wire format, not a storage
    property worth preserving through re-compression)."""
    kvs: list[tuple[bytes | None, bytes | None]] = []
    state: dict = {"timestamp": None}
    _walk(memoryview(buf), kvs, state, nested=False)
    if not kvs:
        raise LegacyBatchError("empty legacy message set")
    # magic-0 messages carry no timestamp: stamp NO_TIMESTAMP (-1), not
    # epoch 0 — time-based retention/ListOffsets must not see 1970
    ts = state["timestamp"] if state["timestamp"] is not None else -1
    records = [
        Record(
            attributes=0,
            timestamp_delta=0,
            offset_delta=i,
            key=k,
            value=v,
            headers=(),
        )
        for i, (k, v) in enumerate(kvs)
    ]
    return RecordBatch.build(records, first_timestamp=ts, max_timestamp=ts)
