"""Kafka wire primitives.

Parity with the reference's kafka/protocol/{request_reader.h,
response_writer.h}: big-endian fixed ints, varint/uvarint (protobuf
zig-zag for signed), STRING / NULLABLE_STRING / COMPACT_STRING, BYTES
variants, ARRAY / COMPACT_ARRAY, UUID, and KIP-482 tagged fields for
flexible versions.
"""

from __future__ import annotations

import struct


class Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes | bytearray | memoryview, pos: int = 0):
        self.buf = memoryview(buf)
        self.pos = pos

    def remaining(self) -> int:
        return len(self.buf) - self.pos

    def _take(self, n: int) -> memoryview:
        if self.pos + n > len(self.buf):
            raise EOFError(f"kafka reader underflow: need {n}, have {self.remaining()}")
        v = self.buf[self.pos : self.pos + n]
        self.pos += n
        return v

    def int8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def uint32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def float64(self) -> float:
        return struct.unpack(">d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.int8() != 0

    def uvarint(self) -> int:
        shift = 0
        result = 0
        while True:
            b = self._take(1)[0]
            result |= (b & 0x7F) << shift
            if not (b & 0x80):
                return result
            shift += 7
            if shift > 63:
                raise ValueError("uvarint too long")

    def varint(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)

    def uuid(self) -> bytes:
        return bytes(self._take(16))

    def string(self) -> str:
        n = self.int16()
        if n < 0:
            raise ValueError("non-nullable string was null")
        return bytes(self._take(n)).decode("utf-8")

    def nullable_string(self) -> str | None:
        n = self.int16()
        if n < 0:
            return None
        return bytes(self._take(n)).decode("utf-8")

    def compact_string(self) -> str:
        n = self.uvarint() - 1
        if n < 0:
            raise ValueError("non-nullable compact string was null")
        return bytes(self._take(n)).decode("utf-8")

    def compact_nullable_string(self) -> str | None:
        n = self.uvarint() - 1
        if n < 0:
            return None
        return bytes(self._take(n)).decode("utf-8")

    def bytes_(self) -> bytes:
        n = self.int32()
        if n < 0:
            raise ValueError("non-nullable bytes was null")
        return bytes(self._take(n))

    def nullable_bytes(self) -> bytes | None:
        n = self.int32()
        if n < 0:
            return None
        return bytes(self._take(n))

    def compact_bytes(self) -> bytes:
        n = self.uvarint() - 1
        if n < 0:
            raise ValueError("non-nullable compact bytes was null")
        return bytes(self._take(n))

    def compact_nullable_bytes(self) -> bytes | None:
        n = self.uvarint() - 1
        if n < 0:
            return None
        return bytes(self._take(n))

    def array(self, fn) -> list | None:
        n = self.int32()
        if n < 0:
            return None
        return [fn(self) for _ in range(n)]

    def compact_array(self, fn) -> list | None:
        n = self.uvarint() - 1
        if n < 0:
            return None
        return [fn(self) for _ in range(n)]

    def tagged_fields(self) -> dict[int, bytes]:
        """KIP-482 unknown-tag passthrough: {tag: raw bytes}."""
        out: dict[int, bytes] = {}
        for _ in range(self.uvarint()):
            tag = self.uvarint()
            size = self.uvarint()
            out[tag] = bytes(self._take(size))  # pandalint: disable=IOB401 -- passthrough tags outlive the frame buffer; they must own their bytes
        return out


class Writer:
    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list[bytes] = []

    def build(self) -> bytes:
        return b"".join(self._parts)

    def size(self) -> int:
        return sum(len(p) for p in self._parts)

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(bytes(b))
        return self

    def int8(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">b", v))
        return self

    def int16(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">h", v))
        return self

    def int32(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">i", v))
        return self

    def int64(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">q", v))
        return self

    def uint32(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">I", v & 0xFFFFFFFF))
        return self

    def float64(self, v: float) -> "Writer":
        self._parts.append(struct.pack(">d", v))
        return self

    def boolean(self, v: bool) -> "Writer":
        return self.int8(1 if v else 0)

    def uvarint(self, v: int) -> "Writer":
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self._parts.append(bytes(out))
        return self

    def varint(self, v: int) -> "Writer":
        return self.uvarint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def uuid(self, v: bytes) -> "Writer":
        assert len(v) == 16
        self._parts.append(v)
        return self

    def string(self, v: str) -> "Writer":
        b = v.encode("utf-8")
        return self.int16(len(b)).raw(b)

    def nullable_string(self, v: str | None) -> "Writer":
        if v is None:
            return self.int16(-1)
        return self.string(v)

    def compact_string(self, v: str) -> "Writer":
        b = v.encode("utf-8")
        return self.uvarint(len(b) + 1).raw(b)

    def compact_nullable_string(self, v: str | None) -> "Writer":
        if v is None:
            return self.uvarint(0)
        return self.compact_string(v)

    def bytes_(self, v: bytes) -> "Writer":
        return self.int32(len(v)).raw(v)

    def nullable_bytes(self, v: bytes | None) -> "Writer":
        if v is None:
            return self.int32(-1)
        return self.bytes_(v)

    def compact_bytes(self, v: bytes) -> "Writer":
        return self.uvarint(len(v) + 1).raw(v)

    def compact_nullable_bytes(self, v: bytes | None) -> "Writer":
        if v is None:
            return self.uvarint(0)
        return self.compact_bytes(v)

    def array(self, items, fn) -> "Writer":
        if items is None:
            return self.int32(-1)
        self.int32(len(items))
        for it in items:
            fn(self, it)
        return self

    def compact_array(self, items, fn) -> "Writer":
        if items is None:
            return self.uvarint(0)
        self.uvarint(len(items) + 1)
        for it in items:
            fn(self, it)
        return self

    def tagged_fields(self, fields: dict[int, bytes] | None = None) -> "Writer":
        fields = fields or {}
        self.uvarint(len(fields))
        for tag in sorted(fields):
            self.uvarint(tag).uvarint(len(fields[tag])).raw(fields[tag])
        return self
