"""Kafka protocol error codes (parity with kafka/protocol/errors.h)."""

from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    unknown_server_error = -1
    none = 0
    offset_out_of_range = 1
    corrupt_message = 2
    unknown_topic_or_partition = 3
    invalid_fetch_size = 4
    leader_not_available = 5
    not_leader_for_partition = 6
    request_timed_out = 7
    broker_not_available = 8
    replica_not_available = 9
    message_too_large = 10
    stale_controller_epoch = 11
    offset_metadata_too_large = 12
    network_exception = 13
    coordinator_load_in_progress = 14
    coordinator_not_available = 15
    not_coordinator = 16
    invalid_topic_exception = 17
    record_list_too_large = 18
    not_enough_replicas = 19
    not_enough_replicas_after_append = 20
    invalid_required_acks = 21
    illegal_generation = 22
    inconsistent_group_protocol = 23
    invalid_group_id = 24
    unknown_member_id = 25
    invalid_session_timeout = 26
    rebalance_in_progress = 27
    invalid_commit_offset_size = 28
    topic_authorization_failed = 29
    group_authorization_failed = 30
    cluster_authorization_failed = 31
    invalid_timestamp = 32
    unsupported_sasl_mechanism = 33
    illegal_sasl_state = 34
    unsupported_version = 35
    topic_already_exists = 36
    invalid_partitions = 37
    invalid_replication_factor = 38
    invalid_replica_assignment = 39
    invalid_config = 40
    not_controller = 41
    invalid_request = 42
    unsupported_for_message_format = 43
    policy_violation = 44
    out_of_order_sequence_number = 45
    duplicate_sequence_number = 46
    invalid_producer_epoch = 47
    invalid_txn_state = 48
    invalid_producer_id_mapping = 49
    invalid_transaction_timeout = 50
    concurrent_transactions = 51
    transaction_coordinator_fenced = 52
    transactional_id_authorization_failed = 53
    security_disabled = 54
    operation_not_attempted = 55
    kafka_storage_error = 56
    log_dir_not_found = 57
    sasl_authentication_failed = 58
    unknown_producer_id = 59
    reassignment_in_progress = 60
    delegation_token_auth_disabled = 61
    delegation_token_not_found = 62
    delegation_token_owner_mismatch = 63
    delegation_token_request_not_allowed = 64
    delegation_token_authorization_failed = 65
    delegation_token_expired = 66
    invalid_principal_type = 67
    non_empty_group = 68
    group_id_not_found = 69
    fetch_session_id_not_found = 70
    invalid_fetch_session_epoch = 71
    listener_not_found = 72
    topic_deletion_disabled = 73
    fenced_leader_epoch = 74
    unknown_leader_epoch = 75
    unsupported_compression_type = 76
    stale_broker_epoch = 77
    offset_not_available = 78
    member_id_required = 79
    preferred_leader_not_available = 80
    group_max_size_reached = 81
    fenced_instance_id = 82
    invalid_record = 87
    unstable_offset_commit = 88
    # KIP-599; retriable — the broker-backpressure shed code the produce
    # admission gate answers with (resource_mgmt budget plane), paired
    # with a throttle_time_ms hint
    throttling_quota_exceeded = 89


class KafkaError(Exception):
    """Raised by handlers to short-circuit into an error response."""

    def __init__(self, code: ErrorCode, message: str = ""):
        super().__init__(f"{code.name}: {message}" if message else code.name)
        self.code = code
