"""Kafka wire RecordBatch (v2) <-> internal RecordBatch adapter.

Parity with the reference's kafka_batch_adapter (kafka/server/
kafka_batch_adapter.cc:43-121): the wire layout is

    base_offset       int64   BE
    batch_length      int32   BE   (bytes after this field)
    partition_leader_epoch int32 BE
    magic             int8         (must be 2)
    crc               uint32  BE   (CRC-32C over attributes..records)
    attributes        int16   BE
    last_offset_delta int32   BE
    first_timestamp   int64   BE
    max_timestamp     int64   BE
    producer_id       int64   BE
    producer_epoch    int16   BE
    base_sequence     int32   BE
    record_count      int32   BE
    records           bytes

while the internal layout is the little-endian 61-byte header
(model/record.h:475-487) with a leading header_crc. The records payload is
byte-identical between the two, so adaptation is a header rewrite plus CRC
verification — the CRC itself can be validated host-side or batched onto
the device CRC kernel (redpanda_tpu.ops.crc32c_device).

The produce path MUST verify the wire CRC (kafka_batch_adapter.cc:93-121);
the fetch path re-emits the wire header from the stored internal header.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from redpanda_tpu.hashing.crc32c import crc32c
from redpanda_tpu.models.record import (
    RecordBatch,
    RecordBatchHeader,
    RecordBatchType,
)

WIRE_HEADER_SIZE = 61  # same size as internal, different layout/endianness
_WIRE_PACK = ">qiibIhiqqqhii"
KAFKA_MAGIC = 2

# Offset (from batch start) of the attributes field — the first byte
# covered by the Kafka CRC: 8+4+4+1+4 = 21.
_CRC_COVER_START = 21


@dataclass
class AdaptResult:
    """Outcome of adapting one wire batch (v2_format/valid_crc flags mirror
    kafka_batch_adapter.h)."""

    batch: RecordBatch | None
    v2_format: bool
    valid_crc: bool


def decode_wire_batch(buf: bytes | memoryview, offset: int = 0, verify_crc: bool = True) -> tuple[AdaptResult, int]:
    """Decode one wire RecordBatch starting at ``offset``; returns the
    adapted internal batch and the next offset."""
    buf = memoryview(buf)
    if len(buf) - offset < WIRE_HEADER_SIZE:
        raise EOFError("short wire batch header")
    (
        base_offset,
        batch_length,
        _leader_epoch,
        magic,
        crc,
        attrs,
        last_offset_delta,
        first_timestamp,
        max_timestamp,
        producer_id,
        producer_epoch,
        base_sequence,
        record_count,
    ) = struct.unpack_from(_WIRE_PACK, buf, offset)
    if batch_length < WIRE_HEADER_SIZE - 12:
        # covers negative/zero lengths that would otherwise stall the
        # decode loop or alias overlapping batches
        raise EOFError(f"invalid wire batch_length {batch_length}")
    end = offset + 12 + batch_length  # base_offset + batch_length fields
    if magic != KAFKA_MAGIC:
        return AdaptResult(None, v2_format=False, valid_crc=False), end
    if end > len(buf):
        raise EOFError("short wire batch payload")
    payload = bytes(buf[offset + WIRE_HEADER_SIZE : end])
    valid = True
    if verify_crc:
        # zero-copy: crc32c takes the memoryview straight off the frame —
        # the CRC cover region is the whole batch, copying it per batch
        # doubled produce-path memory traffic
        valid = crc32c(buf[offset + _CRC_COVER_START : end]) == crc
    header = RecordBatchHeader(
        size_bytes=WIRE_HEADER_SIZE + len(payload),
        base_offset=base_offset,
        type=RecordBatchType.raft_data,
        crc=crc,
        attrs=attrs,
        last_offset_delta=last_offset_delta,
        first_timestamp=first_timestamp,
        max_timestamp=max_timestamp,
        producer_id=producer_id,
        producer_epoch=producer_epoch,
        base_sequence=base_sequence,
        record_count=record_count,
    )
    header.header_crc = header.internal_header_only_crc()
    batch = RecordBatch(header=header, payload=payload)
    return AdaptResult(batch, v2_format=True, valid_crc=valid), end


def decode_wire_batches(buf: bytes | memoryview, verify_crc: bool = True) -> list[AdaptResult]:
    """Decode a full produce `records` blob (possibly several batches)."""
    out = []
    pos = 0
    buf = memoryview(buf)
    while pos + WIRE_HEADER_SIZE <= len(buf):
        res, pos = decode_wire_batch(buf, pos, verify_crc=verify_crc)
        out.append(res)
    return out


def encode_wire_batch(batch: RecordBatch) -> bytes:
    """Internal -> wire RecordBatch v2 (batch_reader.h inverse direction)."""
    h = batch.header
    payload = batch.payload
    batch_length = WIRE_HEADER_SIZE - 12 + len(payload)
    return (
        struct.pack(
            _WIRE_PACK,
            h.base_offset,
            batch_length,
            -1,  # partition_leader_epoch: not tracked on disk
            KAFKA_MAGIC,
            h.crc & 0xFFFFFFFF,
            h.attrs,
            h.last_offset_delta,
            h.first_timestamp,
            h.max_timestamp,
            h.producer_id,
            h.producer_epoch,
            h.base_sequence,
            h.record_count,
        )
        + payload
    )


def encode_wire_batches(batches: list[RecordBatch]) -> bytes:
    return b"".join(encode_wire_batch(b) for b in batches)
