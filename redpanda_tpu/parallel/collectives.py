"""Cross-partition collectives (ICI) for the host consensus plane.

The reference aggregates per-group raft votes and heartbeat responses in
host code, one message at a time (heartbeat_manager.cc:155-204 batches them
per destination node). Here the batched analogues run as mesh collectives:

- ``make_vote_aggregator``: each device holds vote bits for the raft groups
  whose partitions it owns, laid out [n_dev, groups_per_dev] over the 'p'
  axis; one ``psum``-style all-gather yields the per-group tally on every
  device so the host reads a single array instead of n messages (BASELINE
  config 5's vote-aggregation kernel).
- ``make_sharded_crc_check``: the per-shard batched CRC over all partitions
  (config 5's first half): CRC every batch of every partition in one
  sharded launch and reduce per-partition validity counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from redpanda_tpu.parallel.mesh import PARTITION_AXIS
from redpanda_tpu.ops.crc32c_device import make_crc_fn


def make_vote_aggregator(mesh):
    """Returns fn(votes uint8 [D, G]) -> int32 [G]: total votes per group.

    votes is sharded over 'p' on the leading device axis; the reduction is a
    psum over the mesh so every shard (and the host) sees the full tally.
    """

    def _local(votes):
        # votes block: [1, G] on each device -> psum over 'p'
        return jax.lax.psum(votes.astype(jnp.int32).sum(axis=0), PARTITION_AXIS)

    fn = shard_map(
        _local,
        mesh=mesh,
        in_specs=P(PARTITION_AXIS, None),
        out_specs=P(),
    )
    return jax.jit(fn)


def make_sharded_coproc_step(mesh, spec_json: str, r_batch: int, r_rec: int):
    """The full per-tick device program, sharded over the partition axis.

    One launch covers what the reference spreads across three host loops
    (SURVEY §3.2/§3.4): produce-path batch CRC validation, the coproc
    record transform, and the cross-partition vote aggregation collective.

    fn(batch_rows [P,B,r_batch] u8, batch_lens [P,B] i32, claimed [P,B] u32,
       rec_rows [P,N,r_rec] u8, rec_lens [P,N] i32, votes [P,G] u8)
      -> (ok [P,B] bool, out [P,N,r_out] u8, out_len [P,N] i32,
          keep [P,N] bool, tally [G] i32)
    """
    import jax.numpy as jnp
    from redpanda_tpu.ops.transforms import TransformSpec, compile_transform, transform_out_width

    spec = TransformSpec.from_json(spec_json)
    batch_crc = make_crc_fn(r_batch)
    tfn = compile_transform(spec, r_rec)

    def _local(b_rows, b_lens, claimed, rec_rows, rec_lens, votes):
        p, b, _ = b_rows.shape
        got = batch_crc(b_rows.reshape(p * b, r_batch), b_lens.reshape(p * b)).reshape(p, b)
        ok = (got == claimed) & (b_lens > 0)
        n = rec_rows.shape[1]
        out, out_len, keep = tfn(rec_rows.reshape(p * n, r_rec), rec_lens.reshape(p * n))
        r_out = out.shape[-1]
        tally = jax.lax.psum(votes.astype(jnp.int32).sum(axis=0), PARTITION_AXIS)
        return (
            ok,
            out.reshape(p, n, r_out),
            out_len.reshape(p, n),
            keep.reshape(p, n),
            tally,
        )

    fn = shard_map(
        _local,
        mesh=mesh,
        in_specs=(
            P(PARTITION_AXIS, None, None),
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None, None),
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None),
        ),
        out_specs=(
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None, None),
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None),
            P(),
        ),
    )
    return jax.jit(fn)


def make_crc_vote_step(mesh, r: int):
    """The config-5 raft step in ONE sharded launch: batched CRC
    validation of every partition's batches AND the cross-partition vote
    tally (BASELINE config 5; SURVEY §2.4).

    Returns fn(rows u8 [D, B, r], lens i32 [D, B], claimed u32 [D, B],
    votes u8 [D, G]) -> (ok bool [D, B], bad i32 [D], tally i32 [G]).

    The CRC kernel is vmapped over the sharded device axis (each chip
    CRCs only the batches of the partitions it owns); the tally is the
    one collective — a psum over 'p' — so every shard (and the host)
    reads the full per-group count without n_dev separate messages.
    """
    crc = make_crc_fn(r)

    def _local(rows, lens, claimed, votes):
        # block shapes: rows [1, B, r], votes [1, G]
        got = jax.vmap(crc)(rows, lens)
        ok = (got == claimed) & (lens > 0)
        bad = jnp.sum((~ok) & (lens > 0), axis=1).astype(jnp.int32)
        tally = jax.lax.psum(votes.astype(jnp.int32).sum(axis=0), PARTITION_AXIS)
        return ok, bad, tally

    fn = shard_map(
        _local,
        mesh=mesh,
        in_specs=(
            P(PARTITION_AXIS, None, None),
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None),
            P(PARTITION_AXIS, None),
        ),
        out_specs=(P(PARTITION_AXIS, None), P(PARTITION_AXIS), P()),
    )
    return jax.jit(fn)


def make_sharded_crc_check(mesh, r: int):
    """Returns fn(rows uint8 [P, B, r], lens int32 [P, B], claimed uint32
    [P, B]) -> (ok bool [P, B], bad_per_partition int32 [P]).

    Rows shard over 'p'; the CRC matmul runs per shard with no cross-device
    traffic; only the scalar summary is replicated.
    """
    crc = make_crc_fn(r)

    def _local(rows, lens, claimed):
        p, b, _ = rows.shape
        got = crc(rows.reshape(p * b, r), lens.reshape(p * b)).reshape(p, b)
        ok = (got == claimed) & (lens > 0)
        bad = jnp.sum((~ok) & (lens > 0), axis=1).astype(jnp.int32)
        return ok, bad

    fn = shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(PARTITION_AXIS, None, None), P(PARTITION_AXIS, None), P(PARTITION_AXIS, None)),
        out_specs=(P(PARTITION_AXIS, None), P(PARTITION_AXIS)),
    )
    return jax.jit(fn)
