"""Cross-partition collectives (ICI) for the host consensus plane.

The reference aggregates per-group raft votes and heartbeat responses in
host code, one message at a time (heartbeat_manager.cc:155-204 batches them
per destination node). Here the batched analogues run as mesh collectives:

- ``make_vote_aggregator``: each device holds vote bits for the raft groups
  whose partitions it owns, laid out [n_dev, groups_per_dev] over the 'p'
  axis; one ``psum``-style all-gather yields the per-group tally on every
  device so the host reads a single array instead of n messages (BASELINE
  config 5's vote-aggregation kernel).
- ``make_sharded_crc_check``: the per-shard batched CRC over all partitions
  (config 5's first half): CRC every batch of every partition in one
  sharded launch and reduce per-partition validity counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from redpanda_tpu.parallel.mesh import PARTITION_AXIS
from redpanda_tpu.ops.crc32c_device import make_crc_fn


def make_vote_aggregator(mesh):
    """Returns fn(votes uint8 [D, G]) -> int32 [G]: total votes per group.

    votes is sharded over 'p' on the leading device axis; the reduction is a
    psum over the mesh so every shard (and the host) sees the full tally.
    """

    def _local(votes):
        # votes block: [1, G] on each device -> psum over 'p'
        return jax.lax.psum(votes.astype(jnp.int32).sum(axis=0), PARTITION_AXIS)

    fn = shard_map(
        _local,
        mesh=mesh,
        in_specs=P(PARTITION_AXIS, None),
        out_specs=P(),
    )
    return jax.jit(fn)


def make_sharded_crc_check(mesh, r: int):
    """Returns fn(rows uint8 [P, B, r], lens int32 [P, B], claimed uint32
    [P, B]) -> (ok bool [P, B], bad_per_partition int32 [P]).

    Rows shard over 'p'; the CRC matmul runs per shard with no cross-device
    traffic; only the scalar summary is replicated.
    """
    crc = make_crc_fn(r)

    def _local(rows, lens, claimed):
        p, b, _ = rows.shape
        got = crc(rows.reshape(p * b, r), lens.reshape(p * b)).reshape(p, b)
        ok = (got == claimed) & (lens > 0)
        bad = jnp.sum((~ok) & (lens > 0), axis=1).astype(jnp.int32)
        return ok, bad

    fn = shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(PARTITION_AXIS, None, None), P(PARTITION_AXIS, None), P(PARTITION_AXIS, None)),
        out_specs=(P(PARTITION_AXIS, None), P(PARTITION_AXIS)),
    )
    return jax.jit(fn)
