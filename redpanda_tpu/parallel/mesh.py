"""Device mesh + sharding layout for the partition axis.

The reference scales by spreading partitions over cores/nodes (shard-per-core
SMP + the cluster partition allocator — SURVEY §2.3). The TPU-native analogue
is a 1-D ``jax.sharding.Mesh`` whose ``'p'`` axis carries the partition
dimension of every data-plane array: ``[P, B, R]`` shards as ``P('p',)`` so
each chip owns P/n partitions, XLA inserts ICI collectives only where a
kernel genuinely crosses partitions (e.g. vote aggregation), and the host
bridge feeds each shard locally.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PARTITION_AXIS = "p"


def partition_mesh(n_devices: int | None = None, devices=None, backend: str | None = None) -> Mesh:
    """1-D mesh over the partition axis.

    Tests pass backend='cpu' for the virtual 8-device mesh; on hardware the
    default backend's chips are used.
    """
    if devices is None:
        devices = jax.local_devices(backend=backend) if backend else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), (PARTITION_AXIS,))


def partition_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (partition) dim over 'p'; replicate the rest."""
    return NamedSharding(mesh, P(PARTITION_AXIS, *([None] * (ndim - 1))))


def shard_to_mesh(mesh: Mesh, *arrays):
    """device_put each array with its partition-leading sharding."""
    out = tuple(
        jax.device_put(a, partition_sharding(mesh, a.ndim)) for a in arrays
    )
    return out if len(out) != 1 else out[0]


def sharded_jit(fn, mesh: Mesh, in_ndims: tuple[int, ...], out_ndims: tuple[int, ...]):
    """jit `fn` with partition-leading shardings on every input and output.

    in_ndims/out_ndims give the rank of each positional argument / result;
    each gets P('p', None, ...) over its leading dim.
    """
    if not out_ndims:
        raise ValueError("out_ndims must name at least one output")
    spec = lambda nd: NamedSharding(mesh, P(PARTITION_AXIS, *([None] * (nd - 1))))
    in_shardings = tuple(spec(nd) for nd in in_ndims)
    out_shardings = tuple(spec(nd) for nd in out_ndims)
    return jax.jit(
        fn,
        in_shardings=in_shardings if len(in_shardings) > 1 else in_shardings[0],
        out_shardings=out_shardings if len(out_shardings) > 1 else out_shardings[0],
    )
