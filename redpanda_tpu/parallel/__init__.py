from redpanda_tpu.parallel.mesh import (
    partition_mesh,
    partition_sharding,
    shard_to_mesh,
    sharded_jit,
)
from redpanda_tpu.parallel.collectives import (
    make_vote_aggregator,
    make_crc_vote_step,
    make_sharded_crc_check,
    make_sharded_coproc_step,
)

__all__ = [
    "partition_mesh",
    "partition_sharding",
    "shard_to_mesh",
    "sharded_jit",
    "make_vote_aggregator",
    "make_crc_vote_step",
    "make_sharded_crc_check",
    "make_sharded_coproc_step",
]
