"""Metrics registry with prometheus text exposition.

Parity with the reference's probe pattern: every subsystem registers a
"probe" of counters/gauges/histograms (storage/probe.h, raft/probe.cc,
kafka/latency_probe.h) and the admin server exports them all at /metrics in
prometheus format (admin_server.cc:148-151). Gauges may be callables so
live state (partition counts, HWMs) is sampled at scrape time.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

from redpanda_tpu.utils.hdr import HdrHist

PREFIX = "redpanda_tpu"


@dataclass
class Counter:
    name: str
    help: str
    labels: tuple[tuple[str, str], ...] = ()
    value: float = 0
    # `value += n` is a read-modify-write across bytecodes; counters are
    # shared by the harvester daemon, fetch workers, host-pool shards and
    # the tick executor, and unlocked concurrent incs LOSE updates
    # (pandaraces RAC1101). Scrape-side reads of the single float stay
    # lock-free: a read observes one consistent published value.
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n


@dataclass
class Gauge:
    name: str
    help: str
    fn: Callable[[], float]
    labels: tuple[tuple[str, str], ...] = ()


@dataclass
class Histogram:
    name: str
    help: str
    hist: HdrHist = field(default_factory=HdrHist)
    labels: tuple[tuple[str, str], ...] = ()

    def record(self, value: int) -> None:
        self.hist.record(value)


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and newline (not quotes)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _labelstr(labels: tuple[tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def series_key(name: str, labels: tuple[tuple[str, str], ...] = ()) -> str:
    """The unprefixed series identity used by snapshot()/histograms():
    ``name{label="value",...}``. One function so the SLO engine, the
    exemplar store and the snapshot diff all join on the same key."""
    return f"{name}{_labelstr(labels)}"


class MetricsRegistry:
    def __init__(self) -> None:
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def _key(self, name: str, labels) -> str:
        return name + repr(sorted(labels))

    def counter(self, name: str, help: str = "", **labels: str) -> Counter:
        key = self._key(name, labels.items())
        c = self._counters.get(key)
        if c is None:
            c = Counter(name, help, tuple(sorted(labels.items())))
            self._counters[key] = c
        return c

    def gauge(self, name: str, fn: Callable[[], float], help: str = "", **labels: str) -> Gauge:
        key = self._key(name, labels.items())
        g = Gauge(name, help, fn, tuple(sorted(labels.items())))
        self._gauges[key] = g
        return g

    def histogram(self, name: str, help: str = "", **labels: str) -> Histogram:
        key = self._key(name, labels.items())
        h = self._hists.get(key)
        if h is None:
            h = Histogram(name, help, labels=tuple(sorted(labels.items())))
            self._hists[key] = h
        return h

    def histograms(self) -> dict[str, Histogram]:
        """Live histogram series keyed like snapshot() (series_key form).
        The SLO engine quantile-interpolates straight off these buckets;
        callers must treat the Histogram objects as read-only. The dict
        is materialized with one GIL-atomic ``list()`` first: scrapers
        (SLO engine, history recorder) run off-thread from registration,
        and a plain ``.values()`` walk races a concurrent first-label
        registration with "dict changed size during iteration"."""
        return {
            series_key(h.name, h.labels): h
            for h in list(self._hists.values())
        }

    # ------------------------------------------------------------ exposition
    def render_prometheus(self) -> str:
        lines: list[str] = []
        seen_help: set[str] = set()

        def _head(name: str, help: str, typ: str) -> None:
            if name not in seen_help:
                lines.append(f"# HELP {PREFIX}_{name} {_escape_help(help)}")
                lines.append(f"# TYPE {PREFIX}_{name} {typ}")
                seen_help.add(name)

        # GIL-atomic materializations: the scrape runs on the admin loop
        # while worker threads lazily register new labeled series
        for c in list(self._counters.values()):
            _head(c.name, c.help, "counter")
            lines.append(f"{PREFIX}_{c.name}{_labelstr(c.labels)} {c.value}")
        for g in list(self._gauges.values()):
            _head(g.name, g.help, "gauge")
            try:
                v = g.fn()
            except Exception:
                v = float("nan")
            lines.append(f"{PREFIX}_{g.name}{_labelstr(g.labels)} {v}")
        for h in list(self._hists.values()):
            _head(h.name, h.help, "histogram")
            for upper, cum in h.hist.cumulative_buckets():
                le = 'le="%s"' % upper
                lines.append(
                    f"{PREFIX}_{h.name}_bucket{_labelstr(h.labels, le)} {cum}"
                )
            le_inf = 'le="+Inf"'
            lines.append(
                f"{PREFIX}_{h.name}_bucket{_labelstr(h.labels, le_inf)} {h.hist.count}"
            )
            lines.append(f"{PREFIX}_{h.name}_sum{_labelstr(h.labels)} {h.hist.sum}")
            lines.append(f"{PREFIX}_{h.name}_count{_labelstr(h.labels)} {h.hist.count}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Point-in-time metric values keyed by UNPREFIXED series name +
        labels (exposition lines additionally carry the ``redpanda_tpu_``
        prefix) — the before/after anchor tools/microbench.py emits so a
        bench run can be diffed against the counters it moved."""
        out: dict[str, object] = {}
        # same list() materialization as render_prometheus: snapshot is
        # called from the history recorder thread under live registration
        for c in list(self._counters.values()):
            out[f"{c.name}{_labelstr(c.labels)}"] = c.value
        for g in list(self._gauges.values()):
            try:
                v = g.fn()
            except Exception:
                v = None
            out[f"{g.name}{_labelstr(g.labels)}"] = v
        for h in list(self._hists.values()):
            out[f"{h.name}{_labelstr(h.labels)}"] = {
                "count": h.hist.count,
                "sum": h.hist.sum,
                "max": h.hist.max,
                "p99": h.hist.percentile(99),
            }
        return out


# process-wide registry, like the seastar metrics singleton
registry = MetricsRegistry()
