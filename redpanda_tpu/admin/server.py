"""Admin HTTP API.

Parity with redpanda/admin_server.cc:
- GET  /v1/config                      (:218 config get; secrets redacted)
- PUT  /v1/config/log_level/{logger}   (:226-263 runtime log level w/ expiry)
- GET  /v1/brokers                     (broker membership view)
- GET  /v1/partitions                  (local partition inventory)
- POST /v1/raft/{group}/transfer_leadership             (:301)
- GET  /v1/raft/heartbeat_acks         (config-5 batched ack tally + the
  device plane's measured probe stats)
- POST /v1/partitions/kafka/{t}/{p}/transfer_leadership (:486)
- GET/POST/DELETE /v1/security/users   (:401-483 SCRAM CRUD)
- GET  /v1/failure-probes, PUT /v1/failure-probes/{m}/{p}/{type}[?count=N]
  (:948; types exception|delay|wedge|terminate, count=N auto-disarms after
  N injections, DELETE disarms — rpk debug failpoints)
- GET  /v1/coproc/status               (engine breaker + fault-domain stats;
  rpk debug coproc)
- GET  /v1/governor[?limit=N&domain=D] (coproc decision journal + per-domain
  posture/breakers/deadlines; rpk debug governor — no reference analogue,
  the reference's autotune decisions are log-only)
- GET  /v1/slo[?mark=N], POST /v1/slo/mark[?name=N]  (SLO verdicts over the
  pandaprobe histograms + named baseline marks; rpk debug slo — no
  reference analogue, the ducktape suite judges latency externally)
- GET  /metrics                        (:148-151 prometheus)
- GET  /v1/trace/recent, /v1/trace/slow (pandaprobe span traces; no
  reference analogue — seastar requests never leave their shard, ours
  cross the engine's harvester thread)
- GET  /v1/trace/id/{tid}              (this node's spans for one trace)
- GET  /v1/trace/cluster[/{tid}]       (pandascope: the trace assembled
  across every broker it touched — fan-out over each node's admin; no id
  = assemble the local slow ring's traces; rpk debug trace --cluster)
- GET  /v1/federation/metrics          (merged multi-node /metrics scrape,
  HdrHists merged bucket-by-bucket, node label preserved)
- GET  /v1/slo?federated=1             (the SLO spec judged over the
  federated scrape; POST /v1/slo/mark?federated=1 brackets cluster-wide
  incident windows; rpk debug slo --federated)
- GET  /v1/resources                   (resource_mgmt budget plane: account
  occupancy/peaks, pressure signal, admission + autotune state; rpk debug
  resources — the loadgen overload gate judges peak occupancy from it)
- POST /v1/archival/run_once, GET /v1/archival/status (drive one tiered-
  storage reconcile+upload pass / inspect uploaded-segment state; 409 when
  cloud_storage_enabled is false)
- GET  /v1/status/ready
Served on the owned HTTP server (the reference uses seastar httpd with swagger routes).
"""

from __future__ import annotations

import asyncio
import json
import logging

from redpanda_tpu.http import web

from redpanda_tpu.finjector import honey_badger
from redpanda_tpu.metrics import registry

logger = logging.getLogger("rptpu.admin")


class AdminServer:
    def __init__(
        self,
        broker,
        config=None,  # config.Configuration
        group_manager=None,  # raft.GroupManager (multi-node)
        controller=None,  # cluster.Controller (multi-node)
        host: str = "127.0.0.1",
        port: int = 9644,
        require_auth: bool = False,
        auth_token: str | None = None,
        tls=None,
    ) -> None:
        self.tls = tls  # security.tls.ReloadableTlsContext | None
        # listener-name -> ReloadableTlsContext for /v1/tls/reload (the app
        # fills this after wiring every listener)
        self.tls_contexts: dict[str, object] = {}
        self.broker = broker
        self.config = config
        self.gm = group_manager
        self.controller = controller
        self.host = host
        self.port = port
        # Auth: when enabled, every mutating/sensitive route needs either
        # `Authorization: Bearer <auth_token>` or HTTP basic credentials
        # verified against the broker's SCRAM store. /metrics and
        # /v1/status/ready stay open (scrapers/probes). When disabled the
        # admin port MUST NOT be exposed beyond localhost: it can create
        # superusers and arm failure probes.
        self.require_auth = require_auth
        self.auth_token = auth_token
        # archival scheduler (tiered storage): wired by the application
        # AFTER start when cloud_storage_enabled — /v1/archival/* answers
        # 409 otherwise
        self.archival = None
        self._runner: web.AppRunner | None = None
        self._log_level_restores: dict[str, tuple[int, asyncio.TimerHandle]] = {}
        self._federated_slo = None  # lazy: observability.federation

    # ------------------------------------------------------------ auth
    _OPEN_PATHS = ("/metrics", "/v1/status/ready")

    def _authorized(self, req: web.Request) -> bool:
        if not self.require_auth or req.path in self._OPEN_PATHS:
            return True
        hdr = req.headers.get("Authorization", "")
        if self.auth_token and hdr == f"Bearer {self.auth_token}":
            return True
        if hdr.startswith("Basic "):
            import base64 as _b64

            from redpanda_tpu.security.scram import verify_password

            try:
                user, _, pw = _b64.b64decode(hdr[6:]).decode().partition(":")
            except Exception:
                return False
            cred = self.broker.security.credentials.get(user)
            return cred is not None and verify_password(cred, pw)
        return False

    @web.middleware
    async def _auth_middleware(self, req: web.Request, handler):
        if not self._authorized(req):
            return web.json_response(
                {"error": "unauthorized"},
                status=401,
                headers={"WWW-Authenticate": 'Basic realm="redpanda-admin"'},
            )
        return await handler(req)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "AdminServer":
        app = web.Application(middlewares=[self._auth_middleware])
        app.add_routes([
            web.get("/v1/config", self._get_config),
            web.put("/v1/config/log_level/{name}", self._set_log_level),
            web.get("/v1/brokers", self._get_brokers),
            web.put("/v1/brokers/{node_id}/decommission", self._decommission),
            web.put("/v1/brokers/{node_id}/recommission", self._recommission),
            web.get("/v1/partitions", self._get_partitions),
            web.post("/v1/raft/{group}/transfer_leadership", self._raft_transfer),
            web.get("/v1/raft/heartbeat_acks", self._raft_heartbeat_acks),
            web.post(
                "/v1/partitions/kafka/{topic}/{partition}/transfer_leadership",
                self._partition_transfer,
            ),
            web.post("/v1/partitions/rebalance_leaders", self._rebalance_leaders),
            web.get("/v1/security/users", self._list_users),
            web.post("/v1/security/users", self._create_user),
            web.delete("/v1/security/users/{user}", self._delete_user),
            web.put("/v1/security/users/{user}", self._update_user),
            web.post("/v1/tls/reload", self._reload_tls),
            web.get("/v1/data-policies", self._list_policies),
            web.put("/v1/data-policies/{topic}", self._set_policy),
            web.delete("/v1/data-policies/{topic}", self._delete_policy),
            web.get("/v1/failure-probes", self._list_probes),
            web.put("/v1/failure-probes/{module}/{probe}/{type}", self._set_probe),
            web.delete("/v1/failure-probes/{module}/{probe}", self._unset_probe),
            web.get("/v1/coproc/status", self._coproc_status),
            web.get("/v1/governor", self._governor),
            web.get("/v1/resources", self._resources),
            web.post("/v1/archival/run_once", self._archival_run_once),
            web.get("/v1/archival/status", self._archival_status),
            web.get("/v1/slo", self._slo),
            web.post("/v1/slo/mark", self._slo_mark),
            web.get("/v1/slo/exemplars", self._slo_exemplars),
            web.get("/v1/profile", self._profile),
            web.get("/v1/profile/timeline", self._profile_timeline),
            web.get("/v1/history", self._history),
            web.get("/metrics", self._metrics),
            web.get("/v1/trace/recent", self._trace_recent),
            web.get("/v1/trace/slow", self._trace_slow),
            web.get("/v1/trace/id/{trace_id}", self._trace_by_id),
            web.get("/v1/trace/cluster", self._trace_cluster_slow),
            web.get("/v1/trace/cluster/{trace_id}", self._trace_cluster),
            web.get("/v1/federation/metrics", self._federation_metrics),
            web.get("/v1/status/ready", self._ready),
        ])
        from redpanda_tpu.utils.http_server import start_site

        self._runner, self.port = await start_site(
            app, self.host, self.port, logger, "admin api",
            ssl_context=self.tls.server_context if self.tls is not None else None,
        )
        return self

    async def stop(self) -> None:
        for _, handle in self._log_level_restores.values():
            handle.cancel()
        self._log_level_restores.clear()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # ------------------------------------------------------------ config
    async def _get_config(self, req: web.Request) -> web.Response:
        if self.config is not None:
            return web.json_response(self.config.to_dict(redact=True))
        cfg = self.broker.config
        return web.json_response({k: v for k, v in vars(cfg).items() if not k.startswith("_")})

    async def _set_log_level(self, req: web.Request) -> web.Response:
        name = req.match_info["name"]
        level_name = req.query.get("level", "info").upper()
        expiry_s = int(req.query.get("expires", "300"))
        level = getattr(logging, level_name, None)
        if not isinstance(level, int):
            return web.json_response({"error": f"unknown level {level_name}"}, status=400)
        lg = logging.getLogger(name)
        old = lg.level
        lg.setLevel(level)
        # auto-restore, like admin_server.cc's expiring override (:226-263)
        existing = self._log_level_restores.pop(name, None)
        if existing is not None:
            old = existing[0]
            existing[1].cancel()
        loop = asyncio.get_running_loop()
        handle = loop.call_later(expiry_s, self._restore_level, name)
        self._log_level_restores[name] = (old, handle)
        return web.json_response({"logger": name, "level": level_name, "expires_s": expiry_s})

    def _restore_level(self, name: str) -> None:
        entry = self._log_level_restores.pop(name, None)
        if entry is not None:
            logging.getLogger(name).setLevel(entry[0])

    # ------------------------------------------------------------ views
    async def _get_brokers(self, req: web.Request) -> web.Response:
        if self.controller is not None:
            return web.json_response([
                {
                    "node_id": b.node_id, "host": b.host, "port": b.port,
                    "kafka_host": b.kafka_host, "kafka_port": b.kafka_port,
                    "membership_status": b.state.name,
                }
                for b in self.controller.members.all_brokers()
            ])
        cfg = self.broker.config
        return web.json_response([
            {
                "node_id": cfg.node_id, "host": cfg.advertised_host,
                "port": cfg.advertised_port, "kafka_host": cfg.advertised_host,
                "kafka_port": cfg.advertised_port, "membership_status": "active",
            }
        ])

    async def _membership(self, req: web.Request, op: str) -> web.Response:
        """Drain (or restore) a broker: replicated through the controller,
        reconciled cluster-wide (members_backend decommission semantics,
        commands.h:164-173). Works against ANY node — the broker's
        dispatcher forwards to the controller leader."""
        if self.controller is None:
            return web.json_response(
                {"error": "not a clustered broker"}, status=400
            )
        node_id = int(req.match_info["node_id"])
        dispatcher = getattr(self.broker, "controller_dispatcher", None)
        from redpanda_tpu.cluster.service import OP_DECOMMISSION, OP_RECOMMISSION

        opcode = OP_DECOMMISSION if op == "decommission" else OP_RECOMMISSION
        try:
            if dispatcher is not None:
                # frontend op, NOT the raw command: the leader-side
                # decommission kicks replica moves + the drain watcher
                await dispatcher.topic_op(opcode, {"node_id": node_id})
            elif op == "decommission":
                await self.controller.decommission_node(node_id)
            else:
                await self.controller.recommission_node(node_id)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({op: node_id})

    async def _decommission(self, req: web.Request) -> web.Response:
        return await self._membership(req, "decommission")

    async def _recommission(self, req: web.Request) -> web.Response:
        return await self._membership(req, "recommission")

    async def _get_partitions(self, req: web.Request) -> web.Response:
        out = []
        for ntp, p in self.broker.partition_manager.partitions().items():
            out.append({
                "ns": ntp.ns, "topic": ntp.topic, "partition": ntp.partition,
                "leader": p.leader_id, "is_leader": p.is_leader(),
                "high_watermark": p.high_watermark,
                "start_offset": p.start_offset,
            })
        return web.json_response(out)

    async def _ready(self, req: web.Request) -> web.Response:
        return web.json_response({"status": "ready"})

    # ------------------------------------------------------------ leadership
    async def _raft_transfer(self, req: web.Request) -> web.Response:
        if self.gm is None:
            return web.json_response({"error": "not clustered"}, status=400)
        group = int(req.match_info["group"])
        target = int(req.query.get("target", "-1"))
        c = self.gm.consensus_for(group)
        if c is None:
            return web.json_response({"error": f"unknown group {group}"}, status=404)
        ok = await c.do_transfer_leadership(target)
        return web.json_response({"success": bool(ok)})

    async def _raft_heartbeat_acks(self, req: web.Request) -> web.Response:
        """Per-group ack counts from the last heartbeat tick's batched
        tally (BASELINE config 5 vote half, ``raft_device_vote_tally``)
        plus the device plane's measured host-vs-device probe stats —
        the operator's view of whether the batched reduction runs and
        where."""
        from redpanda_tpu.raft import device_plane

        acks = {}
        if self.gm is not None:
            acks = {
                str(g): n
                for g, n in self.gm.heartbeats.last_tick_acks.items()
            }
        return web.json_response({
            "enabled": device_plane.vote_tally_enabled(),
            "last_tick_acks": acks,
            "plane": device_plane.default_plane().stats(),
        })

    async def _partition_transfer(self, req: web.Request) -> web.Response:
        if self.gm is None:
            return web.json_response({"error": "not clustered"}, status=400)
        topic = req.match_info["topic"]
        partition = int(req.match_info["partition"])
        target = int(req.query.get("target", "-1"))
        p = self.broker.get_partition(topic, partition)
        consensus = getattr(p, "consensus", None)
        if p is None or not hasattr(consensus, "do_transfer_leadership"):
            return web.json_response({"error": "unknown or non-raft partition"}, status=404)
        ok = await consensus.do_transfer_leadership(target)
        return web.json_response({"success": bool(ok)})

    async def _rebalance_leaders(self, req: web.Request) -> web.Response:
        """Shed THIS broker's excess leaderships toward under-loaded peers
        (leadership rebalancing via transfer_leadership, SURVEY §5; each
        node can only initiate transfers for groups it leads, so the
        operator — rpk cluster rebalance — calls every node's admin)."""
        if self.controller is None:
            return web.json_response({"error": "not clustered"}, status=400)
        mdc = getattr(self.broker, "metadata_cache", None)
        me = self.broker.config.node_id
        # cluster-wide leader counts over raft-backed partitions
        counts: dict[int, int] = {
            b.node_id: 0 for b in self.controller.members.all_brokers()
        }
        # a decommissioning node is absent from all_brokers() but may still
        # lead groups it should shed; it must count itself without KeyError
        counts.setdefault(me, 0)
        led_here = []  # (ntp, consensus, replicas)
        for md in self.broker.topic_table.topics().values():
            for pa in md.assignments.values():
                if pa.group < 0:
                    continue
                p = self.broker.partition_manager.get(pa.ntp)
                consensus = getattr(p, "consensus", None)
                if (
                    p is not None
                    and p.is_leader()
                    and hasattr(consensus, "do_transfer_leadership")
                ):
                    # this node's own count comes from live raft state, NOT
                    # the gossip cache: under load dissemination lags by
                    # seconds, and a stale self-count makes the node believe
                    # it is already at fair and refuse to shed
                    counts[me] += 1
                    led_here.append((pa.ntp, consensus, list(pa.replicas)))
                else:
                    leader = mdc.get_leader(pa.ntp) if mdc else pa.leader
                    if leader == me:
                        # gossip says we lead it but raft says we don't:
                        # stale entry — we cannot know the real leader, so
                        # leave it uncounted rather than inflate our count
                        continue
                    if leader in counts:
                        counts[leader] += 1
        fair = max(1, round(sum(counts.values()) / len(counts)))
        transferred = []
        for ntp, consensus, replicas in led_here:
            if counts.get(me, 0) <= fair:
                break
            # most under-loaded replica of THIS partition takes it
            candidates = [r for r in replicas if r != me and r in counts]
            if not candidates:
                continue
            target = min(candidates, key=lambda r: counts[r])
            if counts[target] >= counts[me] - 1:
                continue  # transfer would not improve balance
            try:
                ok = await consensus.do_transfer_leadership(target)
            except Exception as e:
                # transfer already in flight / target mid-replica-move:
                # skip this partition, keep balancing the rest
                logger.debug("rebalance transfer %s -> %d skipped: %s",
                             ntp, target, e)
                continue
            if ok:
                counts[me] -= 1
                counts[target] += 1
                transferred.append(
                    {"ns": ntp.ns, "topic": ntp.topic, "partition": ntp.partition,
                     "to": target}
                )
        return web.json_response({"transferred": transferred, "leader_counts": counts})

    # ------------------------------------------------------------ users
    async def _list_users(self, req: web.Request) -> web.Response:
        return web.json_response(self.broker.security.credentials.users())

    async def _create_user(self, req: web.Request) -> web.Response:
        from redpanda_tpu.security import SecurityManager

        body = await req.json()
        try:
            cmd = SecurityManager.create_user_cmd(
                body["username"], body["password"],
                body.get("algorithm", "SCRAM-SHA-256"),
            )
        except KeyError as e:
            return web.json_response({"error": f"missing field {e}"}, status=400)
        try:
            await self.broker.replicate_security_cmd(cmd)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"created": body["username"]})

    async def _update_user(self, req: web.Request) -> web.Response:
        from redpanda_tpu.security import SecurityManager

        body = await req.json()
        cmd = SecurityManager.update_user_cmd(
            req.match_info["user"], body["password"],
            body.get("algorithm", "SCRAM-SHA-256"),
        )
        try:
            await self.broker.replicate_security_cmd(cmd)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"updated": req.match_info["user"]})

    async def _delete_user(self, req: web.Request) -> web.Response:
        from redpanda_tpu.security import SecurityManager

        try:
            await self.broker.replicate_security_cmd(
                SecurityManager.delete_user_cmd(req.match_info["user"])
            )
        except Exception as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"deleted": req.match_info["user"]})

    # ------------------------------------------------------------ failure probes
    async def _reload_tls(self, req: web.Request) -> web.Response:
        """Hot certificate reload on every TLS listener
        (application.cc:704-719 credential reload)."""
        reloaded = []
        for name, ctx in self.tls_contexts.items():
            try:
                if ctx is not None and ctx.reload():
                    reloaded.append(name)
            except Exception as e:
                return web.json_response(
                    {"error": f"{name}: {e}", "reloaded": reloaded}, status=500
                )
        return web.json_response({"reloaded": reloaded})

    # ------------------------------------------------------------ data policy
    async def _list_policies(self, req: web.Request) -> web.Response:
        return web.json_response(
            {
                t: {"name": p.name, "spec": p.spec_json}
                for t, p in self.broker.data_policies.policies().items()
            }
        )

    async def _set_policy(self, req: web.Request) -> web.Response:
        topic = req.match_info["topic"]
        body = await req.json()
        try:
            await self.broker.set_data_policy(
                topic, body.get("name", "policy"), body["spec"]
            )
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"status": "ok"})

    async def _delete_policy(self, req: web.Request) -> web.Response:
        await self.broker.delete_data_policy(req.match_info["topic"])
        return web.json_response({"status": "ok"})

    async def _list_probes(self, req: web.Request) -> web.Response:
        return web.json_response(
            {
                "enabled": honey_badger.enabled,
                "modules": honey_badger.modules(),
                "armed": honey_badger.armed(),
                # remaining injections for count-limited (one-shot) probes
                "counts": honey_badger.armed_counts(),
            }
        )

    async def _set_probe(self, req: web.Request) -> web.Response:
        module = req.match_info["module"]
        probe = req.match_info["probe"]
        typ = req.match_info["type"]
        # arming a name nothing ever injects must fail loudly, not 200:
        # a typo'd module would silently neuter a whole fault campaign
        known = honey_badger.modules()
        if module not in known or probe not in known[module]:
            return web.json_response(
                {"error": f"unknown probe {module}.{probe}", "modules": known},
                status=404,
            )
        count = None
        if "count" in req.query:
            try:
                count = int(req.query["count"])
                if count < 1:
                    raise ValueError(count)
            except ValueError:
                return web.json_response(
                    {"error": f"count must be a positive integer, got "
                              f"{req.query['count']!r}"},
                    status=400,
                )
        delay_ms = None
        if "delay_ms" in req.query:
            # the injected-delay knob is process-local state; a REMOTE
            # chaos driver (multi-process loadgen, rpk) has no other way
            # to size the fault it is arming in this broker
            try:
                delay_ms = int(req.query["delay_ms"])
                if delay_ms < 1:
                    raise ValueError(delay_ms)
            except ValueError:
                return web.json_response(
                    {"error": f"delay_ms must be a positive integer, got "
                              f"{req.query['delay_ms']!r}"},
                    status=400,
                )
        honey_badger.enable()
        if delay_ms is not None:
            honey_badger.delay_ms = delay_ms
        if typ == "exception":
            honey_badger.set_exception(module, probe, count)
        elif typ == "delay":
            honey_badger.set_delay(module, probe, count)
        elif typ == "wedge":
            honey_badger.set_wedge(module, probe, count)
        elif typ == "terminate":
            honey_badger.set_termination(module, probe, count)
        elif typ == "corrupt":
            honey_badger.set_corrupt(module, probe, count)
        else:
            return web.json_response({"error": f"unknown type {typ}"}, status=400)
        body = {"armed": f"{module}.{probe}", "type": typ}
        if count is not None:
            body["count"] = count
        if delay_ms is not None:
            body["delay_ms"] = delay_ms
        return web.json_response(body)

    async def _unset_probe(self, req: web.Request) -> web.Response:
        module = req.match_info["module"]
        probe = req.match_info["probe"]
        # same posture as arming: a typo'd disarm answered 200 would leave
        # the real probe silently armed and the operator believing the
        # broker healthy
        known = honey_badger.modules()
        if module not in known or probe not in known[module]:
            return web.json_response(
                {"error": f"unknown probe {module}.{probe}", "modules": known},
                status=404,
            )
        honey_badger.unset(module, probe)
        if not honey_badger.armed():
            # last probe disarmed: drop the registry back to its zero-cost
            # disabled state, or every probe site keeps paying the enabled
            # check + injection lookup until process restart
            honey_badger.disable()
        return web.json_response({"disarmed": f"{module}.{probe}"})

    # ------------------------------------------------------------ resources
    async def _resources(self, req: web.Request) -> web.Response:
        """The budget plane (resource_mgmt): per-account occupancy/peaks,
        the pressure signal, admission controller stats and the autotune
        launch knobs — what `rpk debug resources` renders and the loadgen
        overload gate judges (peak occupancy must stay <= budget)."""
        if req.query.get("federated", "").lower() in ("1", "true", "yes"):
            # the read-side federation plane: every node's account
            # occupancy merged (limits/held/peaks sum; occupancy and
            # pressure report the worst node) — `rpk debug resources
            # --federated`, and the occupancy column for cluster timelines
            from redpanda_tpu.observability import federation

            body = await federation.assemble_cluster_resources(
                self._admin_targets(), headers=self._peer_headers()
            )
            return web.json_response(body)
        plane = getattr(self.broker, "budget_plane", None)
        if plane is None:
            return web.json_response(
                {"enabled": False, "hint": "no budget plane installed"}
            )
        body = {"enabled": True, **plane.snapshot()}
        ctrl = getattr(self.broker, "produce_admission", None)
        if ctrl is not None:
            body["produce_admission"] = ctrl.snapshot()
        api = getattr(self.broker, "coproc_api", None)
        if api is not None:
            body["coproc_admission"] = api.engine.stats().get("admission")
            body["autotune"] = api.engine.governor.autotune_snapshot()
        return web.json_response(body)

    # ------------------------------------------------------------ archival
    async def _archival_run_once(self, req: web.Request) -> web.Response:
        """Drive one reconcile+upload pass NOW (tiered-storage scenarios:
        loadgen archives closed segments on demand instead of waiting for
        the scheduler cadence). Returns the number of segment uploads."""
        arch = self.archival
        if arch is None:
            return web.json_response(
                {"error": "archival disabled (cloud_storage_enabled=false)"},
                status=409,
            )
        uploads = await arch.run_once()
        return web.json_response({"uploads": uploads})

    async def _archival_status(self, req: web.Request) -> web.Response:
        arch = self.archival
        if arch is None:
            return web.json_response({"enabled": False})
        return web.json_response({
            "enabled": True,
            "interval_s": arch.interval_s,
            "archivers": {
                str(ntp): {
                    "uploaded_segments": len(a.manifest.segments),
                    "last_uploaded_offset": a.manifest.last_uploaded_offset,
                }
                for ntp, a in arch.archivers.items()
            },
        })

    # ------------------------------------------------------------ coproc
    async def _coproc_status(self, req: web.Request) -> web.Response:
        """Engine fault/breaker/stage state for `rpk debug coproc` — the
        operator's one-stop view of whether the device path is healthy or
        the engine is running demoted on the host fallback."""
        api = getattr(self.broker, "coproc_api", None)
        if api is None:
            return web.json_response(
                {"enabled": False, "hint": "coproc_enable is false"}
            )
        stats = api.engine.stats()
        return web.json_response({
            "enabled": True,
            "scripts": api.active_scripts(),
            "breaker": stats.pop("breaker", None),
            # multi-chip meshrunner block surfaced explicitly (devices,
            # mesh-vs-single decision + probe, per-device rows, demotions)
            # so `rpk debug coproc` renders it without digging in stats;
            # popped like breaker so the stats dump doesn't repeat it
            "mesh": stats.pop("mesh", None),
            "stats": stats,
        })

    async def _governor(self, req: web.Request) -> web.Response:
        """The coproc decision plane (coproc/governor.py): every adaptive
        decision this process made — host-pool calibration, columnar
        backend, device_lz4, breaker transitions, harvest path, seal
        engagement, adaptive deadlines — as a journal (newest-first, with
        measured inputs + verdict + reason + active-config snapshot) plus
        the live per-domain posture. ``?limit=N`` caps the journal slice,
        ``?domain=NAME`` filters it. `rpk debug governor` renders this."""
        from redpanda_tpu.coproc import governor as gov_mod

        try:
            limit = max(1, int(req.query.get("limit", "64")))
        except ValueError:
            return web.json_response(
                {"error": "limit must be an int"}, status=400
            )
        domain = req.query.get("domain")
        if domain is not None and domain not in gov_mod.DOMAINS:
            return web.json_response(
                {"error": f"unknown domain {domain!r}",
                 "domains": list(gov_mod.DOMAINS)},
                status=404,
            )
        body = {
            "domains": list(gov_mod.DOMAINS),
            "journal": gov_mod.journal.entries(limit=limit, domain=domain),
            "summary": gov_mod.journal.summary(),
        }
        api = getattr(self.broker, "coproc_api", None)
        if api is None:
            # the journal is process-wide (probes may have run without a
            # live engine), but there is no posture without one
            body["enabled"] = False
        else:
            g = api.engine.governor
            body["enabled"] = True
            body["posture"] = g.posture()
            body["breaker"] = g.aggregate_breaker_snapshot()
        return web.json_response(body)

    # ------------------------------------------------------------ slo
    async def _slo(self, req: web.Request) -> web.Response:
        """Judge the active SLO spec (observability/slo.py) over the probe
        histograms. ``?mark=NAME`` narrows the window to observations since
        that named baseline (POST /v1/slo/mark?name=NAME); without it the
        verdicts cover the process lifetime. Breaching objectives carry
        trace exemplars resolvable via /v1/trace/slow."""
        from redpanda_tpu.observability import tracer
        from redpanda_tpu.observability.slo import slo

        mark = req.query.get("mark")
        if req.query.get("federated", "").lower() in ("1", "true", "yes"):
            # judge the active spec over the MERGED multi-node scrape
            # instead of this process's registry — `rpk debug slo
            # --federated`; marks live in the federated engine, so a
            # federated mark brackets a cluster-wide incident window
            fed = self._federation()
            try:
                report = await fed.evaluate(slo.spec, mark=mark)
            except KeyError:
                return web.json_response(
                    {"error": f"unknown federated mark {mark!r}",
                     "marks": fed.marks()},
                    status=404,
                )
            report["marks"] = fed.marks()
            return web.json_response(report)
        try:
            report = slo.evaluate(mark=mark)
        except KeyError:
            return web.json_response(
                {"error": f"unknown mark {mark!r}", "marks": slo.marks()},
                status=404,
            )
        report["exemplars_enabled"] = tracer.enabled
        report["marks"] = slo.marks()
        return web.json_response(report)

    async def _slo_mark(self, req: web.Request) -> web.Response:
        """Snapshot every histogram as a named baseline, so a later
        GET /v1/slo?mark=NAME judges only what happened since — the
        bracket an operator (or the chaos suite) puts around an incident.
        ``?federated=1`` snapshots the merged cluster scrape instead."""
        from redpanda_tpu.observability.slo import slo

        name = req.query.get("name", "default")
        if req.query.get("federated", "").lower() in ("1", "true", "yes"):
            meta = await self._federation().set_mark(name)
            return web.json_response({
                "mark": name, "federated": True,
                "nodes": meta.get("nodes", []),
                "unreachable": meta.get("unreachable", []),
            })
        series = slo.set_mark(name)
        return web.json_response({"mark": name, "series": series})

    async def _slo_exemplars(self, req: web.Request) -> web.Response:
        """THIS node's breach-exemplar rings (probes.exemplars_snapshot),
        per series key — the per-node leg the federated SLO plane fans out
        to so a cluster-level breach entry can carry the CULPRIT node's
        exemplar trace ids (each resolvable via /v1/trace/cluster/{tid})."""
        from redpanda_tpu.observability import probes, tracer

        return web.json_response({
            "node": self.broker.config.node_id,
            "enabled": tracer.enabled,
            "exemplars": probes.exemplars_snapshot(),
        })

    # ------------------------------------------------------------ pulse
    async def _profile(self, req: web.Request) -> web.Response:
        """pandapulse status: flight-recorder summary, per-stage totals,
        wall-profiler folded-stack top — `rpk debug profile` renders this;
        profile.json in the debug bundle."""
        from redpanda_tpu.observability.pulse import pulse

        try:
            top = max(1, int(req.query.get("top", "20")))
        except ValueError:
            return web.json_response({"error": "top must be an int"}, status=400)
        body = pulse.snapshot(top=top)
        body["node"] = self.broker.config.node_id
        if req.query.get("stacks", "").lower() in ("1", "true", "yes"):
            body["stacks"] = pulse.profiler.stacks()
            body["folded"] = pulse.profiler.folded()
        return web.json_response(body)

    async def _profile_timeline(self, req: web.Request) -> web.Response:
        """Chrome trace-event JSON (Perfetto-loadable) of the newest
        ``?launches=N`` launch lifecycles, governor verdicts + admission
        episodes as instant events on the same clock. ``?federated=1``
        assembles the cluster timeline across every broker's admin (the
        /v1/trace/cluster posture: unreachable nodes reported, not fatal)."""
        from redpanda_tpu.observability.pulse import pulse

        try:
            launches = max(0, int(req.query.get("launches", "0")))
        except ValueError:
            return web.json_response(
                {"error": "launches must be an int"}, status=400
            )
        if req.query.get("federated", "").lower() in ("1", "true", "yes"):
            from redpanda_tpu.observability import federation

            body = await federation.assemble_cluster_timeline(
                self._admin_targets(), launches,
                headers=self._peer_headers(),
            )
            return web.json_response(body)
        return web.json_response(pulse.timeline(launches=launches))

    # ------------------------------------------------------------ history
    async def _history(self, req: web.Request) -> web.Response:
        """The pandatrend metrics-history ring (observability/history.py):
        bounded per-interval delta windows with derived rates/quantiles,
        the EWMA band state, and breach totals — `rpk debug trend` renders
        this. ``?series=SUBSTR`` filters every per-series section,
        ``?limit=N`` caps the window slice (newest last), ``?federated=1``
        fans out to every broker's admin and returns the per-node rings
        side by side (windows never merge across wall clocks)."""
        from redpanda_tpu.observability.history import history

        series = req.query.get("series") or None
        try:
            limit = max(0, int(req.query.get("limit", "0")))
        except ValueError:
            return web.json_response(
                {"error": "limit must be an int"}, status=400
            )
        if req.query.get("federated", "").lower() in ("1", "true", "yes"):
            from redpanda_tpu.observability import federation

            body = await federation.assemble_cluster_history(
                self._admin_targets(), series=series, limit=limit,
                headers=self._peer_headers(),
            )
            return web.json_response(body)
        body = history.snapshot(series=series, limit=limit)
        body["node"] = self.broker.config.node_id
        return web.json_response(body)

    # ------------------------------------------------------------ metrics
    async def _metrics(self, req: web.Request) -> web.Response:
        return web.Response(
            text=registry.render_prometheus(),
            content_type="text/plain",
            charset="utf-8",
        )

    # ------------------------------------------------------------ traces
    async def _trace_recent(self, req: web.Request) -> web.Response:
        from redpanda_tpu.observability import tracer

        try:
            # clamp: recent(0) means "whole ring" programmatically, but an
            # HTTP limit<=0 must never turn a poll into a full-ring dump
            limit = max(1, int(req.query.get("limit", "20")))
        except ValueError:
            return web.json_response({"error": "limit must be an int"}, status=400)
        return web.json_response({
            "enabled": tracer.enabled,
            "spans_recorded": tracer.spans_recorded,
            "traces": tracer.recent(limit),
        })

    async def _trace_slow(self, req: web.Request) -> web.Response:
        from redpanda_tpu.observability import tracer

        try:
            limit = max(1, int(req.query.get("limit", "50")))
        except ValueError:
            return web.json_response({"error": "limit must be an int"}, status=400)
        return web.json_response({
            "enabled": tracer.enabled,
            "threshold_ms": tracer.slow_threshold_us / 1000.0,
            "spans": tracer.slow(limit),
        })

    # ---------------------------------------------------- cluster traces
    def _admin_targets(self) -> list[tuple[int, str | None]]:
        """[(node_id, admin_base_url | None)] for every active broker —
        the fan-out set of the pandascope plane. Self always dials its own
        listener (uniform HTTP path, no special case); a peer that never
        advertised an admin port (pre-pandascope log entry) maps to None
        and is reported unreachable rather than silently skipped."""
        me = self.broker.config.node_id
        self_url = f"http://{self.host}:{self.port}"
        if self.controller is None:
            return [(me, self_url)]
        out: list[tuple[int, str | None]] = []
        for b in self.controller.members.all_brokers():
            if b.node_id == me:
                out.append((b.node_id, self_url))
            elif getattr(b, "admin_port", 0):
                out.append((b.node_id, f"http://{b.host}:{b.admin_port}"))
            else:
                out.append((b.node_id, None))
        return out or [(me, self_url)]

    def _peer_headers(self) -> dict[str, str] | None:
        """Credentials the pandascope fan-out presents to PEER admins.
        Under auth every /v1/trace/* and federated route requires them —
        without this, enabling admin_api_require_auth would silently turn
        every cluster view into a one-node 'partial' (each peer 401s and
        reads as unreachable). The bearer token is cluster-wide by
        operational convention (one token in the deploy config); a
        cluster running per-node tokens degrades to the visible partial
        view rather than anything silent."""
        if self.require_auth and self.auth_token:
            return {"Authorization": f"Bearer {self.auth_token}"}
        return None

    async def _trace_by_id(self, req: web.Request) -> web.Response:
        """THIS node's surviving spans for one trace id — the per-node leg
        the cluster assembler fans out to."""
        from redpanda_tpu.observability import tracer

        try:
            tid = int(req.match_info["trace_id"])
        except ValueError:
            return web.json_response(
                {"error": "trace_id must be an int"}, status=400
            )
        spans = tracer.spans_for(tid)
        me = self.broker.config.node_id
        return web.json_response({
            "trace_id": tid,
            "node": me,
            "epoch": tracer.epoch_wall,
            "spans": spans,
        })

    async def _trace_cluster(self, req: web.Request) -> web.Response:
        """ONE trace assembled cluster-wide: fan out to every node's
        /v1/trace/id/<tid>, merge by trace id — produce → raft replicate →
        follower append → coproc dispatch as a single multi-node trace."""
        from redpanda_tpu.observability import federation

        try:
            tid = int(req.match_info["trace_id"])
        except ValueError:
            return web.json_response(
                {"error": "trace_id must be an int"}, status=400
            )
        trace = await federation.assemble_cluster_trace(
            self._admin_targets(), tid, headers=self._peer_headers()
        )
        return web.json_response(trace)

    async def _trace_cluster_slow(self, req: web.Request) -> web.Response:
        """Assembled cluster traces for the LOCAL slow ring's newest trace
        ids — what the debug bundle captures as cluster_traces.json: the
        requests that breached, stitched across every broker they touched."""
        from redpanda_tpu.observability import federation, tracer

        try:
            limit = max(1, min(16, int(req.query.get("limit", "5"))))
        except ValueError:
            return web.json_response({"error": "limit must be an int"}, status=400)
        tids: list[int] = []
        for s in tracer.slow(limit=200):
            if s["trace_id"] not in tids:
                tids.append(s["trace_id"])
            if len(tids) >= limit:
                break
        targets = self._admin_targets()
        headers = self._peer_headers()
        # concurrent per-trace fan-outs: the assemblies are independent,
        # and awaiting them serially would multiply an unreachable node's
        # timeout by the trace count (a dead peer must cost ONE timeout,
        # not one per bundle entry)
        traces = list(
            await asyncio.gather(
                *(
                    federation.assemble_cluster_trace(
                        targets, tid, headers=headers
                    )
                    for tid in tids
                )
            )
        )
        return web.json_response({
            "enabled": tracer.enabled,
            "targets": [
                {"node": n, "url": u, "reachable": u is not None}
                for n, u in targets
            ],
            "traces": traces,
        })

    # ---------------------------------------------------- federation
    def _federation(self):
        if self._federated_slo is None:
            from redpanda_tpu.observability.federation import FederatedSlo

            self._federated_slo = FederatedSlo(
                self._admin_targets, headers_fn=self._peer_headers
            )
        return self._federated_slo

    async def _federation_metrics(self, req: web.Request) -> web.Response:
        """The merged cluster window in JSON registry form: every series
        scraped off every node's /metrics, HdrHists merged additively with
        the per-node contributions preserved under the node label —
        federated_metrics.json in the debug bundle."""
        from redpanda_tpu.observability import federation

        snap = await federation.federated_snapshot(
            self._admin_targets(), headers=self._peer_headers()
        )
        meta = snap.pop("__meta__", {})
        return web.json_response({
            "nodes": meta.get("nodes", []),
            "unreachable": meta.get("unreachable", []),
            "partial": bool(meta.get("unreachable")),
            "series": snap,
        })
