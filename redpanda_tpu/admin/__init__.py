"""Admin API server (redpanda/admin_server.cc parity)."""

from redpanda_tpu.admin.server import AdminServer

__all__ = ["AdminServer"]
