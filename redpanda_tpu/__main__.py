"""``python -m redpanda_tpu`` → the rpk CLI (main.cc:33 analogue: the same
binary is both the broker (`start`) and the operator tool)."""

import sys

from redpanda_tpu.cli.rpk import main

sys.exit(main())
