"""Minimal S3 client (src/v/s3 parity)."""

from redpanda_tpu.s3.client import S3Client, S3Error, sigv4_headers

__all__ = ["S3Client", "S3Error", "sigv4_headers"]
