"""S3 client with AWS Signature V4.

Parity with s3/client.h:95-227 + signature.h: request_creator signs
GET/PUT/DeleteObject/ListObjectsV2 with SigV4 (canonical request →
string-to-sign → derived signing key), and the client rides the build's own
http layer (`redpanda_tpu.http.HttpClient`, the analogue of the reference's
Beast-based http::client). ListObjectsV2's XML is parsed with the stdlib
ElementTree.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import logging
import urllib.parse
import xml.etree.ElementTree as ET

from redpanda_tpu.http import HttpClient

logger = logging.getLogger("rptpu.s3")


class S3Error(Exception):
    def __init__(self, status: int, body: str = "") -> None:
        super().__init__(f"s3 error {status}: {body[:200]}")
        self.status = status


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def canonical_query_string(query: dict[str, str]) -> str:
    """RFC3986-strict query encoding (space -> %20, nothing else safe).

    Used both for signing AND for the request URL itself — the signature
    only verifies if the server sees byte-identical encoding, so the client
    must never re-encode through a different codec (urlencode's quote_plus
    would turn spaces into '+': SignatureDoesNotMatch from real S3/minio).
    """
    return "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(str(v), safe='')}"
        for k, v in sorted(query.items())
    )


def canonical_uri(path: str) -> str:
    """Canonical URI: each segment URI-encoded, '/' preserved — the exact
    string signed and sent."""
    return urllib.parse.quote(path, safe="/")


def sigv4_headers(
    method: str,
    host: str,
    path: str,
    query: dict[str, str],
    payload: bytes,
    access_key: str,
    secret_key: str,
    region: str,
    *,
    now: datetime.datetime | None = None,
    service: str = "s3",
) -> dict[str, str]:
    """AWS SigV4 (signature.h): returns the headers to attach."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()
    canonical_query = canonical_query_string(query)
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed_headers = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join([
        method,
        canonical_uri(path),
        canonical_query,
        canonical_headers,
        signed_headers,
        payload_hash,
    ])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256",
        amz_date,
        scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    k_date = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return headers


class S3Client:
    """GET/PUT/DeleteObject + ListObjectsV2 (s3/client.h:150)."""

    def __init__(
        self,
        bucket: str,
        *,
        region: str = "us-east-1",
        endpoint: str | None = None,  # e.g. http://127.0.0.1:9000 (minio/imposter)
        access_key: str = "",
        secret_key: str = "",
        request_timeout: float = 300.0,  # whole-round-trip bound; sized for
        # full segment uploads on slow links
    ) -> None:
        self.bucket = bucket
        self.region = region
        self.endpoint = endpoint or f"https://{bucket}.s3.{region}.amazonaws.com"
        self.access_key = access_key
        self.secret_key = secret_key
        self._request_timeout = request_timeout
        self._http: HttpClient | None = None
        # path-style for custom endpoints (minio), virtual-host for AWS
        self._path_style = endpoint is not None

    def _sess(self) -> HttpClient:
        if self._http is None:
            self._http = HttpClient(self.endpoint, request_timeout=self._request_timeout)
        return self._http

    async def close(self) -> None:
        if self._http is not None:
            await self._http.close()
            self._http = None

    def _url_path(self, key: str) -> str:
        key = key.lstrip("/")
        return f"/{self.bucket}/{key}" if self._path_style else f"/{key}"

    async def _request(
        self, method: str, path: str, query: dict[str, str] | None = None,
        payload: bytes = b"",
    ) -> tuple[int, bytes]:
        query = query or {}
        host = urllib.parse.urlparse(self.endpoint).netloc
        headers = sigv4_headers(
            method, host, path, query, payload,
            self.access_key, self.secret_key, self.region,
        )
        # The path+query carries the exact bytes that were signed (canonical
        # URI + canonical query); HttpClient sends them verbatim.
        path_qs = canonical_uri(path)
        if query:
            path_qs += "?" + canonical_query_string(query)
        resp = await self._sess().request(
            method, path_qs, headers=headers, body=payload
        )
        return resp.status, resp.body

    # ------------------------------------------------------------ object ops
    async def put_object(self, key: str, data: bytes) -> None:
        status, body = await self._request("PUT", self._url_path(key), payload=data)
        if status not in (200, 201):
            raise S3Error(status, body.decode("utf-8", "replace"))

    async def get_object(self, key: str) -> bytes:
        status, body = await self._request("GET", self._url_path(key))
        if status == 404:
            raise FileNotFoundError(key)
        if status != 200:
            raise S3Error(status, body.decode("utf-8", "replace"))
        return body

    async def delete_object(self, key: str) -> None:
        status, body = await self._request("DELETE", self._url_path(key))
        if status not in (200, 204, 404):
            raise S3Error(status, body.decode("utf-8", "replace"))

    async def list_objects(self, prefix: str = "") -> list[dict]:
        """ListObjectsV2; returns [{key, size}] (continuation handled)."""
        out: list[dict] = []
        token: str | None = None
        base = f"/{self.bucket}" if self._path_style else "/"
        while True:
            query = {"list-type": "2"}
            if prefix:
                query["prefix"] = prefix
            if token:
                query["continuation-token"] = token
            status, body = await self._request("GET", base, query=query)
            if status != 200:
                raise S3Error(status, body.decode("utf-8", "replace"))
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag.split("}")[0] + "}"
            for item in root.findall(f"{ns}Contents"):
                out.append({
                    "key": item.findtext(f"{ns}Key"),
                    "size": int(item.findtext(f"{ns}Size") or 0),
                })
            token = root.findtext(f"{ns}NextContinuationToken")
            if not token:
                return out
