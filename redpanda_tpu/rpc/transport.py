"""RPC client transports.

- ``Transport``: one TCP connection; concurrent requests matched to
  responses by correlation id (rpc/transport.h — _correlations map).
- ``ReconnectTransport``: wraps a Transport with exponential backoff
  reconnection (rpc/reconnect_transport.h backoff_policy).
- ``ConnectionCache``: one ReconnectTransport per peer node id
  (rpc/connection_cache.h); the reference assigns each cached connection to
  a shard via jump-consistent hash — we keep the hash so ownership is
  deterministic, even though a single asyncio loop plays all shards.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time

from redpanda_tpu.finjector import honey_badger
from redpanda_tpu.hashing.jump import jump_consistent_hash
from redpanda_tpu.observability import probes
from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.rpc import wire

logger = logging.getLogger("rpc.transport")

# transport-level failure probe: one site below every per-method probe
# (rpc.service registers <service>.<method>), so chaos runs can fault the
# WIRE itself — exception/delay/wedge on any outbound send
honey_badger.register_probe("rpc", "send")


class RpcError(Exception):
    def __init__(self, status: int, msg: str = "") -> None:
        super().__init__(msg or f"rpc status {status}")
        self.status = status


class RpcBackpressure(RpcError):
    """The peer shed the request at dispatch (STATUS_BACKPRESSURE): its
    handler never ran, so a resend is always safe. Raft's retry loops
    treat this like any transient send failure — backoff and resend —
    which is exactly the open-loop-overload contract: shed, counted,
    never lost."""

    def __init__(self, msg: str = "") -> None:
        super().__init__(wire.STATUS_BACKPRESSURE, msg or "peer backpressure")


class TransportClosed(Exception):
    pass


class Transport:
    def __init__(
        self, host: str, port: int, compress: bool = False, ssl_context=None
    ) -> None:
        self.host = host
        self.port = port
        self.compress = compress
        self.ssl_context = ssl_context
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._corr = itertools.count(1)
        self._inflight: dict[int, asyncio.Future] = {}
        self._read_task: asyncio.Task | None = None

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self.ssl_context
        )
        self._read_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                h, _ctx, body = await wire.read_message(self._reader)
                fut = self._inflight.pop(h.correlation_id, None)
                if fut is None or fut.done():
                    continue
                if h.meta == wire.STATUS_SUCCESS:
                    fut.set_result(body)
                elif h.meta == wire.STATUS_BACKPRESSURE:
                    fut.set_exception(RpcBackpressure())
                else:
                    fut.set_exception(RpcError(h.meta))
        except asyncio.CancelledError:
            self._fail_all(TransportClosed("cancelled"))
        except Exception as e:  # noqa: BLE001 — any read/decode error is fatal
            self._fail_all(TransportClosed(str(e)))

    def _fail_all(self, exc: Exception) -> None:
        inflight, self._inflight = self._inflight, {}
        for fut in inflight.values():
            if not fut.done():
                fut.set_exception(exc)
        self._writer = None

    async def send(self, method_id: int, payload: bytes, timeout: float | None = None) -> bytes:
        if self._writer is None:
            raise TransportClosed("not connected")
        t0 = time.perf_counter()
        try:
            with tracer.span("rpc.send") as sp:
                sp.set("method_id", method_id)
                if honey_badger.enabled:  # keep the disabled hot path to one
                    # check, not a coroutine allocation per outbound RPC
                    # (hbadger.h:30-37 compiles probes out of release
                    # builds; this is our analogue). Inside the timed span
                    # deliberately: an injected slow/failed link must land
                    # in rpc_request_latency_us and the rpc.send span, or
                    # chaos runs judge a histogram the fault never touched.
                    await honey_badger.maybe_inject("rpc", "send")
                    if self._writer is None:
                        # the transport closed while the fault blocked us
                        raise TransportClosed("not connected")
                corr = next(self._corr)
                fut: asyncio.Future = asyncio.get_event_loop().create_future()
                self._inflight[corr] = fut
                # pandascope: a sampled request (live span joining an
                # ambient trace) carries its context on the wire so the
                # peer's handler span JOINs the same trace; an unsampled
                # one (tracer off, no ambient trace — heartbeats) stays a
                # version-0 frame with zero extra bytes
                ctx = None
                if sp.trace_id is not None:
                    ctx = wire.TraceContext(sp.trace_id, sp.span_id, True)
                self._writer.write(
                    wire.frame(
                        payload, method_id, corr, compress=self.compress,
                        trace_ctx=ctx,
                    )
                )
                await self._writer.drain()
                try:
                    if timeout is not None:
                        return await asyncio.wait_for(fut, timeout)
                    return await fut
                except asyncio.TimeoutError:
                    self._inflight.pop(corr, None)
                    raise RpcError(wire.STATUS_REQUEST_TIMEOUT, "client timeout")
        finally:
            # every exit path — success, timeout, peer-closed RpcError —
            # lands in the histogram, or an incident's latency never shows
            probes.observe_us(probes.rpc_request_hist, t0)

    async def close(self) -> None:
        # Take the writer FIRST: cancelling the read loop runs _fail_all,
        # which nulls _writer — checking it afterwards means the socket is
        # never actually closed, and the server leaks a connection handler
        # per churn (caught by the tron soak test's zero-leak assertion).
        w, self._writer = self._writer, None
        if self._read_task is not None:
            self._read_task.cancel()
            try:
                await self._read_task
            except (asyncio.CancelledError, Exception):
                pass
            self._read_task = None
        if w is not None:
            try:
                w.close()
                await w.wait_closed()
            except Exception:
                pass
        self._fail_all(TransportClosed("closed"))


class BackoffPolicy:
    """Exponential backoff with a cap (rpc/backoff_policy.h)."""

    def __init__(self, base_ms: int = 50, max_ms: int = 2000) -> None:
        self.base_ms = base_ms
        self.max_ms = max_ms
        self._fails = 0

    def next_backoff(self) -> float:
        d = min(self.max_ms, self.base_ms * (2 ** min(self._fails, 10)))
        self._fails += 1
        return d / 1000

    def reset(self) -> None:
        self._fails = 0


class ReconnectTransport:
    def __init__(
        self,
        host: str,
        port: int,
        backoff: BackoffPolicy | None = None,
        compress: bool = False,
        ssl_context=None,
    ) -> None:
        self.host = host
        self.port = port
        self._backoff = backoff or BackoffPolicy()
        self._compress = compress
        self.ssl_context = ssl_context
        self._transport: Transport | None = None
        self._lock = asyncio.Lock()
        self._next_attempt = 0.0  # monotonic deadline gating reconnects

    @property
    def connected(self) -> bool:
        return self._transport is not None and self._transport.connected

    async def get_connected(self, timeout: float | None = None) -> Transport:
        async with self._lock:
            if self._transport is not None and self._transport.connected:
                return self._transport
            # Honour the backoff window: refuse to dial again until it
            # elapses (reconnect_transport.h semantics — callers see an
            # immediate error, the peer is not hammered).
            now = asyncio.get_event_loop().time()
            if now < self._next_attempt:
                raise TransportClosed(
                    f"{self.host}:{self.port} in backoff for {self._next_attempt - now:.2f}s"
                )
            t = Transport(
                self.host, self.port, compress=self._compress,
                ssl_context=self.ssl_context,
            )
            try:
                if timeout is not None:
                    await asyncio.wait_for(t.connect(), timeout)
                else:
                    await t.connect()
            except (OSError, asyncio.TimeoutError) as e:
                delay = self._backoff.next_backoff()
                self._next_attempt = asyncio.get_event_loop().time() + delay
                raise TransportClosed(f"connect {self.host}:{self.port} failed ({e}); backoff {delay:.2f}s")
            self._backoff.reset()
            self._next_attempt = 0.0
            self._transport = t
            return t

    async def send(self, method_id: int, payload: bytes, timeout: float | None = None) -> bytes:
        t = await self.get_connected(timeout)
        return await t.send(method_id, payload, timeout=timeout)

    async def close(self) -> None:
        async with self._lock:
            if self._transport is not None:
                await self._transport.close()
                self._transport = None


class ConnectionCache:
    """node_id → ReconnectTransport (rpc/connection_cache.h)."""

    def __init__(self, n_shards: int = 1, ssl_context=None) -> None:
        self._n_shards = max(1, n_shards)
        self.ssl_context = ssl_context  # dial peers over TLS when set
        self._by_node: dict[int, ReconnectTransport] = {}
        self._addrs: dict[int, tuple[str, int]] = {}
        self._stale: list[ReconnectTransport] = []

    def shard_for(self, node_id: int) -> int:
        return jump_consistent_hash(node_id, self._n_shards)

    def register(self, node_id: int, host: str, port: int) -> None:
        self._addrs[node_id] = (host, port)
        existing = self._by_node.pop(node_id, None)
        if existing is not None:
            # register() is callable from synchronous wiring code, so defer
            # the close to the next async touch point instead of
            # fire-and-forgetting a task that may never run.
            self._stale.append(existing)

    def contains(self, node_id: int) -> bool:
        return node_id in self._addrs

    def get(self, node_id: int) -> ReconnectTransport:
        t = self._by_node.get(node_id)
        if t is None:
            host, port = self._addrs[node_id]
            t = ReconnectTransport(host, port, ssl_context=self.ssl_context)
            self._by_node[node_id] = t
        return t

    async def _drain_stale(self) -> None:
        stale, self._stale = self._stale, []
        for t in stale:
            await t.close()

    async def remove(self, node_id: int) -> None:
        self._addrs.pop(node_id, None)
        t = self._by_node.pop(node_id, None)
        if t is not None:
            await t.close()
        await self._drain_stale()

    async def close(self) -> None:
        for node_id in list(self._by_node):
            await self.remove(node_id)
        await self._drain_stale()
