"""Internal RPC server.

Parity with rpc::server (rpc/server.cc:47-99): an accept loop hands each
connection to a pluggable ``protocol`` — the internal simple_protocol here;
the Kafka layer plugs its own protocol into the same engine
(kafka/server/protocol.py), mirroring how the reference reuses one server
for both (application.cc:791-850).

simple_protocol semantics (rpc/simple_protocol.cc): read header, verify
checksums, look up method id; unknown id → status 404 in the reply header
meta (simple_protocol.cc:101-103); handler exception → 500; per-connection
requests may overlap, responses carry the request's correlation id.
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.rpc import wire
from redpanda_tpu.rpc.service import ServiceHandler

logger = logging.getLogger("rpc.server")


class SimpleProtocol:
    """Method-id dispatch over registered services.

    ``node_id`` stamps the JOINed per-request span (pandascope): a
    process hosting several in-process brokers shares one tracer, so the
    span itself must say which broker served the request."""

    name = "vectorized internal rpc protocol"

    def __init__(self, node_id: int | None = None, inflight_gate=None) -> None:
        self._methods: dict[int, ServiceHandler] = {}
        self.node_id = node_id
        # resource_mgmt.admission.InflightGate (or None = uncapped, the
        # historical semantics): bounds concurrent dispatched requests and
        # their body bytes, shedding WHOLE requests at dispatch with
        # STATUS_BACKPRESSURE before the handler runs — a shed request did
        # nothing, so peers resend safely (transport.RpcBackpressure)
        if inflight_gate is not None:
            # leakwatch balance recorder with coproc_leakwatch on; the raw
            # gate untouched (zero overhead) otherwise
            from redpanda_tpu.coproc import leakwatch

            inflight_gate = leakwatch.wrap(inflight_gate, "rpc.inflight_gate")
        self.inflight_gate = inflight_gate

    def register_service(self, handler: ServiceHandler) -> None:
        for mid in handler.method_ids():
            if mid in self._methods:
                raise ValueError(f"duplicate method id {mid:#x}")
            self._methods[mid] = handler

    async def apply(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        write_lock = asyncio.Lock()
        pending: set[asyncio.Task] = set()
        try:
            while True:
                try:
                    h, ctx, body = await wire.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                reserved = None
                if self.inflight_gate is not None:
                    reserved = self.inflight_gate.try_enter(len(body))
                    if reserved is None:
                        # shed at dispatch: answer backpressure without
                        # spawning the handler (counted by the gate;
                        # retriable by contract — nothing ran)
                        out = wire.frame(
                            b"", wire.STATUS_BACKPRESSURE, h.correlation_id
                        )
                        async with write_lock:
                            try:
                                writer.write(out)
                                await writer.drain()
                            except (ConnectionResetError, BrokenPipeError):
                                return
                        continue
                # Handlers overlap across requests on one connection; each
                # response is written atomically under the lock.
                t = asyncio.ensure_future(
                    self._handle_one(h, body, writer, write_lock, ctx)
                )
                pending.add(t)
                t.add_done_callback(pending.discard)
                if reserved is not None:
                    # release via done-callback, NOT inside the handler: a
                    # task cancelled before its first step (connection
                    # torn down in the same read that delivered the
                    # frame) never enters the coroutine body, so an
                    # in-handler finally would leak the slot — callbacks
                    # run for cancelled tasks too
                    t.add_done_callback(
                        lambda _t, g=self.inflight_gate, r=reserved: g.leave(r)
                    )
        finally:
            for t in pending:
                t.cancel()

    async def _handle_one(
        self, h: wire.Header, body: bytes, writer, write_lock,
        ctx: wire.TraceContext | None = None,
    ) -> None:
        status = wire.STATUS_SUCCESS
        handler = self._methods.get(h.meta)
        if handler is None:
            status, reply = wire.STATUS_METHOD_NOT_FOUND, b""
        else:
            try:
                # JOINed, never root: an inbound request without wire
                # context (unsampled peer, tracer off) must not mint
                # orphan traces — span(trace_id=None) is the usual no-op.
                # Everything the handler awaits under this span (follower
                # storage.append, coproc dispatch, nested sends) inherits
                # the submitter's trace id and this broker's node stamp.
                with tracer.span(
                    "rpc.handle",
                    trace_id=ctx.trace_id if ctx is not None else None,
                    node=self.node_id,
                ) as sp:
                    if ctx is not None:
                        sp.set("method_id", h.meta)
                        sp.set("parent_span", ctx.parent_span_id)
                    reply = await handler.dispatch(h.meta, body)
            except asyncio.CancelledError:
                raise
            except SystemExit:
                raise
            except Exception:
                logger.exception("rpc handler failed (method %#x)", h.meta)
                status, reply = wire.STATUS_SERVER_ERROR, b""
        out = wire.frame(reply, status, h.correlation_id)
        async with write_lock:
            try:
                writer.write(out)
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass


class Server:
    """TCP accept loop with a pluggable protocol."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, tls=None) -> None:
        self.host = host
        self.port = port
        self.tls = tls  # security.tls.ReloadableTlsContext | None
        self._protocol = None
        self._server: asyncio.AbstractServer | None = None
        self._conn_tasks: set[asyncio.Task] = set()

    def set_protocol(self, protocol) -> None:
        self._protocol = protocol

    async def start(self) -> None:
        assert self._protocol is not None, "set_protocol first"
        ssl_ctx = self.tls.server_context if self.tls is not None else None
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port, ssl=ssl_ctx
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def _on_conn(self, reader, writer) -> None:
        t = asyncio.current_task()
        self._conn_tasks.add(t)
        try:
            await self._protocol.apply(reader, writer)
        except (wire.WireError, ConnectionResetError) as e:
            logger.debug("connection dropped: %s", e)
        finally:
            self._conn_tasks.discard(t)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def stop(self) -> None:
        # Cancel live connection handlers BEFORE wait_closed(): since
        # Python 3.12 wait_closed blocks until every handler returns.
        if self._server is not None:
            self._server.close()
        for t in list(self._conn_tasks):
            t.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._conn_tasks.clear()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
