"""RPC service definitions.

The reference generates a service base class + client protocol + per-method
failure probes from JSON schemas (tools/rpcgen.py). Here a ``ServiceDef`` is
declared inline: methods carry serde codecs for request/response, and method
ids follow the same scheme — ``crc32(namespace:service) ^ crc32(method-key)``
(rpcgen.py:226-236) — so ids are stable across processes.

``Client(stub)`` objects expose one async callable per method;
``ServiceHandler`` dispatches ids to a bound implementation and runs the
honey-badger probe registered per method (rpcgen.py:159-165).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

from redpanda_tpu.finjector import honey_badger


@dataclass(frozen=True)
class MethodDef:
    name: str
    request: object  # serde Struct/Envelope
    response: object
    id: int = 0  # filled by ServiceDef


class ServiceDef:
    def __init__(self, namespace: str, name: str, methods: list[MethodDef]):
        self.namespace = namespace
        self.name = name
        self.id = zlib.crc32(f"{namespace}:{name}".encode())
        self.methods: dict[str, MethodDef] = {}
        self.by_id: dict[int, MethodDef] = {}
        for m in methods:
            mid = self.id ^ zlib.crc32(f"{m.name}:{namespace}".encode())
            bound = MethodDef(m.name, m.request, m.response, mid & 0xFFFFFFFF)
            self.methods[m.name] = bound
            self.by_id[bound.id] = bound
        honey_badger.register_probe(name, *self.methods.keys())


class ServiceHandler:
    """Binds a ServiceDef to an implementation object.

    The implementation provides ``async def <method>(self, request: dict)``
    for each method; dispatch decodes/encodes via the method codecs.
    """

    def __init__(self, definition: ServiceDef, impl) -> None:
        self.definition = definition
        self.impl = impl

    def method_ids(self):
        return self.definition.by_id.keys()

    async def dispatch(self, method_id: int, payload: bytes) -> bytes:
        m = self.definition.by_id[method_id]
        await honey_badger.maybe_inject(self.definition.name, m.name)
        request = m.request.decode(payload)
        response = await getattr(self.impl, m.name)(request)
        return m.response.encode(response)


class Client:
    """Per-service async client over an rpc transport.

    ``await client.method_name(request_dict)`` → response dict. Mirrors the
    generated ``client_protocol`` classes.
    """

    def __init__(self, definition: ServiceDef, transport) -> None:
        self._definition = definition
        self._transport = transport

    def __getattr__(self, name: str):
        m = self._definition.methods.get(name)
        if m is None:
            raise AttributeError(name)

        async def call(request: dict, timeout: float | None = None) -> dict:
            payload = m.request.encode(request)
            raw = await self._transport.send(m.id, payload, timeout=timeout)
            return m.response.decode(raw)

        return call
