"""Internal RPC stack (parity with src/v/rpc).

26-byte checksummed wire header, serde payloads, method-id dispatch with a
pluggable server protocol, reconnecting client transports, and a per-node
connection cache. Raft, the cluster control plane, and the coproc engine
speak this protocol between brokers.
"""

from redpanda_tpu.rpc.serde import (
    BOOL,
    BYTES,
    F64,
    I8,
    I16,
    I32,
    I64,
    STRING,
    U8,
    U16,
    U32,
    U64,
    Envelope,
    Map,
    Optional,
    S,
    Struct,
    Vector,
)
from redpanda_tpu.rpc.server import Server, SimpleProtocol
from redpanda_tpu.rpc.service import Client, MethodDef, ServiceDef, ServiceHandler
from redpanda_tpu.rpc.transport import (
    BackoffPolicy,
    ConnectionCache,
    ReconnectTransport,
    RpcBackpressure,
    RpcError,
    Transport,
    TransportClosed,
)
from redpanda_tpu.rpc.wire import Header, WireError

__all__ = [
    "BOOL", "BYTES", "F64", "I8", "I16", "I32", "I64", "STRING", "U8", "U16",
    "U32", "U64", "Envelope", "Map", "Optional", "S", "Struct", "Vector",
    "Server", "SimpleProtocol", "Client", "MethodDef", "ServiceDef",
    "ServiceHandler", "BackoffPolicy", "ConnectionCache", "ReconnectTransport",
    "RpcBackpressure", "RpcError", "Transport", "TransportClosed", "Header",
    "WireError",
]
