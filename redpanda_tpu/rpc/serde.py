"""Declarative binary serialization for internal RPC and disk types.

The reference walks C++ structs at compile time (reflection/adl.h,
reflection/to_tuple.h) and layers a versioned envelope on top
(serde/envelope.h). Here the same information is a field table interpreted at
runtime: ``Struct`` holds ordered (name, type) pairs; values travel as plain
dicts. Everything is little-endian, matching adl.

Envelope framing (serde/envelope.h): {version u8, compat_version u8,
size u32} then the body; readers written against an older compat version
reject newer incompatible payloads, and trailing bytes added by newer
versions are skipped using the size field.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass


class SerdeError(Exception):
    pass


# ------------------------------------------------------------------ writer/reader
class Writer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(bytes(b))
        return self

    def pack(self, fmt: str, *vals) -> "Writer":
        self._parts.append(struct.pack("<" + fmt, *vals))
        return self

    def build(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = bytes(buf)
        self._pos = 0

    def unpack(self, fmt: str):
        s = struct.Struct("<" + fmt)
        if self._pos + s.size > len(self._buf):
            raise SerdeError("short buffer")
        vals = s.unpack_from(self._buf, self._pos)
        self._pos += s.size
        return vals if len(vals) > 1 else vals[0]

    def take(self, n: int) -> bytes:
        if n < 0 or self._pos + n > len(self._buf):
            raise SerdeError(f"short buffer: want {n}")
        b = self._buf[self._pos : self._pos + n]
        self._pos += n
        return b

    def skip(self, n: int) -> None:
        self.take(n)

    def remaining(self) -> int:
        return len(self._buf) - self._pos


# ------------------------------------------------------------------ types
@dataclass(frozen=True)
class Scalar:
    fmt: str  # struct format char


I8 = Scalar("b")
U8 = Scalar("B")
I16 = Scalar("h")
U16 = Scalar("H")
I32 = Scalar("i")
U32 = Scalar("I")
I64 = Scalar("q")
U64 = Scalar("Q")
F64 = Scalar("d")
BOOL = Scalar("?")


class _String:
    pass


class _Bytes:
    pass


STRING = _String()
BYTES = _Bytes()


@dataclass(frozen=True)
class Vector:
    inner: object


@dataclass(frozen=True)
class Optional:
    inner: object


@dataclass(frozen=True)
class Map:
    key: object
    value: object


@dataclass(frozen=True)
class Struct:
    fields: tuple  # of (name, type)

    def encode(self, msg: dict) -> bytes:
        w = Writer()
        _write(w, self, msg)
        return w.build()

    def decode(self, buf: bytes) -> dict:
        return _read(Reader(buf), self)


def S(*fields) -> Struct:
    return Struct(tuple(fields))


@dataclass(frozen=True)
class Envelope:
    """serde::envelope-style versioned wrapper around a Struct."""

    body: Struct
    version: int = 0
    compat_version: int = 0

    def encode(self, msg: dict) -> bytes:
        inner = self.body.encode(msg)
        return struct.pack("<BBI", self.version, self.compat_version, len(inner)) + inner

    def decode(self, buf: bytes) -> dict:
        r = Reader(buf)
        version, compat, size = r.unpack("BBI")
        if compat > self.version:
            raise SerdeError(
                f"incompatible envelope: peer compat {compat} > our version {self.version}"
            )
        body = r.take(size)
        return self.body.decode(body)


# ------------------------------------------------------------------ codec core
def _write(w: Writer, typ, value) -> None:
    if isinstance(typ, Scalar):
        w.pack(typ.fmt, value)
    elif typ is STRING:
        b = value.encode() if isinstance(value, str) else bytes(value)
        w.pack("i", len(b)).raw(b)
    elif typ is BYTES:
        b = bytes(value)
        w.pack("i", len(b)).raw(b)
    elif isinstance(typ, Vector):
        items = list(value)
        w.pack("i", len(items))
        for item in items:
            _write(w, typ.inner, item)
    elif isinstance(typ, Optional):
        if value is None:
            w.pack("b", 0)
        else:
            w.pack("b", 1)
            _write(w, typ.inner, value)
    elif isinstance(typ, Map):
        items = sorted(value.items()) if isinstance(value, dict) else list(value)
        w.pack("i", len(items))
        for k, v in items:
            _write(w, typ.key, k)
            _write(w, typ.value, v)
    elif isinstance(typ, Struct):
        for name, ft in typ.fields:
            _write(w, ft, value.get(name, _default(ft)) if isinstance(value, dict) else getattr(value, name))
    elif isinstance(typ, Envelope):
        w.raw(typ.encode(value))
    else:
        raise SerdeError(f"unknown type {typ!r}")


def _read(r: Reader, typ):
    if isinstance(typ, Scalar):
        return r.unpack(typ.fmt)
    if typ is STRING:
        n = r.unpack("i")
        return r.take(n).decode()
    if typ is BYTES:
        n = r.unpack("i")
        return r.take(n)
    if isinstance(typ, Vector):
        n = r.unpack("i")
        return [_read(r, typ.inner) for _ in range(n)]
    if isinstance(typ, Optional):
        return _read(r, typ.inner) if r.unpack("b") else None
    if isinstance(typ, Map):
        n = r.unpack("i")
        return {_read(r, typ.key): _read(r, typ.value) for _ in range(n)}
    if isinstance(typ, Struct):
        return {name: _read(r, ft) for name, ft in typ.fields}
    if isinstance(typ, Envelope):
        version, compat, size = r.unpack("BBI")
        if compat > typ.version:
            raise SerdeError("incompatible nested envelope")
        return typ.body.decode(r.take(size))
    raise SerdeError(f"unknown type {typ!r}")


def _default(typ):
    if isinstance(typ, Scalar):
        return 0
    if typ is STRING:
        return ""
    if typ is BYTES:
        return b""
    if isinstance(typ, Vector):
        return []
    if isinstance(typ, Optional):
        return None
    if isinstance(typ, Map):
        return {}
    if isinstance(typ, Struct):
        return {}
    return None
