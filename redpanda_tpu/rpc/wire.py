"""Internal-RPC wire format.

Parity with the reference's 26-byte header (rpc/types.h:73-99): every payload
travels behind ``{version u8, header_checksum u32, compression u8,
payload_size u32, meta u32, correlation_id u32, payload_checksum u64}``.
The header checksum is CRC-32C over everything after the checksum field; the
payload checksum is xxhash64. ``meta`` carries the method id on requests and
an HTTP-style status (rpc/types.h:64-70) on responses. Optional zstd payload
compression mirrors compression_type (rpc/types.h:50-55).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from redpanda_tpu.hashing.crc32c import crc32c
from redpanda_tpu.hashing.xx import xxhash64

HEADER_SIZE = 26
_PRE = struct.Struct("<B I")        # version, header_checksum
_POST = struct.Struct("<B I I I Q")  # compression, payload_size, meta, corr, payload_checksum

COMPRESSION_NONE = 0
COMPRESSION_ZSTD = 1

# rpc::status (rpc/types.h:64-70) — well-known HTTP codes for readability.
STATUS_SUCCESS = 200
STATUS_METHOD_NOT_FOUND = 404
STATUS_REQUEST_TIMEOUT = 408
STATUS_SERVER_ERROR = 500

# Compress payloads above this size when the transport negotiated zstd.
ZSTD_MIN_SIZE = 1024


class WireError(Exception):
    pass


@dataclass
class Header:
    version: int = 0
    compression: int = COMPRESSION_NONE
    payload_size: int = 0
    meta: int = 0
    correlation_id: int = 0
    payload_checksum: int = 0

    def _post_bytes(self) -> bytes:
        return _POST.pack(
            self.compression,
            self.payload_size,
            self.meta,
            self.correlation_id & 0xFFFFFFFF,
            self.payload_checksum,
        )

    def encode(self) -> bytes:
        post = self._post_bytes()
        return _PRE.pack(self.version, crc32c(post)) + post

    @staticmethod
    def decode(buf: bytes) -> "Header":
        if len(buf) < HEADER_SIZE:
            raise WireError(f"short header: {len(buf)}")
        version, hcrc = _PRE.unpack_from(buf, 0)
        post = buf[_PRE.size : HEADER_SIZE]
        if crc32c(post) != hcrc:
            raise WireError("header checksum mismatch")
        compression, size, meta, corr, pcrc = _POST.unpack(post)
        return Header(version, compression, size, meta, corr, pcrc)


def frame(payload: bytes, meta: int, correlation_id: int, compress: bool = False) -> bytes:
    """Build header+payload for one message."""
    compression = COMPRESSION_NONE
    if compress and len(payload) >= ZSTD_MIN_SIZE:
        from redpanda_tpu.compression.codecs import zstd_compress

        payload = zstd_compress(payload)
        compression = COMPRESSION_ZSTD
    h = Header(
        compression=compression,
        payload_size=len(payload),
        meta=meta,
        correlation_id=correlation_id,
        payload_checksum=xxhash64(payload),
    )
    return h.encode() + payload


def open_payload(h: Header, payload: bytes) -> bytes:
    """Verify the payload checksum and undo wire compression."""
    if xxhash64(payload) != h.payload_checksum:
        raise WireError("payload checksum mismatch")
    if h.compression == COMPRESSION_ZSTD:
        from redpanda_tpu.compression.codecs import zstd_uncompress

        return zstd_uncompress(payload)
    if h.compression != COMPRESSION_NONE:
        raise WireError(f"unknown compression {h.compression}")
    return payload
