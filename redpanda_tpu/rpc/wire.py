"""Internal-RPC wire format.

Parity with the reference's 26-byte header (rpc/types.h:73-99): every payload
travels behind ``{version u8, header_checksum u32, compression u8,
payload_size u32, meta u32, correlation_id u32, payload_checksum u64}``.
The header checksum is CRC-32C over everything after the checksum field; the
payload checksum is xxhash64. ``meta`` carries the method id on requests and
an HTTP-style status (rpc/types.h:64-70) on responses. Optional zstd payload
compression mirrors compression_type (rpc/types.h:50-55).

pandascope trace propagation (no reference analogue — seastar requests
never leave their shard, ours hop brokers): a SAMPLED request may carry a
compact Dapper-style trace context ``{trace_id u64, parent_span_id u64,
flags u8}`` between the header and the payload, announced by
``version == VERSION_TRACE_CTX``. An unsampled request (tracer disabled, or
no ambient trace — heartbeats, chatter) stays version 0 and adds ZERO
bytes, so the feature costs nothing until an operator turns tracing on.
The block is deliberately outside both checksums: it is advisory
observability metadata, fixed-size, and keeping it out leaves the
version-0 header layout and its golden checksums untouched.

Upgrade contract: there is no per-connection version negotiation in this
rpc layer, so a version-1 frame requires a pandascope-aware peer — an
older reader would consume the ctx block as payload and desync the
stream. That is exactly why the header is feature-flagged rather than
always-on: ``trace_enabled`` defaults false, and the operator turns it on
only once the whole fleet runs pandascope-aware binaries (the standard
flag-gated wire-change rollout; README "Cluster observability").
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from redpanda_tpu.hashing.crc32c import crc32c
from redpanda_tpu.hashing.xx import xxhash64

HEADER_SIZE = 26
_PRE = struct.Struct("<B I")        # version, header_checksum
_POST = struct.Struct("<B I I I Q")  # compression, payload_size, meta, corr, payload_checksum

# version 1: a TraceContext block follows the header, ahead of the payload
VERSION_TRACE_CTX = 1
_TRACE_CTX = struct.Struct("<Q Q B")  # trace_id, parent_span_id, flags
TRACE_CTX_SIZE = _TRACE_CTX.size
_FLAG_SAMPLED = 0x01
_MASK64 = (1 << 64) - 1

COMPRESSION_NONE = 0
COMPRESSION_ZSTD = 1

# rpc::status (rpc/types.h:64-70) — well-known HTTP codes for readability.
STATUS_SUCCESS = 200
STATUS_METHOD_NOT_FOUND = 404
STATUS_REQUEST_TIMEOUT = 408
# server shed the request at dispatch (rpc inflight cap, resource_mgmt
# budget plane): retriable backpressure — the handler never ran, so the
# caller may safely resend
STATUS_BACKPRESSURE = 429
STATUS_SERVER_ERROR = 500

# Compress payloads above this size when the transport negotiated zstd.
ZSTD_MIN_SIZE = 1024


class WireError(Exception):
    pass


class TraceContext:
    """The trace context that rides a sampled request: enough for the
    receiving broker to JOIN its handler span to the submitter's trace
    (never to mint a new one). ``parent_span_id`` is the sender's rpc.send
    span, so cross-node flamegraphs can anchor the remote legs.

    A ``__slots__`` class, not a dataclass: one is decoded per sampled
    inbound request, and a frozen-dataclass construction costs ~2x (every
    field goes through object.__setattr__) — measured against the
    propagation microbench's <1%-of-an-rpc budget."""

    __slots__ = ("trace_id", "parent_span_id", "sampled")

    def __init__(
        self, trace_id: int, parent_span_id: int = 0, sampled: bool = True
    ) -> None:
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.sampled = sampled

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.parent_span_id == other.parent_span_id
            and self.sampled == other.sampled
        )

    def __repr__(self) -> str:
        return (
            f"TraceContext(trace_id={self.trace_id}, "
            f"parent_span_id={self.parent_span_id}, sampled={self.sampled})"
        )

    def encode(self) -> bytes:
        return _TRACE_CTX.pack(
            self.trace_id & _MASK64,
            self.parent_span_id & _MASK64,
            _FLAG_SAMPLED if self.sampled else 0,
        )

    @staticmethod
    def decode(buf: bytes) -> "TraceContext":
        if len(buf) < TRACE_CTX_SIZE:
            raise WireError(f"short trace context: {len(buf)}")
        tid, parent, flags = _TRACE_CTX.unpack_from(buf, 0)
        return TraceContext(tid, parent, bool(flags & _FLAG_SAMPLED))


@dataclass
class Header:
    version: int = 0
    compression: int = COMPRESSION_NONE
    payload_size: int = 0
    meta: int = 0
    correlation_id: int = 0
    payload_checksum: int = 0

    def _post_bytes(self) -> bytes:
        return _POST.pack(
            self.compression,
            self.payload_size,
            self.meta,
            self.correlation_id & 0xFFFFFFFF,
            self.payload_checksum,
        )

    def encode(self) -> bytes:
        post = self._post_bytes()
        return _PRE.pack(self.version, crc32c(post)) + post

    @staticmethod
    def decode(buf: bytes) -> "Header":
        if len(buf) < HEADER_SIZE:
            raise WireError(f"short header: {len(buf)}")
        version, hcrc = _PRE.unpack_from(buf, 0)
        post = buf[_PRE.size : HEADER_SIZE]
        if crc32c(post) != hcrc:
            raise WireError("header checksum mismatch")
        compression, size, meta, corr, pcrc = _POST.unpack(post)
        return Header(version, compression, size, meta, corr, pcrc)


def frame(
    payload: bytes,
    meta: int,
    correlation_id: int,
    compress: bool = False,
    trace_ctx: TraceContext | None = None,
) -> bytes:
    """Build header+payload for one message. ``trace_ctx`` (sampled
    requests only) rides between header and payload behind
    ``version == VERSION_TRACE_CTX``; ``None`` emits the classic version-0
    frame byte-for-byte — a disabled tracer adds nothing to the wire."""
    compression = COMPRESSION_NONE
    if compress and len(payload) >= ZSTD_MIN_SIZE:
        from redpanda_tpu.compression.codecs import zstd_compress

        payload = zstd_compress(payload)
        compression = COMPRESSION_ZSTD
    h = Header(
        version=VERSION_TRACE_CTX if trace_ctx is not None else 0,
        compression=compression,
        payload_size=len(payload),
        meta=meta,
        correlation_id=correlation_id,
        payload_checksum=xxhash64(payload),
    )
    if trace_ctx is not None:
        return h.encode() + trace_ctx.encode() + payload
    return h.encode() + payload


async def read_message(reader) -> tuple[Header, TraceContext | None, bytes]:
    """Read one framed message off an asyncio stream: header, the optional
    trace-context block (version 1 only), and the verified/uncompressed
    payload. ONE reader for both sides of the wire — the client transport's
    response loop and the server's request loop must agree on where the
    ctx block sits or a sampled frame desyncs the stream."""
    raw = await reader.readexactly(HEADER_SIZE)
    h = Header.decode(raw)
    ctx = None
    if h.version == VERSION_TRACE_CTX:
        ctx = TraceContext.decode(await reader.readexactly(TRACE_CTX_SIZE))
    payload = await reader.readexactly(h.payload_size)
    return h, ctx, open_payload(h, payload)


def open_payload(h: Header, payload: bytes) -> bytes:
    """Verify the payload checksum and undo wire compression."""
    if xxhash64(payload) != h.payload_checksum:
        raise WireError("payload checksum mismatch")
    if h.compression == COMPRESSION_ZSTD:
        from redpanda_tpu.compression.codecs import zstd_uncompress

        return zstd_uncompress(payload)
    if h.compression != COMPRESSION_NONE:
        raise WireError(f"unknown compression {h.compression}")
    return payload
