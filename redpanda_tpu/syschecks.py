"""Startup environment checks (reference: syschecks/syschecks.h:54-64,
used from application.cc:364-373 check_environment).

The reference refuses to start on unsuitable environments (too little
memory, bad filesystem, missing CPU features) with actionable one-line
messages rather than failing obscurely later. Same posture here, adapted to
what actually matters for this runtime: memory floor, data-directory
existence/writability/free space, file-descriptor budget (one asyncio
socket per connection + segment files), and an event-loop clock sanity
probe. TPU/device availability is deliberately NOT checked — the data plane
degrades to host paths by design (ops/crc_backend.py, coproc/column_plan.py).

``check_environment(cfg)`` raises :class:`SysCheckError` listing EVERY
failed check (an operator fixes them in one pass, not one per restart).
"""

from __future__ import annotations

import errno
import os
import resource
import time

# Floors chosen against measured engine needs: a 64-partition coproc tick
# stages ~20 MB of exploded batches and jax/XLA itself needs ~400 MB RSS.
MIN_MEMORY_BYTES = 1 << 30
MIN_FREE_DISK_BYTES = 256 << 20
MIN_FDS = 1024


class SysCheckError(RuntimeError):
    """Environment unfit to start; .failures lists every failed check."""

    def __init__(self, failures: list[str]):
        self.failures = failures
        super().__init__(
            "environment checks failed:\n  - " + "\n  - ".join(failures)
        )


def _total_memory_bytes() -> int | None:
    try:
        page = os.sysconf("SC_PAGE_SIZE")
        pages = os.sysconf("SC_PHYS_PAGES")
        return page * pages
    except (ValueError, OSError):
        return None


def check_memory(min_bytes: int = MIN_MEMORY_BYTES) -> str | None:
    total = _total_memory_bytes()
    if total is not None and total < min_bytes:
        return (
            f"memory: {total >> 20} MiB available, need >= {min_bytes >> 20} MiB "
            "(syschecks::memory)"
        )
    return None


def check_data_directory(path: str, min_free: int = MIN_FREE_DISK_BYTES) -> list[str]:
    out = []
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        return [f"data_directory: cannot create {path!r}: {e.strerror}"]
    if not os.access(path, os.W_OK):
        out.append(f"data_directory: {path!r} is not writable")
        return out
    # prove a real write works (catches read-only remounts access() misses)
    probe = os.path.join(path, ".rp_write_probe")
    try:
        with open(probe, "wb") as f:
            f.write(b"ok")
        os.unlink(probe)
    except OSError as e:
        out.append(f"data_directory: write probe failed in {path!r}: {e.strerror}")
    try:
        st = os.statvfs(path)
        free = st.f_bavail * st.f_frsize
        if free < min_free:
            out.append(
                f"data_directory: {free >> 20} MiB free on {path!r}, "
                f"need >= {min_free >> 20} MiB"
            )
    except OSError:
        pass
    return out


def check_fd_limit(min_fds: int = MIN_FDS) -> str | None:
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    except (ValueError, OSError):
        return None
    if soft < min_fds:
        if hard >= min_fds:
            # raise the soft limit ourselves, as rpk's tuner would
            try:
                resource.setrlimit(resource.RLIMIT_NOFILE, (min_fds, hard))
                return None
            except (ValueError, OSError):
                pass
        return (
            f"fd_limit: RLIMIT_NOFILE soft={soft}, need >= {min_fds} "
            "(raise with `ulimit -n`)"
        )
    return None


def check_clock() -> str | None:
    """monotonic must actually be monotonic and advance (paravirt clocks
    gone bad stall every timeout in the runtime)."""
    a = time.monotonic()
    b = time.monotonic()
    if b < a:
        return "clock: time.monotonic went backwards"
    return None


def check_environment(cfg=None, *, data_directory: str | None = None) -> None:
    """Run every check; raise SysCheckError listing all failures.

    Accepts either a Configuration (reads .data_directory) or an explicit
    path. Called from Application.start() before any service starts.
    """
    if data_directory is None and cfg is not None:
        data_directory = getattr(cfg, "data_directory", None)
    failures: list[str] = []
    # floors passed explicitly so they read the CURRENT module globals
    # (operators and tests can tune them at runtime)
    m = check_memory(MIN_MEMORY_BYTES)
    if m:
        failures.append(m)
    if data_directory:
        failures.extend(
            check_data_directory(str(data_directory), MIN_FREE_DISK_BYTES)
        )
    f = check_fd_limit()
    if f:
        failures.append(f)
    c = check_clock()
    if c:
        failures.append(c)
    if failures:
        raise SysCheckError(failures)
