"""Typed configuration store.

Parity with src/v/config: ``Property`` mirrors base_property.h:30 /
property.h:25 (name, description, default, validator, YAML/JSON (de)ser)
and ``Configuration`` mirrors configuration.cc's `shard_local_cfg()`
singleton — the property groups below cover the key knobs the reference
exposes (kafka/rpc/admin endpoints, raft timings, storage sizing and
retention, coproc_* from configuration.h:57-61, quotas, tx). Unknown keys
are preserved so configs written by newer versions round-trip.
"""

from redpanda_tpu.config.properties import (
    Configuration,
    Property,
    ValidationError,
    shard_local_cfg,
)

__all__ = ["Configuration", "Property", "ValidationError", "shard_local_cfg"]
