"""io-config.json: the on-disk contract between `rpk iotune` (writer) and
the broker (reader) — the analogue of the reference's io-properties file
that `rpk iotune` produces and the IO scheduler consumes at startup.

Lives under config/ (not cli/) because both the operator tool and the
data-plane Application depend on the format.
"""

from __future__ import annotations

import json
import os

IO_CONFIG_NAME = "io-config.json"


def write_io_config(data_dir: str, result: dict) -> str:
    path = os.path.join(data_dir, IO_CONFIG_NAME)
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    return path


def load_io_config(data_dir: str) -> dict | None:
    """Startup hook: the broker publishes these numbers when present."""
    try:
        with open(os.path.join(data_dir, IO_CONFIG_NAME)) as f:
            loaded = json.load(f)
    except (OSError, ValueError):
        return None
    return loaded if isinstance(loaded, dict) and loaded.get("version") == 1 else None
