"""Property tables + the broker configuration.

See package docstring. Reference: config/base_property.h:30 (metadata +
validation), config/property.h:25 (typed), config/configuration.cc (the
property set), application.cc:312-362 (YAML hydration to every shard).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable


class ValidationError(ValueError):
    pass


@dataclass
class Property:
    name: str
    description: str
    default: Any
    type: type = str
    validator: Callable[[Any], str | None] | None = None  # returns error or None
    needs_restart: bool = True

    def coerce(self, value: Any) -> Any:
        if value is None:
            return None
        if self.type is bool and isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        try:
            return self.type(value)
        except (TypeError, ValueError) as e:
            raise ValidationError(f"{self.name}: {e}") from e

    def validate(self, value: Any) -> None:
        if self.validator is not None:
            err = self.validator(value)
            if err:
                raise ValidationError(f"{self.name}: {err}")


def _positive(v) -> str | None:
    return None if v is None or v > 0 else "must be positive"


def _non_negative(v) -> str | None:
    return None if v is None or v >= 0 else "must be >= 0"


def _port(v) -> str | None:
    if v is None:
        return "port may not be empty"
    return None if 0 <= v <= 65535 else "not a port"


# The reference's property groups (configuration.cc), trimmed to the knobs
# this build actually consumes plus the well-known ones operators expect.
PROPERTIES: list[Property] = [
    # --- identity / listeners
    Property("node_id", "Unique broker id", 0, int, _non_negative),
    Property("cluster_id", "Cluster identity string", "redpanda_tpu"),
    Property("data_directory", "Data directory", "/var/lib/redpanda_tpu"),
    Property("kafka_api_host", "Kafka API bind host", "127.0.0.1"),
    Property("kafka_api_port", "Kafka API port", 9092, int, _port),
    Property("advertised_kafka_api_host", "Advertised kafka host", "127.0.0.1"),
    Property("advertised_kafka_api_port", "Advertised kafka port", 9092, int, _port),
    Property("rpc_server_host", "Internal RPC bind host", "127.0.0.1"),
    Property("rpc_server_port", "Internal RPC port", 33145, int, _port),
    Property("admin_api_host", "Admin API bind host", "127.0.0.1"),
    Property("admin_api_port", "Admin API port", 9644, int, _port),
    Property("admin_api_require_auth", "Require auth on the admin API", False, bool),
    Property("admin_api_auth_token", "Static bearer token for the admin API", ""),
    # --- TLS (per listener, hot-reloadable: application.cc:704-719)
    Property("kafka_api_tls_enabled", "TLS on the kafka listener", False, bool),
    Property("kafka_api_tls_cert_file", "Kafka listener cert (PEM)", ""),
    Property("kafka_api_tls_key_file", "Kafka listener key (PEM)", ""),
    Property("kafka_api_tls_truststore_file", "Kafka listener CA bundle", ""),
    Property("kafka_api_tls_require_client_auth", "Kafka mTLS", False, bool),
    Property("rpc_server_tls_enabled", "TLS on the internal RPC mesh", False, bool),
    Property("rpc_server_tls_cert_file", "RPC cert (PEM)", ""),
    Property("rpc_server_tls_key_file", "RPC key (PEM)", ""),
    Property("rpc_server_tls_truststore_file", "RPC CA bundle", ""),
    Property("rpc_server_tls_require_client_auth", "RPC mTLS", False, bool),
    Property("admin_api_tls_enabled", "TLS on the admin API", False, bool),
    Property("admin_api_tls_cert_file", "Admin cert (PEM)", ""),
    Property("admin_api_tls_key_file", "Admin key (PEM)", ""),
    Property("admin_api_tls_truststore_file", "Admin CA bundle", ""),
    Property("admin_api_tls_require_client_auth", "Admin mTLS", False, bool),
    Property("seed_servers", "Seed broker list host:port,...", ""),
    # --- raft timings (configuration.cc raft group)
    Property("raft_election_timeout_ms", "Election timeout", 1500, int, _positive, needs_restart=False),
    Property("raft_heartbeat_interval_ms", "Leader heartbeat interval", 150, int, _positive, needs_restart=False),
    Property("raft_recovery_concurrency", "Parallel follower recoveries", 4, int, _positive),
    # --- storage (log_config application.cc:421-443)
    Property("log_segment_size", "Segment roll size bytes", 128 * 1024 * 1024, int, _positive),
    Property("log_retention_bytes", "Default retention bytes (-1 none)", -1, int),
    Property("log_retention_ms", "Default retention ms (-1 none)", 7 * 24 * 3600 * 1000, int),
    Property("log_compaction_interval_ms", "Housekeeping cadence", 10_000, int, _positive),
    Property("fsync_on_append", "Flush to disk on quorum writes", True, bool),
    # --- kafka server
    Property("auto_create_topics_enabled", "Auto-create topics on metadata", True, bool),
    Property("default_topic_partitions", "Default partition count", 1, int, _positive),
    Property("default_topic_replication", "Default replication factor", 1, int, _positive),
    Property("group_topic_partitions", "__consumer_offsets partitions", 16, int, _positive),
    Property("fetch_poll_interval_ms", "Long-poll re-check cadence", 20, int, _positive, needs_restart=False),
    Property("unsafe_relaxed_acks", "CONSISTENCY-TESTING ONLY: ack acks=-1 at leader level (deliberately unsafe)", False, bool),
    Property("target_quota_byte_rate", "Per-client produce quota B/s (0 off)", 0, int, _non_negative, needs_restart=False),
    Property("kafka_qdc_enable", "Queue-depth latency control on the kafka path", False, bool),
    Property("kafka_qdc_max_latency_ms", "qdc target handler latency", 80, int, _positive),
    Property("debug_sanitize_files", "Debug file-handle sanitizer on storage I/O", False, bool),
    # --- observability (pandaprobe; probes at /metrics are always on).
    # All three snapshot into the tracer once at app start: needs_restart
    # stays True until a runtime config-set path actually re-applies them
    # (tracer.configure() itself is hot-safe when that path arrives).
    Property("trace_enabled", "Record pandaprobe spans (GET /v1/trace/recent)", False, bool),
    Property("trace_ring_capacity", "Bounded span ring size", 2048, int, _positive),
    Property("trace_slow_threshold_ms", "Spans over this land in the slow-request log", 500, int, _positive),
    # pandapulse (observability/pulse.py): the flight recorder installs a
    # span sink on the tracer commit path; it records whenever tracing is
    # on (trace_enabled is the whole plane's rollout gate). profile_hz
    # runs the wall-sampling profiler thread; 0 = no thread at all.
    Property(
        "pulse_enabled",
        "Install the pandapulse flight recorder (per-launch lifecycle "
        "timelines at GET /v1/profile/timeline; records while tracing is on)",
        True, bool,
    ),
    Property(
        "pulse_ring_capacity",
        "Bounded flight-recorder span ring size",
        8192, int, _positive,
    ),
    Property(
        "profile_hz",
        "Wall-profile sampling rate for the pandapulse profiler thread "
        "(0 = off, no thread; ~19 Hz recommended when on — prime, aliases "
        "with nothing periodic)",
        0.0, float, _non_negative,
    ),
    # pandatrend (observability/history.py): the bounded metrics-history
    # ring behind GET /v1/history, `rpk debug trend` and the Perfetto
    # counter tracks. interval 0 = off AND no recorder thread (the
    # profile_hz=0 contract); the ring is bounded both by window count
    # and by history_max_bytes, evicting oldest-first.
    Property(
        "history_interval_s",
        "Metrics-history sampling cadence in seconds (pandatrend delta "
        "windows; 0 = off, no recorder thread)",
        5.0, float, _non_negative,
    ),
    Property(
        "history_windows",
        "Maximum retained metrics-history delta windows (oldest evicted "
        "first; the byte budget below also bounds the ring)",
        240, int, _positive,
    ),
    Property(
        "history_max_bytes",
        "Estimated byte budget for the metrics-history ring (label-"
        "cardinality growth evicts history, never grows the process)",
        4 * 1024 * 1024, int, _positive,
    ),
    Property(
        "slo_objectives_file",
        "YAML/JSON SLO objective spec judged at GET /v1/slo (empty = the "
        "lenient broker defaults in observability/slo.py); loading a spec "
        "arms per-metric breach thresholds for trace exemplars",
        "",
    ),
    # --- security
    Property("enable_sasl", "Require SASL on the kafka listener", False, bool),
    Property("superusers", "Comma-separated superuser principals", ""),
    # --- tx / idempotence
    Property("enable_idempotence", "Accept idempotent producers", True, bool),
    Property("enable_transactions", "Accept transactional producers", True, bool),
    Property("transactional_id_expiration_ms", "Idle tx expiry", 15 * 60 * 1000, int, _positive),
    # --- resource management / budget plane (resource_mgmt/budgets.py;
    # memory_groups.h posture: one total split into per-subsystem accounts,
    # admission sheds with retriable backpressure on exhaustion)
    Property(
        "resource_memory_total_mb",
        "Total byte budget the plane carves into per-subsystem accounts "
        "(kafka_produce 25%, rpc 12.5%, coproc 25%, storage 25%, raft "
        "12.5% — see resource_mgmt/budgets.py DEFAULT_SPLIT)",
        512, int, _positive,
    ),
    Property(
        "resource_pressure_warn_pct",
        "Worst-account occupancy fraction at which MemoryPressure reads "
        "warn (autotune shrinks launch knobs)",
        0.75, float, _positive,
    ),
    Property(
        "resource_pressure_critical_pct",
        "Occupancy fraction at which MemoryPressure reads critical (arena "
        "free-list trims, column cache halves, launch knobs floor)",
        0.90, float, _positive,
    ),
    Property(
        "rpc_server_max_inflight_requests",
        "Concurrent dispatched requests the internal rpc server admits "
        "before shedding with STATUS_BACKPRESSURE (body bytes are bounded "
        "separately by the rpc memory account)",
        1024, int, _positive,
    ),
    # --- coproc (configuration.h:57-61)
    Property("coproc_enable", "Enable the TPU transform engine", False, bool),
    Property("coproc_max_batch_size", "Max read per ntp per tick", 32 * 1024, int, _positive),
    Property("coproc_max_inflight_bytes", "Read semaphore budget", 10 * 1024 * 1024, int, _positive),
    Property("coproc_offset_flush_interval_ms", "Offset snapshot cadence", 300_000, int, _positive),
    Property(
        "coproc_host_workers",
        "Host-stage worker pool size for the transform engine (0 = inline single-thread path)",
        min(4, os.cpu_count() or 1), int, _non_negative,
    ),
    Property(
        "coproc_host_pool_probe",
        "Measure real parallel capacity before sharding host stages (quota-limited boxes advertise CPUs they don't have); false trusts coproc_host_workers as-is",
        True, bool,
    ),
    Property(
        "coproc_host_pool_recal_launches",
        "Re-run the inline-vs-sharded host-pool probe every N shardable launches (burstable hosts change capacity over time); 0 pins the first measurement forever",
        512, int, _non_negative,
    ),
    Property(
        "coproc_gather_frame",
        "Zero-copy harvest: frame byte-identity transform output straight from the joined blob's (offset, len) columns instead of packing a padded row matrix",
        True, bool,
    ),
    Property(
        "coproc_structural_parse",
        "Allow the structural-index fused parse ladder (rp_explode_find2 + one fused extraction crossing); the engine still MEASURES fused-vs-staged on the first representative launch and pins the winner. False pins the scalar staged ladder outright",
        True, bool,
    ),
    Property(
        "coproc_device_column_cache_mb",
        "LRU byte budget for the device-resident column cache (repeat scripts over unchanged batch windows skip the host parse/extract ladder and the H2D replay); 0 disables it",
        32, int, _non_negative,
    ),
    # --- coproc launch knobs / autotune (governor ADMISSION domain)
    Property(
        "coproc_group_ticks_per_launch",
        "How many ticks' worth of input one coproc launch fuses (the "
        "per-ntp read budget multiplier); the autotune starting point",
        1, int, _positive,
    ),
    Property(
        "coproc_group_ticks_max",
        "Autotune cap on group_ticks_per_launch",
        8, int, _positive,
    ),
    Property(
        "coproc_launch_depth",
        "Concurrent submit+harvest regions across all script fibers; the "
        "autotune starting point",
        4, int, _positive,
    ),
    Property(
        "coproc_launch_depth_max",
        "Autotune cap on launch_depth",
        8, int, _positive,
    ),
    Property(
        "coproc_autotune_launch",
        "Let the governor move group_ticks_per_launch/launch_depth "
        "dynamically (hysteresis-bounded, journaled under the admission "
        "domain) off the success-only dispatch-leg p99.9 and the budget "
        "plane's occupancy; false pins the static knobs",
        True, bool,
    ),
    # --- coproc multi-chip mesh (coproc/meshrunner.py)
    Property(
        "coproc_mesh_devices",
        "Shard the coproc partition axis over an N-device mesh (pjit/shard_map; per-device sub-launches, one SPMD predicate program). 0/1 keeps the single-device engine; clamped to the devices actually present",
        0, int, _non_negative,
    ),
    Property(
        "coproc_mesh_backend",
        "jax backend whose devices the mesh spans ('' = default backend; 'cpu' = the virtual host-platform mesh, for forced-multi-device runs)",
        "",
    ),
    Property(
        "coproc_mesh_probe",
        "Measure mesh-vs-single-device on the first representative launch and pin the winner (PROBE_MARGIN posture, journaled in the governor 'mesh' domain); false pins 'mesh' unmeasured",
        True, bool,
    ),
    # --- raft device plane (raft/device_plane.py, BASELINE config 5);
    # the plane spans the coproc mesh topology (coproc_mesh_devices /
    # coproc_mesh_backend >= 2 devices = the sharded crc+vote psum step)
    Property(
        "raft_device_crc_validate",
        "Follower-side batched CRC validation of every append_entries blob in one kernel call (the device plane's measured probe picks host or device; both bit-exact). Off = appends are not CRC-checked on the follower (the historical posture)",
        False, bool,
    ),
    Property(
        "raft_device_vote_tally",
        "Per-tick cross-group heartbeat ack tally as one batched reduction (mesh psum on multi-chip, np.sum on host) feeding HeartbeatManager.last_tick_acks; off = no tally",
        False, bool,
    ),
    # --- coproc fault domains (coproc/faults.py)
    Property(
        "coproc_device_deadline_ms",
        "Per-attempt deadline on every device interaction (dispatch, mask fetch, harvest); a wedged fetch is abandoned after this",
        30_000, int, _positive,
    ),
    Property(
        "coproc_launch_retries",
        "Bounded retries per device interaction before the launch fails closed onto the pure-host path",
        2, int, _non_negative,
    ),
    Property(
        "coproc_retry_backoff_ms",
        "Base exponential backoff between device retries (jittered 50-100%)",
        50, int, _positive,
    ),
    Property(
        "coproc_breaker_threshold",
        "Consecutive device failures that trip the engine's circuit breaker to open (host execution)",
        5, int, _positive,
    ),
    Property(
        "coproc_breaker_cooldown_ms",
        "Open-breaker cooldown before one half-open probe launch may re-admit the device",
        30_000, int, _positive,
    ),
    # --- coproc governor / decision plane (coproc/governor.py)
    Property(
        "coproc_adaptive_deadline",
        "Derive per-domain device deadlines from the observed coproc_stage_latency_us p99.9 (coproc_device_deadline_ms stays the floor and is never undercut); false pins every domain to the static knob",
        True, bool,
    ),
    Property(
        "coproc_adaptive_deadline_margin",
        "Multiplier over the observed stage p99.9 when deriving an adaptive deadline (clamped to [floor, 8x floor])",
        4.0, float, _positive,
    ),
    Property(
        "coproc_governor_journal_capacity",
        "Bounded in-memory governor decision journal size (GET /v1/governor, rpk debug governor)",
        256, int, _positive,
    ),
    Property(
        "coproc_lockwatch",
        "Debug: wrap the engine's named locks in a lock-order recorder that journals acquisition edges into the governor 'lockwatch' domain (validates the pandalint static acquisition graph); off = no wrapper installed, zero overhead",
        False, bool,
    ),
    Property(
        "coproc_leakwatch",
        "Debug: wrap the broker's budget accounts/gates/arenas in an acquire-release balance recorder that journals per-site deltas into the governor 'leakwatch' domain (validates the pandalint RSL16xx lifecycle model); off = no proxy installed, zero overhead",
        False, bool,
    ),
    # --- tiered storage (cloud_storage_* group)
    Property("cloud_storage_enabled", "Enable tiered storage", False, bool),
    Property("cloud_storage_bucket", "S3 bucket", ""),
    Property("cloud_storage_region", "S3 region", "us-east-1"),
    Property("cloud_storage_api_endpoint", "S3 endpoint override", ""),
    Property("cloud_storage_access_key", "S3 access key", ""),
    Property("cloud_storage_secret_key", "S3 secret key", ""),
    Property("cloud_storage_segment_max_upload_interval_sec", "Upload cadence", 30, int, _positive),
    Property("cloud_storage_cache_size", "Local read-cache bytes", 1 << 30, int, _positive),
]


class Configuration:
    """Runtime store over the property table (config_store semantics)."""

    def __init__(self) -> None:
        self._props: dict[str, Property] = {p.name: p for p in PROPERTIES}
        self._values: dict[str, Any] = {p.name: p.default for p in PROPERTIES}
        self._extra: dict[str, Any] = {}  # unknown keys, preserved

    # ------------------------------------------------------------ access
    def get(self, name: str) -> Any:
        if name in self._values:
            return self._values[name]
        if name in self._extra:
            return self._extra[name]
        raise KeyError(name)

    def __getattr__(self, name: str) -> Any:
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None

    def set(self, name: str, value: Any) -> None:
        prop = self._props.get(name)
        if prop is None:
            self._extra[name] = value
            return
        value = prop.coerce(value)
        prop.validate(value)
        self._values[name] = value

    def property(self, name: str) -> Property | None:
        return self._props.get(name)

    def properties(self) -> list[Property]:
        return list(self._props.values())

    # ------------------------------------------------------------ io
    def to_dict(self, redact: bool = True) -> dict:
        out = dict(self._values)
        out.update(self._extra)
        if redact:
            for k in list(out):
                if "secret" in k or "password" in k:
                    if out[k]:
                        out[k] = "[secret]"
        return out

    def load_dict(self, data: dict) -> None:
        # the reference nests under a `redpanda:` section in redpanda.yaml
        section = data.get("redpanda", data)
        for k, v in section.items():
            self.set(k, v)

    def load_yaml(self, path: str) -> "Configuration":
        import yaml

        with open(path) as f:
            self.load_dict(yaml.safe_load(f) or {})
        return self

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)


_cfg: Configuration | None = None


def shard_local_cfg() -> Configuration:
    """Process-wide configuration (configuration.cc shard_local_cfg())."""
    global _cfg
    if _cfg is None:
        _cfg = Configuration()
    return _cfg
