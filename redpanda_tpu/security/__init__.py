"""Security layer: SCRAM SASL, credential store, ACLs, authorizer.

Parity with src/v/security: scram_algorithm.h:203 (templated SHA-256/512
SCRAM with client/server message parsing), credential_store.h,
acl.h/acl_store/authorizer.h:39. Credentials and ACLs replicate through the
controller (user_management_cmd / acl_management_cmd batches) exactly like
topics do — the SecurityManager is the STM-side applier.
"""

from redpanda_tpu.security.acl import (
    AclBinding,
    AclBindingFilter,
    AclEntry,
    AclOperation,
    AclPermission,
    AclStore,
    Authorizer,
    PatternType,
    ResourcePattern,
    ResourceType,
)
from redpanda_tpu.security.credential_store import CredentialStore
from redpanda_tpu.security.manager import SecurityManager
from redpanda_tpu.security.scram import (
    ScramAlgorithm,
    ScramCredential,
    ScramServerConversation,
    scram_client_first,
    scram_client_final,
)

__all__ = [
    "AclBinding",
    "AclBindingFilter",
    "AclEntry",
    "AclOperation",
    "AclPermission",
    "AclStore",
    "Authorizer",
    "CredentialStore",
    "PatternType",
    "ResourcePattern",
    "ResourceType",
    "ScramAlgorithm",
    "ScramCredential",
    "ScramServerConversation",
    "SecurityManager",
    "scram_client_first",
    "scram_client_final",
]
