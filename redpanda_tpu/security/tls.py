"""TLS configuration with hot certificate reload.

Parity with the reference's per-listener TLS (application.cc:704-719 builds
reloadable credentials for the internal RPC server; each kafka listener and
the admin server get the same treatment). Python's ssl.SSLContext allows
``load_cert_chain`` to be called again on a LIVE context: connections
already established keep their session, new handshakes pick up the fresh
chain — which is exactly hot reload. ``ReloadableTlsContext.reload()``
re-reads the files; the admin API exposes POST /v1/tls/reload.

mTLS: set require_client_auth and provide a truststore; the client context
verifies the server against the same truststore (private CA deployments).
"""

from __future__ import annotations

import logging
import ssl
from dataclasses import dataclass

logger = logging.getLogger("rptpu.tls")


@dataclass
class TlsConfig:
    enabled: bool = False
    cert_file: str = ""
    key_file: str = ""
    truststore_file: str = ""  # CA bundle for peer verification
    require_client_auth: bool = False  # mTLS


class ReloadableTlsContext:
    """One live server context + client-context factory per listener."""

    def __init__(self, config: TlsConfig):
        self.config = config
        self._server_ctx: ssl.SSLContext | None = None
        if config.enabled:
            self._server_ctx = self._build_server()

    # ------------------------------------------------------------ contexts
    def _build_server(self) -> ssl.SSLContext:
        c = self.config
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(c.cert_file, c.key_file)
        if c.require_client_auth:
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(c.truststore_file)
        return ctx

    @property
    def server_context(self) -> ssl.SSLContext | None:
        """None when TLS is disabled (plaintext listener)."""
        return self._server_ctx

    def client_context(self, *, verify: bool = True) -> ssl.SSLContext:
        """Context for dialing a TLS listener of this cluster."""
        c = self.config
        if verify and c.truststore_file:
            ctx = ssl.create_default_context(cafile=c.truststore_file)
            ctx.check_hostname = False  # brokers dial by IP inside the mesh
        else:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if c.require_client_auth and c.cert_file:
            ctx.load_cert_chain(c.cert_file, c.key_file)
        return ctx

    # ------------------------------------------------------------ reload
    def reload(self) -> bool:
        """Re-read cert/key (+truststore) into the LIVE context: existing
        connections are untouched, new handshakes use the fresh chain."""
        if self._server_ctx is None:
            return False
        c = self.config
        self._server_ctx.load_cert_chain(c.cert_file, c.key_file)
        if c.require_client_auth:
            self._server_ctx.load_verify_locations(c.truststore_file)
        logger.info("reloaded TLS credentials from %s", c.cert_file)
        return True
