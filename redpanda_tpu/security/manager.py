"""Security manager: the controller-side applier + frontend for users/ACLs.

Parity with cluster/security_manager + security_frontend: SCRAM user CRUD
and ACL CRUD are controller commands (commands.h:116-150 create/delete/
update_user, create/delete_acls) replicated through raft0 and applied on
every broker, so the credential store and ACL store are cluster-consistent.
The kafka SASL handlers and the admin API both route through this.
"""

from __future__ import annotations

from redpanda_tpu.cluster.commands import Command, CommandType
from redpanda_tpu.security.acl import AclBinding, AclBindingFilter, AclStore
from redpanda_tpu.security.credential_store import CredentialStore
from redpanda_tpu.security.scram import (
    MECHANISMS,
    SCRAM_SHA256,
    ScramCredential,
    make_credential,
)

_USER_ACL_CMDS = [
    CommandType.create_user,
    CommandType.delete_user,
    CommandType.update_user,
    CommandType.create_acls,
    CommandType.delete_acls,
]


class SecurityManager:
    def __init__(self) -> None:
        self.credentials = CredentialStore()
        self.acls = AclStore()

    # ------------------------------------------------------------ wiring
    def attach(self, controller) -> "SecurityManager":
        """Register as the applier for user/acl command types; returns self.
        Frontend methods then need the controller (or a dispatcher) to
        replicate — they accept it per call to stay import-cycle-free."""
        controller.register_applier(_USER_ACL_CMDS, self.apply_command)
        return self

    # ------------------------------------------------------------ apply (every node)
    async def apply_command(self, cmd: Command) -> None:
        d = cmd.data
        if cmd.type == CommandType.create_user:
            self.credentials.put(d["username"], ScramCredential.from_dict(d["credential"]))
        elif cmd.type == CommandType.update_user:
            if not self.credentials.contains(d["username"]):
                raise ValueError(f"unknown user: {d['username']}")
            self.credentials.put(d["username"], ScramCredential.from_dict(d["credential"]))
        elif cmd.type == CommandType.delete_user:
            if not self.credentials.remove(d["username"]):
                raise ValueError(f"unknown user: {d['username']}")
        elif cmd.type == CommandType.create_acls:
            self.acls.add([AclBinding.from_dict(b) for b in d["bindings"]])
        elif cmd.type == CommandType.delete_acls:
            # filters serialized as binding-filter dicts; None = wildcard
            filters = [
                AclBindingFilter(**{k: _flt(k, v) for k, v in f.items()})
                for f in d["filters"]
            ]
            self.acls.remove(filters)

    # ------------------------------------------------------------ command builders
    @staticmethod
    def create_user_cmd(
        username: str, password: str, mechanism: str = SCRAM_SHA256.name,
        iterations: int | None = None,
    ) -> Command:
        algo = MECHANISMS[mechanism]
        cred = make_credential(password, algo, iterations)
        return Command(
            CommandType.create_user,
            {"username": username, "credential": cred.to_dict()},
        )

    @staticmethod
    def update_user_cmd(
        username: str, password: str, mechanism: str = SCRAM_SHA256.name
    ) -> Command:
        cred = make_credential(password, MECHANISMS[mechanism])
        return Command(
            CommandType.update_user,
            {"username": username, "credential": cred.to_dict()},
        )

    @staticmethod
    def delete_user_cmd(username: str) -> Command:
        return Command(CommandType.delete_user, {"username": username})

    @staticmethod
    def create_acls_cmd(bindings: list[AclBinding]) -> Command:
        return Command(
            CommandType.create_acls, {"bindings": [b.to_dict() for b in bindings]}
        )

    @staticmethod
    def delete_acls_cmd(filters: list[AclBindingFilter]) -> Command:
        return Command(
            CommandType.delete_acls,
            {
                "filters": [
                    {
                        "resource_type": int(f.resource_type),
                        "name": f.name,
                        "pattern_type": int(f.pattern_type),
                        "principal": f.principal,
                        "host": f.host,
                        "operation": int(f.operation),
                        "permission": int(f.permission),
                    }
                    for f in filters
                ]
            },
        )


def _flt(key: str, value):
    from redpanda_tpu.security.acl import (
        AclOperation,
        AclPermission,
        PatternType,
        ResourceType,
    )

    if value is None:
        return None
    conv = {
        "resource_type": ResourceType,
        "pattern_type": PatternType,
        "operation": AclOperation,
        "permission": AclPermission,
    }.get(key)
    return conv(value) if conv else value
