"""Credential store: username → SCRAM credential.

Parity with security/credential_store.h. Mutations arrive as applied
controller commands (user_management_cmd batches), so every broker holds
the same verifier material.
"""

from __future__ import annotations

from redpanda_tpu.security.scram import ScramCredential


class CredentialStore:
    def __init__(self) -> None:
        self._creds: dict[str, ScramCredential] = {}

    def put(self, username: str, cred: ScramCredential) -> None:
        self._creds[username] = cred

    def get(self, username: str) -> ScramCredential | None:
        return self._creds.get(username)

    def remove(self, username: str) -> bool:
        return self._creds.pop(username, None) is not None

    def contains(self, username: str) -> bool:
        return username in self._creds

    def users(self) -> list[str]:
        return sorted(self._creds)
