"""SCRAM-SHA-256 / SCRAM-SHA-512 (RFC 5802).

Parity with security/scram_algorithm.h:203: the same algorithm templated
over the hash, credential generation (salted-password PBKDF2 → client/server
keys), and the server-side 4-message conversation with strict message
parsing (scram_algorithm.h:53-201 parses via regex; we parse attr=value
pairs with the same validation rules). Used by the kafka SASL handlers and
by the admin API's user CRUD (credentials are created controller-side and
replicated — only salted verifier material is ever stored, never the
password).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
from dataclasses import dataclass


class ScramError(Exception):
    pass


@dataclass(frozen=True)
class ScramAlgorithm:
    name: str  # SASL mechanism name
    hash_name: str  # hashlib name
    min_iterations: int

    def hmac(self, key: bytes, msg: bytes) -> bytes:
        return hmac.new(key, msg, self.hash_name).digest()

    def h(self, data: bytes) -> bytes:
        return hashlib.new(self.hash_name, data).digest()

    def hi(self, password: bytes, salt: bytes, iterations: int) -> bytes:
        return hashlib.pbkdf2_hmac(self.hash_name, password, salt, iterations)


SCRAM_SHA256 = ScramAlgorithm("SCRAM-SHA-256", "sha256", 4096)
SCRAM_SHA512 = ScramAlgorithm("SCRAM-SHA-512", "sha512", 4096)
MECHANISMS: dict[str, ScramAlgorithm] = {
    SCRAM_SHA256.name: SCRAM_SHA256,
    SCRAM_SHA512.name: SCRAM_SHA512,
}


@dataclass
class ScramCredential:
    """What the broker stores per user (scram_credential: salt, server_key,
    stored_key, iterations — never the password)."""

    salt: bytes
    server_key: bytes
    stored_key: bytes
    iterations: int
    mechanism: str = SCRAM_SHA256.name

    def to_dict(self) -> dict:
        return {
            "salt": base64.b64encode(self.salt).decode(),
            "server_key": base64.b64encode(self.server_key).decode(),
            "stored_key": base64.b64encode(self.stored_key).decode(),
            "iterations": self.iterations,
            "mechanism": self.mechanism,
        }

    @staticmethod
    def from_dict(d: dict) -> "ScramCredential":
        return ScramCredential(
            base64.b64decode(d["salt"]),
            base64.b64decode(d["server_key"]),
            base64.b64decode(d["stored_key"]),
            int(d["iterations"]),
            d.get("mechanism", SCRAM_SHA256.name),
        )


def make_credential(
    password: str, algo: ScramAlgorithm = SCRAM_SHA256, iterations: int | None = None
) -> ScramCredential:
    iterations = iterations or algo.min_iterations
    if iterations < algo.min_iterations:
        raise ScramError(f"iterations < {algo.min_iterations}")
    salt = os.urandom(16)
    salted = algo.hi(password.encode(), salt, iterations)
    client_key = algo.hmac(salted, b"Client Key")
    server_key = algo.hmac(salted, b"Server Key")
    stored_key = algo.h(client_key)
    return ScramCredential(salt, server_key, stored_key, iterations, algo.name)


def verify_password(cred: ScramCredential, password: str) -> bool:
    """Check a plaintext password against a stored SCRAM verifier
    (re-derive the client key with the stored salt/iterations and compare
    H(client_key) to stored_key). Used by HTTP basic auth on the admin
    API, where no SCRAM conversation happens."""
    algo = SCRAM_SHA256 if cred.mechanism == SCRAM_SHA256.name else SCRAM_SHA512
    salted = algo.hi(password.encode(), cred.salt, cred.iterations)
    client_key = algo.hmac(salted, b"Client Key")
    return hmac.compare_digest(algo.h(client_key), cred.stored_key)


# Per-process seed for unknown-user dummy salts (stable within a broker's
# lifetime so the same username always sees the same salt).
_DUMMY_SALT_SEED = os.urandom(16)

# -------------------------------------------------------------- wire parsing
_ATTR_RE = re.compile(r"^[a-z]=")


def _parse_attrs(msg: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in msg.split(","):
        if not part:
            continue
        if not _ATTR_RE.match(part):
            raise ScramError(f"malformed scram attribute: {part!r}")
        out[part[0]] = part[2:]
    return out


def _saslname_decode(name: str) -> str:
    return name.replace("=2C", ",").replace("=3D", "=")


def _saslname_encode(name: str) -> str:
    return name.replace("=", "=3D").replace(",", "=2C")


class ScramServerConversation:
    """Server side of one SCRAM authentication (scram_authenticator).

    handle_client_first() -> server-first message
    handle_client_final() -> server-final message (raises on bad proof)
    """

    def __init__(self, lookup_credential, algo: ScramAlgorithm) -> None:
        """lookup_credential(username) -> ScramCredential | None"""
        self._lookup = lookup_credential
        self.algo = algo
        self.username: str | None = None
        self._cred: ScramCredential | None = None
        self._client_first_bare = ""
        self._server_first = ""
        self._nonce = ""
        self.complete = False

    def handle_client_first(self, msg: bytes) -> bytes:
        text = msg.decode("utf-8")
        # gs2 header: "n," [authzid] "," then client-first-bare
        if not (text.startswith("n,") or text.startswith("y,")):
            raise ScramError("channel binding not supported")
        gs2_end = text.index(",", 2)
        bare = text[gs2_end + 1 :]
        attrs = _parse_attrs(bare)
        if "n" not in attrs or "r" not in attrs:
            raise ScramError("missing user/nonce in client-first")
        self.username = _saslname_decode(attrs["n"])
        client_nonce = attrs["r"]
        self._client_first_bare = bare
        self._cred = self._lookup(self.username)
        if self._cred is None or self._cred.mechanism != self.algo.name:
            # Keep going with a dummy credential; fail at proof check so
            # usernames can't be probed (the reference fails late too). The
            # dummy salt is DERIVED from the username so repeated attempts
            # see a stable salt — a fresh random salt per attempt would
            # itself reveal that the account doesn't exist.
            salt = hmac.new(_DUMMY_SALT_SEED, self.username.encode(), "sha256").digest()[:16]
            digest_len = hashlib.new(self.algo.hash_name).digest_size
            self._cred = ScramCredential(
                salt, b"\x00" * digest_len, b"\x00" * digest_len,
                self.algo.min_iterations, self.algo.name,
            )
        self._nonce = client_nonce + base64.b64encode(os.urandom(18)).decode()
        self._server_first = (
            f"r={self._nonce},"
            f"s={base64.b64encode(self._cred.salt).decode()},"
            f"i={self._cred.iterations}"
        )
        return self._server_first.encode()

    def handle_client_final(self, msg: bytes) -> bytes:
        text = msg.decode("utf-8")
        attrs = _parse_attrs(text)
        if "c" not in attrs or "r" not in attrs or "p" not in attrs:
            raise ScramError("missing attributes in client-final")
        if attrs["r"] != self._nonce:
            raise ScramError("nonce mismatch")
        without_proof = text[: text.rindex(",p=")]
        auth_message = ",".join(
            [self._client_first_bare, self._server_first, without_proof]
        ).encode()
        proof = base64.b64decode(attrs["p"])
        client_signature = self.algo.hmac(self._cred.stored_key, auth_message)
        if len(proof) != len(client_signature):
            raise ScramError("bad proof length")
        client_key = bytes(a ^ b for a, b in zip(proof, client_signature))
        if not hmac.compare_digest(self.algo.h(client_key), self._cred.stored_key):
            raise ScramError("authentication failed")
        self.complete = True
        server_signature = self.algo.hmac(self._cred.server_key, auth_message)
        return b"v=" + base64.b64encode(server_signature)


# -------------------------------------------------------------- client side
def scram_client_first(username: str, nonce: str) -> bytes:
    return f"n,,n={_saslname_encode(username)},r={nonce}".encode()


def scram_client_final(
    username: str,
    password: str,
    nonce: str,
    client_first: bytes,
    server_first: bytes,
    algo: ScramAlgorithm = SCRAM_SHA256,
) -> tuple[bytes, bytes]:
    """Returns (client-final message, expected server signature)."""
    attrs = _parse_attrs(server_first.decode())
    full_nonce, salt, iterations = attrs["r"], base64.b64decode(attrs["s"]), int(attrs["i"])
    if not full_nonce.startswith(nonce):
        raise ScramError("server nonce does not extend client nonce")
    salted = algo.hi(password.encode(), salt, iterations)
    client_key = algo.hmac(salted, b"Client Key")
    stored_key = algo.h(client_key)
    bare = client_first.decode()[2:]
    gs2_end = bare.index(",")
    bare = bare[gs2_end + 1 :]
    channel = base64.b64encode(b"n,,").decode()
    without_proof = f"c={channel},r={full_nonce}"
    auth_message = ",".join([bare, server_first.decode(), without_proof]).encode()
    client_signature = algo.hmac(stored_key, auth_message)
    proof = bytes(a ^ b for a, b in zip(client_key, client_signature))
    final = f"{without_proof},p={base64.b64encode(proof).decode()}".encode()
    server_key = algo.hmac(salted, b"Server Key")
    expected_sig = algo.hmac(server_key, auth_message)
    return final, expected_sig
