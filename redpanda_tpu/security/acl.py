"""Kafka-model ACLs + authorizer.

Parity with security/acl.h (resource patterns, operations, permission
types), acl_store, and authorizer.h:39 — the authorizer is consulted by
every kafka handler through the request context. Semantics follow Kafka:
DENY wins over ALLOW, absence of any matching ALLOW denies, superusers
bypass, and READ/WRITE/DELETE/ALTER imply DESCRIBE (ALTER_CONFIGS implies
DESCRIBE_CONFIGS).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class ResourceType(enum.IntEnum):
    # kafka wire values (AclBinding resourceType)
    any = 1
    topic = 2
    group = 3
    cluster = 4
    transactional_id = 5


class PatternType(enum.IntEnum):
    any = 1
    match = 2
    literal = 3
    prefixed = 4


class AclOperation(enum.IntEnum):
    any = 1
    all = 2
    read = 3
    write = 4
    create = 5
    delete = 6
    alter = 7
    describe = 8
    cluster_action = 9
    describe_configs = 10
    alter_configs = 11
    idempotent_write = 12


class AclPermission(enum.IntEnum):
    any = 1
    deny = 2
    allow = 3


WILDCARD = "*"
DEFAULT_CLUSTER_NAME = "kafka-cluster"


@dataclass(frozen=True)
class ResourcePattern:
    resource_type: ResourceType
    name: str
    pattern_type: PatternType = PatternType.literal

    def matches(self, resource_type: ResourceType, name: str) -> bool:
        if self.resource_type != resource_type:
            return False
        if self.pattern_type == PatternType.literal:
            return self.name == name or self.name == WILDCARD
        if self.pattern_type == PatternType.prefixed:
            return name.startswith(self.name)
        return False


@dataclass(frozen=True)
class AclEntry:
    principal: str  # "User:<name>" or "User:*"
    host: str  # "*" or exact
    operation: AclOperation
    permission: AclPermission

    def matches(self, principal: str, host: str, operation: AclOperation) -> bool:
        if self.principal not in (principal, "User:*", WILDCARD):
            return False
        if self.host not in (host, WILDCARD):
            return False
        if self.operation == AclOperation.all:
            return True
        if self.operation == operation:
            return True
        # implied describes
        if operation == AclOperation.describe and self.operation in (
            AclOperation.read, AclOperation.write, AclOperation.delete, AclOperation.alter,
        ):
            return True
        if operation == AclOperation.describe_configs and self.operation == AclOperation.alter_configs:
            return True
        return False


@dataclass(frozen=True)
class AclBinding:
    pattern: ResourcePattern
    entry: AclEntry

    def to_dict(self) -> dict:
        return {
            "rt": int(self.pattern.resource_type),
            "rn": self.pattern.name,
            "pt": int(self.pattern.pattern_type),
            "principal": self.entry.principal,
            "host": self.entry.host,
            "op": int(self.entry.operation),
            "perm": int(self.entry.permission),
        }

    @staticmethod
    def from_dict(d: dict) -> "AclBinding":
        return AclBinding(
            ResourcePattern(ResourceType(d["rt"]), d["rn"], PatternType(d["pt"])),
            AclEntry(d["principal"], d["host"], AclOperation(d["op"]), AclPermission(d["perm"])),
        )


@dataclass(frozen=True)
class AclBindingFilter:
    """Filter for describe/delete (acl.h acl_binding_filter): `any` wildcards."""

    resource_type: ResourceType = ResourceType.any
    name: str | None = None
    pattern_type: PatternType = PatternType.any
    principal: str | None = None
    host: str | None = None
    operation: AclOperation = AclOperation.any
    permission: AclPermission = AclPermission.any

    def matches(self, b: AclBinding) -> bool:
        if self.resource_type != ResourceType.any and b.pattern.resource_type != self.resource_type:
            return False
        if self.name is not None and b.pattern.name != self.name:
            return False
        if self.pattern_type not in (PatternType.any, PatternType.match) and b.pattern.pattern_type != self.pattern_type:
            return False
        if self.principal is not None and b.entry.principal != self.principal:
            return False
        if self.host is not None and b.entry.host != self.host:
            return False
        if self.operation != AclOperation.any and b.entry.operation != self.operation:
            return False
        if self.permission != AclPermission.any and b.entry.permission != self.permission:
            return False
        return True


class AclStore:
    def __init__(self) -> None:
        self._bindings: set[AclBinding] = set()

    def add(self, bindings: list[AclBinding]) -> None:
        self._bindings.update(bindings)

    def remove(self, filters: list[AclBindingFilter]) -> list[AclBinding]:
        removed = [b for b in self._bindings if any(f.matches(b) for f in filters)]
        self._bindings.difference_update(removed)
        return removed

    def describe(self, flt: AclBindingFilter) -> list[AclBinding]:
        return [b for b in self._bindings if flt.matches(b)]

    def all_bindings(self) -> list[AclBinding]:
        return list(self._bindings)


class Authorizer:
    """authorizer.h:39: deny > allow > implicit-deny, superuser bypass.

    An empty ACL store authorizes everything (the reference boots open until
    ACLs exist and kafka_enable_authorization is effectively off; tests and
    single-user dev clusters rely on this)."""

    def __init__(self, store: AclStore, superusers: set[str] | None = None, *, allow_empty: bool = True) -> None:
        self.store = store
        self.superusers = {f"User:{u}" if not u.startswith("User:") else u for u in (superusers or set())}
        self.allow_empty = allow_empty

    def authorized(
        self,
        resource_type: ResourceType,
        resource_name: str,
        operation: AclOperation,
        principal: str | None,
        host: str = WILDCARD,
    ) -> bool:
        principal = principal or "User:anonymous"
        if not principal.startswith("User:"):
            principal = f"User:{principal}"
        if principal in self.superusers:
            return True
        bindings = [
            b for b in self.store.all_bindings()
            if b.pattern.matches(resource_type, resource_name)
        ]
        if not bindings:
            return self.allow_empty and not self.store.all_bindings()
        matching = [b for b in bindings if b.entry.matches(principal, host, operation)]
        if any(b.entry.permission == AclPermission.deny for b in matching):
            return False
        return any(b.entry.permission == AclPermission.allow for b in matching)
