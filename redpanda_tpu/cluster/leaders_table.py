"""Partition leaders table.

Parity with cluster/partition_leaders_table.h: the per-node cache of who
leads each partition, fed locally by raft leadership notifications and
remotely by metadata dissemination gossip. Waiters let the kafka layer block
until a leader is known (e.g. right after topic creation).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from redpanda_tpu.models.fundamental import NTP, NodeId, Term


@dataclass
class LeaderInfo:
    leader: NodeId | None
    term: Term


class PartitionLeadersTable:
    def __init__(self) -> None:
        self._leaders: dict[NTP, LeaderInfo] = {}
        self._waiters: dict[NTP, list[asyncio.Future]] = {}

    def update(self, ntp: NTP, leader: NodeId | None, term: Term) -> None:
        cur = self._leaders.get(ntp)
        if cur is not None and term < cur.term:
            return  # stale gossip
        if (
            cur is not None
            and term == cur.term
            and leader is None
            and cur.leader is not None
        ):
            # A deposed leader gossips (None, term N) while the term-N
            # winner gossips (winner, term N): raft guarantees ONE leader
            # per term, so known always beats unknown within a term —
            # otherwise arrival order could blank the winner's entry
            # (observed: every node missing exactly the partitions it
            # leads itself).
            return
        self._leaders[ntp] = LeaderInfo(leader, term)
        if leader is not None:
            for fut in self._waiters.pop(ntp, []):
                if not fut.done():
                    fut.set_result(leader)

    def remove(self, ntp: NTP) -> None:
        self._leaders.pop(ntp, None)
        for fut in self._waiters.pop(ntp, []):
            if not fut.done():
                fut.cancel()

    def get_leader(self, ntp: NTP) -> NodeId | None:
        info = self._leaders.get(ntp)
        return info.leader if info else None

    def get_term(self, ntp: NTP) -> Term:
        info = self._leaders.get(ntp)
        return info.term if info else -1

    async def wait_for_leader(self, ntp: NTP, timeout: float = 5.0) -> NodeId:
        leader = self.get_leader(ntp)
        if leader is not None:
            return leader
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(ntp, []).append(fut)
        return await asyncio.wait_for(fut, timeout)

    def snapshot(self) -> dict[NTP, LeaderInfo]:
        return dict(self._leaders)
