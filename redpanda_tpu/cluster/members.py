"""Cluster membership: broker table + state transitions.

Parity with cluster/members_table + members_manager + members_backend:
brokers join by RPC to the controller leader, which replicates a
register_node command (the reference folds this into raft0 configuration +
members_manager; commands.h:164-173 covers decommission/recommission).
Decommission drains every replica off the node (members_backend reallocates
partitions), then the node can be removed.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class MembershipState(enum.IntEnum):
    active = 0
    draining = 1  # decommissioning: replicas being moved away
    removed = 2


@dataclass
class Broker:
    node_id: int
    host: str
    port: int  # internal rpc
    kafka_host: str = "127.0.0.1"
    kafka_port: int = 9092
    admin_port: int = 0  # 0 = not advertised (pre-pandascope log entries)
    state: MembershipState = MembershipState.active


class MembersTable:
    """node_id → Broker, plus change callbacks (members_table.h)."""

    def __init__(self) -> None:
        self._brokers: dict[int, Broker] = {}
        self._callbacks: list = []

    def register_change_callback(self, cb) -> None:
        """cb(broker) on every membership update."""
        self._callbacks.append(cb)

    def _notify(self, b: Broker) -> None:
        for cb in self._callbacks:
            cb(b)

    def apply_register(self, b: Broker) -> None:
        existing = self._brokers.get(b.node_id)
        if existing is not None and existing.state != MembershipState.removed:
            # re-join of a live node: update address only
            existing.host, existing.port = b.host, b.port
            existing.kafka_host, existing.kafka_port = b.kafka_host, b.kafka_port
            existing.admin_port = b.admin_port
            self._notify(existing)
            return
        self._brokers[b.node_id] = b
        self._notify(b)

    def apply_state(self, node_id: int, state: MembershipState) -> None:
        b = self._brokers.get(node_id)
        if b is not None:
            b.state = state
            self._notify(b)

    def get(self, node_id: int) -> Broker | None:
        return self._brokers.get(node_id)

    def contains(self, node_id: int) -> bool:
        b = self._brokers.get(node_id)
        return b is not None and b.state != MembershipState.removed

    def all_brokers(self) -> list[Broker]:
        return [b for b in self._brokers.values() if b.state != MembershipState.removed]

    def node_ids(self) -> list[int]:
        return [b.node_id for b in self.all_brokers()]
