"""Leadership gossip between brokers.

Parity with cluster/metadata_dissemination_service + handler
(metadata_dissemination_rpc.json): raft elections are per-group and only the
replicas learn the outcome directly, so the new leader's node broadcasts
{ntp, term, leader} updates to every other broker, and a joining broker
pulls a full snapshot. Keeps each node's partition_leaders_table converged
without routing every metadata query to the controller.
"""

from __future__ import annotations

import asyncio
import json
import logging

from redpanda_tpu import rpc
from redpanda_tpu.cluster.leaders_table import PartitionLeadersTable
from redpanda_tpu.cluster.members import MembersTable
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.rpc import serde

logger = logging.getLogger("rptpu.cluster.md_dissemination")

UPDATE_LEADERSHIP_REQUEST = serde.S(("updates_json", serde.BYTES))
UPDATE_LEADERSHIP_REPLY = serde.S(("ok", serde.BOOL))
GET_LEADERSHIP_REQUEST = serde.S(("dummy", serde.I8))
GET_LEADERSHIP_REPLY = serde.S(("updates_json", serde.BYTES))

md_dissemination_service = rpc.ServiceDef(
    "cluster",
    "metadata_dissemination",
    [
        rpc.MethodDef("update_leadership", UPDATE_LEADERSHIP_REQUEST, UPDATE_LEADERSHIP_REPLY),
        rpc.MethodDef("get_leadership", GET_LEADERSHIP_REQUEST, GET_LEADERSHIP_REPLY),
    ],
)


def _encode_updates(updates: list[tuple[NTP, int | None, int]]) -> bytes:
    return json.dumps(
        [
            {"ns": n.ns, "t": n.topic, "p": n.partition, "leader": l, "term": t}
            for n, l, t in updates
        ]
    ).encode()


def _decode_updates(blob: bytes) -> list[tuple[NTP, int | None, int]]:
    return [
        (NTP(u["ns"], u["t"], u["p"]), u["leader"], u["term"])
        for u in json.loads(blob.decode())
    ]


class MetadataDisseminationService:
    """Both halves: the RPC handler (apply peer updates) and the
    broadcaster fiber (push local leadership changes to all peers)."""

    def __init__(
        self,
        self_node_id: int,
        leaders: PartitionLeadersTable,
        members: MembersTable,
        connection_cache: rpc.ConnectionCache,
        interval_s: float = 0.2,
    ) -> None:
        self.self_node_id = self_node_id
        self.leaders = leaders
        self.members = members
        self.connections = connection_cache
        self.interval_s = interval_s
        self._pending: list[tuple[NTP, int | None, int]] = []
        # node_id -> updates that peer has not acked yet (retried alone)
        self._deferred: dict[int, list] = {}
        self._task: asyncio.Task | None = None
        self._wake = asyncio.Event()

    # ------------------------------------------------------------ rpc handlers
    async def update_leadership(self, req: dict) -> dict:
        for ntp, leader, term in _decode_updates(req["updates_json"]):
            self.leaders.update(ntp, leader, term)
        return {"ok": True}

    async def get_leadership(self, req: dict) -> dict:
        snap = [
            (ntp, info.leader, info.term)
            for ntp, info in self.leaders.snapshot().items()
        ]
        return {"updates_json": _encode_updates(snap)}

    # ------------------------------------------------------------ broadcast side
    def notify_leadership(self, ntp: NTP, leader: int | None, term: int) -> None:
        """Hook for raft leadership notifications on this node: queue a
        gossip round (batched, like the reference's dissemination queue)."""
        self.leaders.update(ntp, leader, term)
        self._pending.append((ntp, leader, term))
        self._wake.set()

    async def start(self) -> "MetadataDisseminationService":
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            if self._deferred:
                # a peer still owes us an ack: retry on a timer even with
                # no fresh elections to coalesce
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=2.0)
                except asyncio.TimeoutError:
                    pass
            else:
                await self._wake.wait()
            await asyncio.sleep(self.interval_s)  # coalesce a burst of elections
            self._wake.clear()
            updates, self._pending = self._pending, []
            # per-peer payload: fresh updates for everyone + whatever that
            # peer failed to ack before (a dropped gossip round would leave
            # it PERMANENTLY stale — elections are events, not a stream).
            # The term guard in the leaders table makes duplicates no-ops.
            peers = [
                b.node_id
                for b in self.members.all_brokers()
                if b.node_id != self.self_node_id
            ]
            batches: dict[int, list] = {}
            for node_id in peers:
                batch = self._deferred.pop(node_id, []) + updates
                if batch:
                    batches[node_id] = batch
            if not batches:
                continue
            # gather (not fire-and-forget: unreferenced tasks can be GC'd):
            # sends run concurrently and each has its own short rpc timeout
            results = await asyncio.gather(
                *(
                    self._send(node_id, _encode_updates(batch))
                    for node_id, batch in batches.items()
                )
            )
            for (node_id, batch), ok in zip(batches.items(), results):
                if not ok:
                    self._deferred[node_id] = batch  # ONLY this peer retries

    async def _send(self, node_id: int, blob: bytes) -> bool:
        try:
            client = rpc.Client(md_dissemination_service, self.connections.get(node_id))
            await client.update_leadership({"updates_json": blob}, timeout=2.0)
            return True
        except Exception:
            logger.debug("leadership gossip to node %d failed", node_id, exc_info=True)
            return False

    async def pull_initial(self, from_node: int) -> None:
        """Joining broker: seed the leaders table from a peer."""
        client = rpc.Client(md_dissemination_service, self.connections.get(from_node))
        reply = await client.get_leadership({"dummy": 0}, timeout=5.0)
        for ntp, leader, term in _decode_updates(reply["updates_json"]):
            self.leaders.update(ntp, leader, term)
