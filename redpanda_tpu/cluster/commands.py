"""Controller command set.

Parity with cluster/commands.h:31-177: every cluster mutation is a typed
command serialized into a record batch and replicated through raft group 0;
each node's controller STM applies the command batch-type-by-batch-type
(mux_state_machine). The command carries everything needed for a
deterministic apply on every node — including allocated raft group ids —
so replicas never need to ask the leader anything while applying.

Encoding: record key = serde {type i8, version i8}, record value = JSON
payload (the reference uses adl-reflection on C++ structs; a schemaless
value keeps this layer flexible while the key stays binary-stable).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any

from redpanda_tpu.models.fundamental import NTP, NodeId
from redpanda_tpu.models.record import RecordBatch, RecordBatchType, Record
from redpanda_tpu.rpc import serde


class CommandType(enum.IntEnum):
    """cluster/commands.h command ids (one enum across all batch types)."""

    # topic_management_cmd batches
    create_topic = 0
    delete_topic = 1
    update_topic_properties = 2
    move_partition_replicas = 3
    finish_moving_partition_replicas = 4
    create_partition = 5
    create_non_replicable_topic = 6
    # user_management_cmd batches
    create_user = 10
    delete_user = 11
    update_user = 12
    # acl_management_cmd batches
    create_acls = 13
    delete_acls = 14
    # data_policy_management_cmd batches
    create_data_policy = 15
    delete_data_policy = 16
    # node_management_cmd batches
    register_node = 17
    decommission_node = 18
    recommission_node = 19
    finish_reallocations = 20


# Which record-batch type each command travels in (mux STM routing key).
BATCH_TYPE_FOR = {
    CommandType.create_topic: RecordBatchType.topic_management_cmd,
    CommandType.delete_topic: RecordBatchType.topic_management_cmd,
    CommandType.update_topic_properties: RecordBatchType.topic_management_cmd,
    CommandType.move_partition_replicas: RecordBatchType.topic_management_cmd,
    CommandType.finish_moving_partition_replicas: RecordBatchType.topic_management_cmd,
    CommandType.create_partition: RecordBatchType.topic_management_cmd,
    CommandType.create_non_replicable_topic: RecordBatchType.topic_management_cmd,
    CommandType.create_user: RecordBatchType.user_management_cmd,
    CommandType.delete_user: RecordBatchType.user_management_cmd,
    CommandType.update_user: RecordBatchType.user_management_cmd,
    CommandType.create_acls: RecordBatchType.acl_management_cmd,
    CommandType.delete_acls: RecordBatchType.acl_management_cmd,
    CommandType.create_data_policy: RecordBatchType.data_policy_management_cmd,
    CommandType.delete_data_policy: RecordBatchType.data_policy_management_cmd,
    CommandType.register_node: RecordBatchType.node_management_cmd,
    CommandType.decommission_node: RecordBatchType.node_management_cmd,
    CommandType.recommission_node: RecordBatchType.node_management_cmd,
    CommandType.finish_reallocations: RecordBatchType.node_management_cmd,
}

_KEY = serde.S(("type", serde.I8), ("version", serde.I8))


@dataclass
class Command:
    type: CommandType
    data: dict[str, Any] = field(default_factory=dict)

    def to_batch(self) -> RecordBatch:
        key = _KEY.encode({"type": int(self.type), "version": 0})
        value = json.dumps(self.data, separators=(",", ":")).encode()
        return RecordBatch.build(
            [Record(key=key, value=value)], type=BATCH_TYPE_FOR[self.type]
        )

    @staticmethod
    def from_record(rec: Record) -> "Command":
        k = _KEY.decode(rec.key)
        data = json.loads(rec.value.decode()) if rec.value else {}
        return Command(CommandType(k["type"]), data)


# ---------------------------------------------------------------- payloads
# Helper constructors so frontends build well-formed payloads.

def assignment_payload(ntp: NTP, group: int, replicas: list[NodeId]) -> dict:
    return {
        "ns": ntp.ns,
        "topic": ntp.topic,
        "partition": ntp.partition,
        "group": group,
        "replicas": list(replicas),
    }


def create_topic_cmd(config_map: dict, assignments: list[dict]) -> Command:
    return Command(
        CommandType.create_topic,
        {"config": config_map, "assignments": assignments},
    )


def delete_topic_cmd(ns: str, topic: str) -> Command:
    return Command(CommandType.delete_topic, {"ns": ns, "topic": topic})


def create_partition_cmd(ns: str, topic: str, assignments: list[dict]) -> Command:
    return Command(
        CommandType.create_partition,
        {"ns": ns, "topic": topic, "assignments": assignments},
    )


def update_topic_properties_cmd(ns: str, topic: str, overrides: dict) -> Command:
    return Command(
        CommandType.update_topic_properties,
        {"ns": ns, "topic": topic, "overrides": overrides},
    )


def move_partition_replicas_cmd(ntp: NTP, replicas: list[NodeId]) -> Command:
    return Command(
        CommandType.move_partition_replicas,
        {"ns": ntp.ns, "topic": ntp.topic, "partition": ntp.partition,
         "replicas": list(replicas)},
    )


def finish_moving_cmd(ntp: NTP, replicas: list[NodeId]) -> Command:
    return Command(
        CommandType.finish_moving_partition_replicas,
        {"ns": ntp.ns, "topic": ntp.topic, "partition": ntp.partition,
         "replicas": list(replicas)},
    )


def create_non_replicable_topic_cmd(
    source_ns: str, source_topic: str, name: str
) -> Command:
    """Coproc materialized topic (commands.h create_non_replicable_topic)."""
    return Command(
        CommandType.create_non_replicable_topic,
        {"source_ns": source_ns, "source_topic": source_topic, "name": name},
    )


def register_node_cmd(
    node_id: NodeId, host: str, port: int, kafka_host: str, kafka_port: int,
    admin_port: int = 0,
) -> Command:
    """``admin_port`` (0 = not advertised) lets peers dial this node's
    admin API for the cluster observability plane — trace fan-out and
    /metrics federation; old replicated log entries simply lack the key
    and decode to 0 (admin-unreachable, a partial-merge degradation)."""
    return Command(
        CommandType.register_node,
        {"node_id": node_id, "host": host, "port": port,
         "kafka_host": kafka_host, "kafka_port": kafka_port,
         "admin_port": admin_port},
    )


def create_data_policy_cmd(topic: str, name: str, spec_json: str) -> Command:
    """Per-topic fetch-path transform policy (commands.h:152-162
    create_data_policy_cmd; the v8 function name + script become a
    TransformSpec here)."""
    return Command(
        CommandType.create_data_policy,
        {"topic": topic, "name": name, "spec": spec_json},
    )


def delete_data_policy_cmd(topic: str) -> Command:
    return Command(CommandType.delete_data_policy, {"topic": topic})


def decommission_node_cmd(node_id: NodeId) -> Command:
    return Command(CommandType.decommission_node, {"node_id": node_id})


def recommission_node_cmd(node_id: NodeId) -> Command:
    return Command(CommandType.recommission_node, {"node_id": node_id})
