"""Internal cluster RPC service + leader-forwarding frontend.

Parity with cluster/service.cc + controller.json: brokers that are not the
controller leader forward mutations (topic ops, node join, decommission) to
the leader over the internal RPC mesh. The wire carries the already-built
``Command`` (type + JSON payload), so the leader-side handler is one line:
replicate_and_wait. join_node is the cluster entry point for new brokers
(members_manager handle_join_request).
"""

from __future__ import annotations

import json
import logging

from redpanda_tpu import rpc
from redpanda_tpu.cluster.commands import Command, CommandType
from redpanda_tpu.cluster.controller import (
    ClusterError,
    Controller,
    NotControllerError,
    TopicExistsError,
)
from redpanda_tpu.cluster.members import Broker
from redpanda_tpu.rpc import serde

logger = logging.getLogger("rptpu.cluster.service")

REPLICATE_CMD_REQUEST = serde.S(("type", serde.I32), ("data_json", serde.BYTES))
REPLICATE_CMD_REPLY = serde.S(
    ("errc", serde.I32),  # 0 ok, 1 not-leader, 2 error
    ("leader", serde.I32),  # -1 unknown
    ("message", serde.STRING),
)
JOIN_NODE_REQUEST = serde.S(
    ("node_id", serde.I32),
    ("host", serde.STRING),
    ("port", serde.I32),
    ("kafka_host", serde.STRING),
    ("kafka_port", serde.I32),
    # pandascope: peers dial this for trace fan-out + /metrics federation
    ("admin_port", serde.I32),
)
JOIN_NODE_REPLY = REPLICATE_CMD_REPLY
# Topic ops need LEADER-side logic (partition allocation, group ids), so
# they cannot ride replicate_command's pre-built payloads; op: 0 create,
# 1 delete, 2 add_partitions (controller.json create/delete_topic analogue).
TOPIC_OP_REQUEST = serde.S(("op", serde.I32), ("data_json", serde.BYTES))
TOPIC_OP_REPLY = REPLICATE_CMD_REPLY

cluster_service = rpc.ServiceDef(
    "cluster",
    "controller",
    [
        rpc.MethodDef("replicate_command", REPLICATE_CMD_REQUEST, REPLICATE_CMD_REPLY),
        rpc.MethodDef("join_node", JOIN_NODE_REQUEST, JOIN_NODE_REPLY),
        rpc.MethodDef("topic_op", TOPIC_OP_REQUEST, TOPIC_OP_REPLY),
    ],
)

_OK, _NOT_LEADER, _ERROR, _EXISTS = 0, 1, 2, 3


OP_CREATE_TOPIC, OP_DELETE_TOPIC, OP_ADD_PARTITIONS = 0, 1, 2
OP_DECOMMISSION, OP_RECOMMISSION = 3, 4
OP_CREATE_NON_REPLICABLE = 5  # coproc materialized topics


async def apply_topic_op(controller: Controller, op: int, data: dict) -> None:
    """Leader-side controller frontend op (topics + membership); the ONE
    implementation used by both the RPC handler and the dispatcher's
    local-leader path. Membership ops ride the same channel because they
    too need LEADER-side logic (decommission kicks the replica drain and
    the finish_reallocations watcher, controller.decommission_node — the
    raw replicated command alone only flips membership state)."""
    if op == OP_CREATE_TOPIC:
        from redpanda_tpu.cluster.topic_table import TopicConfig

        cfg = TopicConfig(
            data["name"],
            data["partitions"],
            data["replication"],
            ns=data.get("ns", "kafka"),
        )
        for k, v in (data.get("overrides") or {}).items():
            cfg.apply_override(k, v)
        await controller.create_topic(cfg)
    elif op == OP_DELETE_TOPIC:
        await controller.delete_topic(data["name"], data.get("ns", "kafka"))
    elif op == OP_ADD_PARTITIONS:
        await controller.create_partitions(data["name"], data["total"])
    elif op == OP_DECOMMISSION:
        await controller.decommission_node(data["node_id"])
    elif op == OP_RECOMMISSION:
        await controller.recommission_node(data["node_id"])
    elif op == OP_CREATE_NON_REPLICABLE:
        await controller.create_non_replicable_topic(
            data["source"], data["name"], data.get("ns", "kafka")
        )
    else:
        raise ClusterError(f"unknown frontend op {op}")


class ClusterService:
    """Server-side handler bound on every broker.

    With a dispatcher attached, join_node works against ANY broker (the
    handler forwards to the controller leader itself — members_manager
    handle_join_request semantics); without one it serves leader-local only.
    """

    def __init__(self, controller: Controller, dispatcher: "ControllerDispatcher | None" = None) -> None:
        self.controller = controller
        self.dispatcher = dispatcher

    def register(self, protocol: rpc.SimpleProtocol) -> None:
        protocol.register_service(rpc.ServiceHandler(cluster_service, self))

    def _reply(self, errc: int, message: str = "") -> dict:
        leader = self.controller.leader_id
        return {"errc": errc, "leader": -1 if leader is None else leader, "message": message}

    async def replicate_command(self, req: dict) -> dict:
        cmd = Command(CommandType(req["type"]), json.loads(req["data_json"].decode()))
        try:
            await self.controller.replicate_and_wait(cmd)
            return self._reply(_OK)
        except NotControllerError:
            return self._reply(_NOT_LEADER)
        except Exception as e:
            logger.exception("replicate_command failed")
            return self._reply(_ERROR, str(e))

    async def topic_op(self, req: dict) -> dict:
        """Leader-side topic mutation (create/delete/add_partitions)."""
        data = json.loads(req["data_json"].decode())
        try:
            await apply_topic_op(self.controller, req["op"], data)
            return self._reply(_OK)
        except NotControllerError:
            return self._reply(_NOT_LEADER)
        except TopicExistsError as e:
            return self._reply(_EXISTS, str(e))
        except ClusterError as e:
            return self._reply(_ERROR, str(e))
        except Exception as e:
            logger.exception("topic_op failed")
            return self._reply(_ERROR, str(e))

    async def join_node(self, req: dict) -> dict:
        from redpanda_tpu.cluster import commands as cmds

        cmd = cmds.register_node_cmd(
            req["node_id"], req["host"], req["port"],
            req["kafka_host"], req["kafka_port"],
            admin_port=req.get("admin_port", 0),
        )
        try:
            if self.dispatcher is not None:
                await self.dispatcher.replicate(cmd)
            else:
                await self.controller.replicate_and_wait(cmd)
            return self._reply(_OK)
        except NotControllerError:
            return self._reply(_NOT_LEADER)
        except Exception as e:
            logger.exception("join_node failed")
            return self._reply(_ERROR, str(e))


class ControllerDispatcher:
    """Run a controller mutation from ANY broker: try locally, forward to
    the leader otherwise (topics_frontend redirect semantics)."""

    def __init__(self, controller: Controller, connection_cache: rpc.ConnectionCache) -> None:
        self.controller = controller
        self.connections = connection_cache

    async def replicate(self, cmd: Command, *, retries: int = 3, timeout: float = 10.0) -> None:
        last = "no controller leader"
        for _ in range(retries):
            if self.controller.is_leader():
                try:
                    await self.controller.replicate_and_wait(cmd, timeout)
                    return
                except NotControllerError:
                    pass  # lost leadership mid-call; fall through to forward
            leader = self.controller.leader_id
            if leader is None or leader == self.controller.self_node.id:
                import asyncio

                await asyncio.sleep(0.2)
                continue
            # serialization is deterministic: do it OUTSIDE the retry guard
            # so a bad command surfaces immediately with its real traceback
            payload = {"type": int(cmd.type), "data_json": json.dumps(cmd.data).encode()}
            try:
                client = rpc.Client(cluster_service, self.connections.get(leader))
                reply = await client.replicate_command(payload, timeout=timeout)
            except Exception as e:
                # Leader died mid-RPC: re-resolve after the election — the
                # path startup registration rides through a SIGKILL/restart
                # (retries=300 must actually outwait it). A reply lost
                # after commit means the retry re-appends the command;
                # controller commands are apply-idempotent (registrations
                # and topic ops re-apply as no-ops/exists).
                last = f"{type(e).__name__}: {e}"
                logger.debug("controller forward to %s failed", leader, exc_info=True)
                import asyncio

                await asyncio.sleep(0.2)
                continue
            if reply["errc"] == _OK:
                return
            last = reply["message"] or f"errc={reply['errc']}"
        raise ClusterError(f"controller mutation failed: {last}", retriable=True)

    async def topic_op(
        self, op: int, data: dict, *, retries: int = 25, timeout: float = 10.0
    ) -> None:
        """Create/delete/add_partitions on the controller leader, from any
        broker. Leader-side because allocation + group-id assignment live
        there. Only LEADERLESS states retry (elections in flight — a real
        cluster spends seconds leaderless after a kill); permanent errors
        (exists, allocation impossible) surface immediately and identically
        from both the local-leader and the forwarded path.

        Raises ValueError for already-exists (the single-node
        topic_table.add_topic contract every idempotent caller handles).
        """
        import asyncio

        last = "no controller leader"
        for _ in range(retries):
            if self.controller.is_leader():
                try:
                    await apply_topic_op(self.controller, op, data)
                    return
                except NotControllerError:
                    pass  # lost leadership mid-call; fall through to forward
                except TopicExistsError as e:
                    raise ValueError(str(e)) from e
            leader = self.controller.leader_id
            if leader is None or leader == self.controller.self_node.id:
                await asyncio.sleep(0.2)
                continue
            try:
                client = rpc.Client(cluster_service, self.connections.get(leader))
                reply = await client.topic_op(
                    {"op": op, "data_json": json.dumps(data).encode()},
                    timeout=timeout,
                )
            except Exception as e:  # leader just died: retry after re-election
                last = str(e)
                await asyncio.sleep(0.2)
                continue
            if reply["errc"] == _OK:
                return
            last = reply["message"] or f"errc={reply['errc']}"
            if reply["errc"] == _EXISTS:
                raise ValueError(last)
            if reply["errc"] == _ERROR:
                raise ClusterError(last)  # permanent: no retry
            await asyncio.sleep(0.2)  # _NOT_LEADER: election in flight
        raise ClusterError(f"topic op failed: {last}", retriable=True)


async def join_cluster(
    broker: Broker,
    seed_addr: tuple[str, int],
    connections: rpc.ConnectionCache,
    *,
    seed_node_hint: int = 0,
    timeout: float = 10.0,
) -> None:
    """Client side of node join: a fresh broker announces itself to a seed
    broker, which forwards to the controller leader if needed."""
    import asyncio

    connections.register(seed_node_hint, *seed_addr)
    client = rpc.Client(cluster_service, connections.get(seed_node_hint))
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        reply = await client.join_node(
            {
                "node_id": broker.node_id,
                "host": broker.host,
                "port": broker.port,
                "kafka_host": broker.kafka_host,
                "kafka_port": broker.kafka_port,
                "admin_port": broker.admin_port,
            },
            timeout=5.0,
        )
        if reply["errc"] == _OK:
            return
        if asyncio.get_running_loop().time() > deadline:
            raise ClusterError(f"join failed: {reply['message']}")
        await asyncio.sleep(0.3)
