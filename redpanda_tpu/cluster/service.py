"""Internal cluster RPC service + leader-forwarding frontend.

Parity with cluster/service.cc + controller.json: brokers that are not the
controller leader forward mutations (topic ops, node join, decommission) to
the leader over the internal RPC mesh. The wire carries the already-built
``Command`` (type + JSON payload), so the leader-side handler is one line:
replicate_and_wait. join_node is the cluster entry point for new brokers
(members_manager handle_join_request).
"""

from __future__ import annotations

import json
import logging

from redpanda_tpu import rpc
from redpanda_tpu.cluster.commands import Command, CommandType
from redpanda_tpu.cluster.controller import ClusterError, Controller, NotControllerError
from redpanda_tpu.cluster.members import Broker
from redpanda_tpu.rpc import serde

logger = logging.getLogger("rptpu.cluster.service")

REPLICATE_CMD_REQUEST = serde.S(("type", serde.I32), ("data_json", serde.BYTES))
REPLICATE_CMD_REPLY = serde.S(
    ("errc", serde.I32),  # 0 ok, 1 not-leader, 2 error
    ("leader", serde.I32),  # -1 unknown
    ("message", serde.STRING),
)
JOIN_NODE_REQUEST = serde.S(
    ("node_id", serde.I32),
    ("host", serde.STRING),
    ("port", serde.I32),
    ("kafka_host", serde.STRING),
    ("kafka_port", serde.I32),
)
JOIN_NODE_REPLY = REPLICATE_CMD_REPLY

cluster_service = rpc.ServiceDef(
    "cluster",
    "controller",
    [
        rpc.MethodDef("replicate_command", REPLICATE_CMD_REQUEST, REPLICATE_CMD_REPLY),
        rpc.MethodDef("join_node", JOIN_NODE_REQUEST, JOIN_NODE_REPLY),
    ],
)

_OK, _NOT_LEADER, _ERROR = 0, 1, 2


class ClusterService:
    """Server-side handler bound on every broker.

    With a dispatcher attached, join_node works against ANY broker (the
    handler forwards to the controller leader itself — members_manager
    handle_join_request semantics); without one it serves leader-local only.
    """

    def __init__(self, controller: Controller, dispatcher: "ControllerDispatcher | None" = None) -> None:
        self.controller = controller
        self.dispatcher = dispatcher

    def register(self, protocol: rpc.SimpleProtocol) -> None:
        protocol.register_service(rpc.ServiceHandler(cluster_service, self))

    def _reply(self, errc: int, message: str = "") -> dict:
        leader = self.controller.leader_id
        return {"errc": errc, "leader": -1 if leader is None else leader, "message": message}

    async def replicate_command(self, req: dict) -> dict:
        cmd = Command(CommandType(req["type"]), json.loads(req["data_json"].decode()))
        try:
            await self.controller.replicate_and_wait(cmd)
            return self._reply(_OK)
        except NotControllerError:
            return self._reply(_NOT_LEADER)
        except Exception as e:
            logger.exception("replicate_command failed")
            return self._reply(_ERROR, str(e))

    async def join_node(self, req: dict) -> dict:
        from redpanda_tpu.cluster import commands as cmds

        cmd = cmds.register_node_cmd(
            req["node_id"], req["host"], req["port"],
            req["kafka_host"], req["kafka_port"],
        )
        try:
            if self.dispatcher is not None:
                await self.dispatcher.replicate(cmd)
            else:
                await self.controller.replicate_and_wait(cmd)
            return self._reply(_OK)
        except NotControllerError:
            return self._reply(_NOT_LEADER)
        except Exception as e:
            logger.exception("join_node failed")
            return self._reply(_ERROR, str(e))


class ControllerDispatcher:
    """Run a controller mutation from ANY broker: try locally, forward to
    the leader otherwise (topics_frontend redirect semantics)."""

    def __init__(self, controller: Controller, connection_cache: rpc.ConnectionCache) -> None:
        self.controller = controller
        self.connections = connection_cache

    async def replicate(self, cmd: Command, *, retries: int = 3, timeout: float = 10.0) -> None:
        last = "no controller leader"
        for _ in range(retries):
            if self.controller.is_leader():
                try:
                    await self.controller.replicate_and_wait(cmd, timeout)
                    return
                except NotControllerError:
                    pass  # lost leadership mid-call; fall through to forward
            leader = self.controller.leader_id
            if leader is None or leader == self.controller.self_node.id:
                import asyncio

                await asyncio.sleep(0.2)
                continue
            client = rpc.Client(cluster_service, self.connections.get(leader))
            reply = await client.replicate_command(
                {
                    "type": int(cmd.type),
                    "data_json": json.dumps(cmd.data).encode(),
                },
                timeout=timeout,
            )
            if reply["errc"] == _OK:
                return
            last = reply["message"] or f"errc={reply['errc']}"
        raise ClusterError(f"controller mutation failed: {last}", retriable=True)


async def join_cluster(
    broker: Broker,
    seed_addr: tuple[str, int],
    connections: rpc.ConnectionCache,
    *,
    seed_node_hint: int = 0,
    timeout: float = 10.0,
) -> None:
    """Client side of node join: a fresh broker announces itself to a seed
    broker, which forwards to the controller leader if needed."""
    import asyncio

    connections.register(seed_node_hint, *seed_addr)
    client = rpc.Client(cluster_service, connections.get(seed_node_hint))
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        reply = await client.join_node(
            {
                "node_id": broker.node_id,
                "host": broker.host,
                "port": broker.port,
                "kafka_host": broker.kafka_host,
                "kafka_port": broker.kafka_port,
            },
            timeout=5.0,
        )
        if reply["errc"] == _OK:
            return
        if asyncio.get_running_loop().time() > deadline:
            raise ClusterError(f"join failed: {reply['message']}")
        await asyncio.sleep(0.3)
