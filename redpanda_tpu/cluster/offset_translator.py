"""Kafka <-> raft offset translation.

Parity with kafka/server/offset_translator.h:11-26: raft configuration (and
any other non-data) batches occupy log offsets that Kafka clients must never
see — a topic that went through elections or membership changes would
otherwise show offset gaps on the client side. The translator tracks every
non-data batch range ("gap") and converts between the two domains:

    kafka_offset = raft_offset - (# non-data offsets at or below it)

Design differences from the reference (which derives state from raft's
configuration_manager): this translator is self-contained at the partition
level. It observes every append through a log listener (leader, follower,
and recovery paths all funnel through DiskLog.append), persists its state in
the kvstore keyspace reserved for it in round 1 (storage/kvstore.py
KeySpace.offset_translator), and catches up by scanning only the log suffix
written since the last persisted state.

All Partition-facing APIs (produce results, fetch reads, watermarks,
timequery, list_offsets) speak Kafka offsets; raft internals keep raw log
offsets. Batches returned to clients are re-based into the Kafka domain —
safe because the Kafka CRC covers attributes..records, not base_offset.
"""

from __future__ import annotations

import struct

from redpanda_tpu.models.record import RecordBatchType

_HDR = struct.Struct("<qqqI")  # base_offset, base_delta, upto, ngaps
_GAP = struct.Struct("<qq")  # start, length


class OffsetTranslator:
    def __init__(self, ntp, kvs=None):
        self.ntp = ntp
        self._kvs = kvs
        self._key = f"otl/{ntp.path()}".encode()
        # raft offsets < _base are summarized by _base_delta gap offsets
        self._base = 0
        self._base_delta = 0
        self._gaps: list[tuple[int, int]] = []  # (raft start, length), sorted
        self._upto = -1  # highest raft offset observed

    # ------------------------------------------------------------ state
    @property
    def upto(self) -> int:
        return self._upto

    def total_delta(self) -> int:
        return self._base_delta + sum(l for _, l in self._gaps)

    # ------------------------------------------------------------ conversion
    def gaps_below(self, bound: int) -> int:
        """Number of gap (non-data) offsets strictly below `bound` (raft)."""
        total = self._base_delta
        for s, l in self._gaps:
            if s >= bound:
                break
            total += min(l, bound - s)
        return total

    def to_kafka_excl(self, bound: int) -> int:
        """Translate an exclusive raft upper bound (HWM/LSO convention)."""
        return bound - self.gaps_below(bound)

    def to_kafka(self, raft_offset: int) -> int:
        """Translate an inclusive raft offset (must not sit inside a gap)."""
        return self.to_kafka_excl(raft_offset + 1) - 1

    def from_kafka(self, kafka_offset: int) -> int:
        """Inclusive kafka -> raft (the first raft offset whose kafka
        translation is >= kafka_offset)."""
        r = kafka_offset + self._base_delta
        for s, l in self._gaps:
            if s <= r:
                r += l
            else:
                break
        return r

    def from_kafka_excl(self, bound: int) -> int:
        return self.from_kafka(bound - 1) + 1 if bound > 0 else self.from_kafka(0)

    # ------------------------------------------------------------ updates
    def observe(self, btype: RecordBatchType, base: int, last: int) -> None:
        """Feed one appended batch (any type); idempotent for replays."""
        if last <= self._upto:
            return
        if btype != RecordBatchType.raft_data:
            start = max(base, self._upto + 1)
            length = last - start + 1
            if length > 0:
                if self._gaps and self._gaps[-1][0] + self._gaps[-1][1] == start:
                    s, l = self._gaps[-1]
                    self._gaps[-1] = (s, l + length)
                else:
                    self._gaps.append((start, length))
                self._upto = last
                self._persist()
                return
        self._upto = last

    def truncate(self, offset: int) -> None:
        """Raft suffix truncation: forget gaps at/after `offset`."""
        changed = False
        while self._gaps and self._gaps[-1][0] + self._gaps[-1][1] > offset:
            s, l = self._gaps.pop()
            if s < offset:  # partial: keep the prefix of the gap
                self._gaps.append((s, offset - s))
                changed = True
                break
            changed = True
        if self._upto >= offset:
            self._upto = offset - 1
            changed = True
        if changed:
            self._persist()

    def advance_base(self, new_base: int) -> None:
        """Prefix truncation: collapse gaps fully below `new_base`."""
        changed = False
        while self._gaps and self._gaps[0][0] + self._gaps[0][1] <= new_base:
            s, l = self._gaps.pop(0)
            self._base_delta += l
            changed = True
        if new_base > self._base:
            self._base = new_base
            changed = True
        if changed:
            self._persist()

    # ------------------------------------------------------------ persistence
    def _persist(self) -> None:
        if self._kvs is None:
            return
        from redpanda_tpu.storage.kvstore import KeySpace

        blob = _HDR.pack(self._base, self._base_delta, self._upto, len(self._gaps))
        blob += b"".join(_GAP.pack(s, l) for s, l in self._gaps)
        self._kvs.put(KeySpace.offset_translator, self._key, blob)

    def _load(self) -> bool:
        if self._kvs is None:
            return False
        from redpanda_tpu.storage.kvstore import KeySpace

        blob = self._kvs.get(KeySpace.offset_translator, self._key)
        if not blob or len(blob) < _HDR.size:
            return False
        self._base, self._base_delta, self._upto, n = _HDR.unpack_from(blob, 0)
        self._gaps = [
            _GAP.unpack_from(blob, _HDR.size + i * _GAP.size) for i in range(n)
        ]
        return True

    async def bootstrap(self, log) -> "OffsetTranslator":
        """Load persisted state, then scan the log suffix written since
        (covers crashes between append and persist, and fresh logs)."""
        self._load()
        offs = log.offsets()
        if self._upto >= offs.dirty_offset:
            # persisted state may be AHEAD of the log after an unflushed
            # crash: clamp back so re-appends re-observe correctly
            self.truncate(offs.dirty_offset + 1)
            return self
        start = max(self._upto + 1, offs.start_offset)
        while start <= offs.dirty_offset:
            batches = await log.read(start, 4 << 20)
            if not batches:
                break
            for b in batches:
                self.observe(b.header.type, b.base_offset, b.last_offset)
            start = batches[-1].last_offset + 1
        if self._upto < offs.dirty_offset:
            # tail entirely non-data or empty reads: mark caught-up anyway
            self._upto = offs.dirty_offset
        self._persist()
        return self
