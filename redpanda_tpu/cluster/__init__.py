"""Cluster layer: control plane + partition runtime.

Parity with src/v/cluster: the ``Controller`` replicates typed commands
over raft group 0 (controller.h:31), every node's STM applies them to
shared tables (topic_table, members_table), and the ``ControllerBackend``
reconciles deltas into local raft groups/partitions
(controller_backend.cc:202). ``Partition`` fronts a pluggable consensus
(direct-log single-node, raft::consensus replicated).
"""

from redpanda_tpu.cluster.allocator import AllocationError, PartitionAllocator
from redpanda_tpu.cluster.commands import Command, CommandType
from redpanda_tpu.cluster.controller import (
    CONTROLLER_GROUP,
    CONTROLLER_NTP,
    ClusterError,
    Controller,
    NotControllerError,
)
from redpanda_tpu.cluster.controller_backend import ControllerBackend
from redpanda_tpu.cluster.leaders_table import PartitionLeadersTable
from redpanda_tpu.cluster.members import Broker, MembersTable, MembershipState
from redpanda_tpu.cluster.metadata_cache import MetadataCache
from redpanda_tpu.cluster.metadata_dissemination import MetadataDisseminationService
from redpanda_tpu.cluster.partition import Partition, PartitionManager
from redpanda_tpu.cluster.service import ClusterService, ControllerDispatcher, join_cluster
from redpanda_tpu.cluster.shard_table import ShardTable
from redpanda_tpu.cluster.topic_table import (
    PartitionAssignment,
    TopicConfig,
    TopicMetadata,
    TopicTable,
)

__all__ = [
    "AllocationError",
    "Broker",
    "CONTROLLER_GROUP",
    "CONTROLLER_NTP",
    "ClusterError",
    "ClusterService",
    "Command",
    "CommandType",
    "Controller",
    "ControllerBackend",
    "ControllerDispatcher",
    "MembersTable",
    "MembershipState",
    "MetadataCache",
    "MetadataDisseminationService",
    "NotControllerError",
    "Partition",
    "PartitionAllocator",
    "PartitionAssignment",
    "PartitionLeadersTable",
    "PartitionManager",
    "ShardTable",
    "TopicConfig",
    "TopicMetadata",
    "TopicTable",
    "join_cluster",
]
