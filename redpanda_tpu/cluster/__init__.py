"""Cluster layer: partition facade, topic metadata, partition manager.

Parity with src/v/cluster. Phase-3 scope is single-node: the ``Partition``
facade fronts a pluggable consensus (direct-log for one node, raft once the
consensus layer lands — mirroring cluster::partition over raft::consensus,
cluster/partition.h:34).
"""

from redpanda_tpu.cluster.partition import Partition, PartitionManager
from redpanda_tpu.cluster.topic_table import TopicConfig, TopicMetadata, TopicTable

__all__ = [
    "Partition",
    "PartitionManager",
    "TopicConfig",
    "TopicMetadata",
    "TopicTable",
]
