"""Partition facade + partition manager.

Parity with cluster::partition (cluster/partition.h:34-69) and
cluster::partition_manager (partition_manager.cc:53): the partition is the
broker-facing handle for one replicated log — replicate / make_reader /
offsets — delegating to a consensus implementation. Single-node mode uses
``DirectConsensus`` (append straight to storage, always leader); the raft
layer plugs in behind the same interface.
"""

from __future__ import annotations

from dataclasses import dataclass

from redpanda_tpu.models.fundamental import NTP, NodeId
from redpanda_tpu.models.record import RecordBatch, RecordBatchType
from redpanda_tpu.storage.log import DiskLog
from redpanda_tpu.storage.log_manager import StorageApi


class ConsistencyLevel:
    """raft/types.h consistency levels."""

    quorum_ack = 0  # acks=-1
    leader_ack = 1  # acks=1
    no_ack = 2  # acks=0


@dataclass
class ReplicateResult:
    base_offset: int
    last_offset: int


class DirectConsensus:
    """Single-node consensus: the local log IS the replicated log.

    Mirrors the no-raft slice of SURVEY.md §7 step 3; replaced by
    raft.Consensus for replicated topics.
    """

    def __init__(self, log: DiskLog, node_id: NodeId, term: int = 0):
        self.log = log
        self.node_id = node_id
        self._term = term

    @property
    def term(self) -> int:
        return self._term

    def is_leader(self) -> bool:
        return True

    def leadership_settled(self) -> bool:
        return True  # no elections on a direct log

    @property
    def leader_id(self) -> NodeId | None:
        return self.node_id

    @property
    def committed_offset(self) -> int:
        return self.log.offsets().dirty_offset

    @property
    def last_stable_offset(self) -> int:
        return self.committed_offset + 1  # exclusive, kafka LSO convention

    @property
    def start_offset(self) -> int:
        return self.log.offsets().start_offset

    async def replicate(self, batches: list[RecordBatch], level: int) -> ReplicateResult:
        res = await self.log.append(batches, term=self._term)
        if level == ConsistencyLevel.quorum_ack:
            await self.log.flush()
        return ReplicateResult(res.base_offset, res.last_offset)

    async def make_reader(
        self, start: int, max_bytes: int, max_offset: int | None = None, type_filter=None
    ) -> list[RecordBatch]:
        return await self.log.read(
            start,
            max_bytes,
            max_offset=max_offset,
            type_filter=type_filter,
        )


class Partition:
    """Broker-facing partition handle (cluster/partition.h:34).

    Every offset crossing this boundary is a KAFKA offset: raft config
    batches occupy raw log offsets that clients must never see
    (offset_translator.h:11-26), so produce results, reader start/limits,
    watermarks, and fetched batch base offsets are all translated here.
    Raft and storage below this line speak raw log offsets.
    """

    def __init__(self, ntp: NTP, consensus, log: DiskLog, kvs=None):
        from redpanda_tpu.cluster.offset_translator import OffsetTranslator

        self.ntp = ntp
        self.consensus = consensus
        self.log = log
        self.otl = OffsetTranslator(ntp, kvs)
        log.append_listeners.append(self.otl.observe)
        log.truncate_listeners.append(self.otl.truncate)
        self._otl_ready = False
        # tiered storage read side (cloud_storage.RemotePartition); serves
        # offsets below the local log start when attached
        self.remote = None

    async def start(self) -> "Partition":
        """Bootstrap the offset translator from kvstore + log scan."""
        if not self._otl_ready:
            await self.otl.bootstrap(self.log)
            self._otl_ready = True
        return self

    # -------------------------------------------------------------- state
    def is_leader(self) -> bool:
        return self.consensus.is_leader()

    def ready_for_reads(self) -> bool:
        """Leader AND settled (own-term entry committed): the read barrier
        consumers need for linearizable fetches right after an election."""
        settled = getattr(self.consensus, "leadership_settled", None)
        return self.is_leader() and (settled is None or settled())

    @property
    def leader_id(self) -> NodeId | None:
        return self.consensus.leader_id

    @property
    def term(self) -> int:
        return self.consensus.term

    def attach_remote(self, remote_partition) -> None:
        self.remote = remote_partition

    @property
    def start_offset(self) -> int:
        """Kafka-visible log start: extends back into tiered storage when a
        remote partition with uploaded data is attached."""
        local = self.otl.to_kafka_excl(self.consensus.start_offset)
        if self.remote is not None and self.remote.manifest.segments:
            return min(local, self.otl.to_kafka_excl(self.remote.start_offset))
        return local

    @property
    def high_watermark(self) -> int:
        """Exclusive next-offset convention, like kafka HWM."""
        return self.otl.to_kafka_excl(self.consensus.committed_offset + 1)

    @property
    def last_stable_offset(self) -> int:
        return self.otl.to_kafka_excl(self.consensus.last_stable_offset)

    # -------------------------------------------------------------- io
    async def replicate(self, batches: list[RecordBatch], level: int) -> ReplicateResult:
        res = await self.consensus.replicate(batches, level)
        base = getattr(res, "base_offset", None)
        if base is None:
            # raft's ReplicateResult carries only last_offset; offsets are
            # assigned contiguously, so the base falls out of the span
            span = sum(b.header.last_offset_delta + 1 for b in batches)
            base = res.last_offset - span + 1
        return ReplicateResult(
            self.otl.to_kafka(base), self.otl.to_kafka(res.last_offset)
        )

    async def make_reader(
        self, start: int, max_bytes: int = 1 << 20, max_offset: int | None = None
    ) -> list[RecordBatch]:
        """Read data batches in [start, max_offset] (kafka domain), re-based
        into kafka offsets. Safe to rewrite base_offset: the Kafka CRC
        covers attributes..records only."""
        if max_offset is None:
            max_offset = self.high_watermark - 1
        if start > max_offset:
            return []
        raft_start = self.otl.from_kafka(start)
        raft_max = self.otl.from_kafka(max_offset)
        batches: list[RecordBatch] = []
        if self.remote is not None and raft_start < self.consensus.start_offset:
            # tiered fall-through: the prefix lives only in the bucket
            batches = await self.remote.read(
                raft_start,
                max_bytes,
                max_offset=min(raft_max, self.consensus.start_offset - 1),
                type_filter=(RecordBatchType.raft_data,),
            )
            raft_start = self.consensus.start_offset
            max_bytes -= sum(b.size_bytes for b in batches)
        if max_bytes > 0 and raft_start <= raft_max:
            batches += await self.consensus.make_reader(
                raft_start,
                max_bytes,
                max_offset=raft_max,
                type_filter=(RecordBatchType.raft_data,),
            )
        out = []
        for b in batches:
            k = self.otl.to_kafka(b.base_offset)
            out.append(b.with_base_offset(k) if k != b.base_offset else b)
        return out

    async def timequery(self, ts: int) -> int | None:
        raft_off = await self.log.timequery(ts)
        return None if raft_off is None else self.otl.to_kafka(raft_off)

    async def prefix_truncate(self, offset: int) -> None:
        """offset is a kafka offset (DeleteRecords / archival housekeeping).

        The translator keeps its FULL gap history (no advance_base): evicted
        prefixes may still be served from tiered storage, and those reads
        need per-offset translation below the local start."""
        raft_off = self.otl.from_kafka(offset)
        await self.log.prefix_truncate(raft_off)


class PartitionManager:
    """Creates/looks up partitions over the storage api
    (cluster/partition_manager.cc:53 manage())."""

    def __init__(self, storage: StorageApi, node_id: NodeId):
        self.storage = storage
        self.node_id = node_id
        self._partitions: dict[NTP, Partition] = {}

    async def manage(self, ntp: NTP, *, term: int = 0, log_overrides=None) -> Partition:
        if ntp in self._partitions:
            return self._partitions[ntp]
        log = await self.storage.log_mgr.manage(ntp, overrides=log_overrides)
        consensus = DirectConsensus(log, self.node_id, term)
        p = await Partition(ntp, consensus, log, kvs=self.storage.kvs).start()
        self._partitions[ntp] = p
        return p

    def attach(self, ntp: NTP, partition: Partition) -> None:
        """Register an externally built partition (raft-backed)."""
        self._partitions[ntp] = partition

    def detach(self, ntp: NTP) -> Partition | None:
        """Unregister without touching storage (raft-backed partitions: the
        group manager owns the log teardown)."""
        return self._partitions.pop(ntp, None)

    def get(self, ntp: NTP) -> Partition | None:
        return self._partitions.get(ntp)

    def partitions(self) -> dict[NTP, Partition]:
        return dict(self._partitions)

    async def remove(self, ntp: NTP) -> None:
        p = self._partitions.pop(ntp, None)
        if p is not None:
            await self.storage.log_mgr.remove(ntp)
