"""Partition facade + partition manager.

Parity with cluster::partition (cluster/partition.h:34-69) and
cluster::partition_manager (partition_manager.cc:53): the partition is the
broker-facing handle for one replicated log — replicate / make_reader /
offsets — delegating to a consensus implementation. Single-node mode uses
``DirectConsensus`` (append straight to storage, always leader); the raft
layer plugs in behind the same interface.
"""

from __future__ import annotations

from dataclasses import dataclass

from redpanda_tpu.models.fundamental import NTP, NodeId
from redpanda_tpu.models.record import RecordBatch, RecordBatchType
from redpanda_tpu.storage.log import DiskLog
from redpanda_tpu.storage.log_manager import StorageApi


class ConsistencyLevel:
    """raft/types.h consistency levels."""

    quorum_ack = 0  # acks=-1
    leader_ack = 1  # acks=1
    no_ack = 2  # acks=0


@dataclass
class ReplicateResult:
    base_offset: int
    last_offset: int


class DirectConsensus:
    """Single-node consensus: the local log IS the replicated log.

    Mirrors the no-raft slice of SURVEY.md §7 step 3; replaced by
    raft.Consensus for replicated topics.
    """

    def __init__(self, log: DiskLog, node_id: NodeId, term: int = 0):
        self.log = log
        self.node_id = node_id
        self._term = term

    @property
    def term(self) -> int:
        return self._term

    def is_leader(self) -> bool:
        return True

    @property
    def leader_id(self) -> NodeId | None:
        return self.node_id

    @property
    def committed_offset(self) -> int:
        return self.log.offsets().dirty_offset

    @property
    def last_stable_offset(self) -> int:
        return self.committed_offset + 1  # exclusive, kafka LSO convention

    @property
    def start_offset(self) -> int:
        return self.log.offsets().start_offset

    async def replicate(self, batches: list[RecordBatch], level: int) -> ReplicateResult:
        res = await self.log.append(batches, term=self._term)
        if level == ConsistencyLevel.quorum_ack:
            await self.log.flush()
        return ReplicateResult(res.base_offset, res.last_offset)

    async def make_reader(
        self, start: int, max_bytes: int, max_offset: int | None = None
    ) -> list[RecordBatch]:
        return await self.log.read(
            start,
            max_bytes,
            max_offset=max_offset,
            type_filter=(RecordBatchType.raft_data,),
        )


class Partition:
    """Broker-facing partition handle (cluster/partition.h:34)."""

    def __init__(self, ntp: NTP, consensus, log: DiskLog):
        self.ntp = ntp
        self.consensus = consensus
        self.log = log

    # -------------------------------------------------------------- state
    def is_leader(self) -> bool:
        return self.consensus.is_leader()

    @property
    def leader_id(self) -> NodeId | None:
        return self.consensus.leader_id

    @property
    def term(self) -> int:
        return self.consensus.term

    @property
    def start_offset(self) -> int:
        return self.consensus.start_offset

    @property
    def high_watermark(self) -> int:
        """Exclusive next-offset convention, like kafka HWM."""
        return self.consensus.committed_offset + 1

    @property
    def last_stable_offset(self) -> int:
        return self.consensus.last_stable_offset

    # -------------------------------------------------------------- io
    async def replicate(self, batches: list[RecordBatch], level: int) -> ReplicateResult:
        return await self.consensus.replicate(batches, level)

    async def make_reader(
        self, start: int, max_bytes: int = 1 << 20, max_offset: int | None = None
    ) -> list[RecordBatch]:
        if max_offset is None:
            max_offset = self.high_watermark - 1
        if start > max_offset:
            return []
        return await self.consensus.make_reader(start, max_bytes, max_offset)

    async def timequery(self, ts: int) -> int | None:
        return await self.log.timequery(ts)

    async def prefix_truncate(self, offset: int) -> None:
        await self.log.prefix_truncate(offset)


class PartitionManager:
    """Creates/looks up partitions over the storage api
    (cluster/partition_manager.cc:53 manage())."""

    def __init__(self, storage: StorageApi, node_id: NodeId):
        self.storage = storage
        self.node_id = node_id
        self._partitions: dict[NTP, Partition] = {}

    async def manage(self, ntp: NTP, *, term: int = 0, log_overrides=None) -> Partition:
        if ntp in self._partitions:
            return self._partitions[ntp]
        log = await self.storage.log_mgr.manage(ntp, overrides=log_overrides)
        consensus = DirectConsensus(log, self.node_id, term)
        p = Partition(ntp, consensus, log)
        self._partitions[ntp] = p
        return p

    def attach(self, ntp: NTP, partition: Partition) -> None:
        """Register an externally built partition (raft-backed)."""
        self._partitions[ntp] = partition

    def detach(self, ntp: NTP) -> Partition | None:
        """Unregister without touching storage (raft-backed partitions: the
        group manager owns the log teardown)."""
        return self._partitions.pop(ntp, None)

    def get(self, ntp: NTP) -> Partition | None:
        return self._partitions.get(ntp)

    def partitions(self) -> dict[NTP, Partition]:
        return dict(self._partitions)

    async def remove(self, ntp: NTP) -> None:
        p = self._partitions.pop(ntp, None)
        if p is not None:
            await self.storage.log_mgr.remove(ntp)
