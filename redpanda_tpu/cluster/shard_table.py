"""NTP → shard routing table.

Parity with cluster/shard_table.h. The reference pins each partition to one
seastar core and every cross-shard touch goes through this map. The TPU
build's "shards" are asyncio workers feeding per-shard device batches (the
`[partition, batch, record]` packing axis — SURVEY.md §2.3.1); the table
still exists so the coproc pacemaker and kafka fetch planner can group
partitions by shard exactly like the reference's fetch plan does
(kafka/server/fetch.cc:390).
"""

from __future__ import annotations

from redpanda_tpu.hashing.jump import jump_consistent_hash
from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.models.fundamental import NTP


class ShardTable:
    def __init__(self, n_shards: int = 1) -> None:
        self.n_shards = max(1, n_shards)
        self._explicit: dict[NTP, int] = {}

    def update(self, ntp: NTP, shard: int) -> None:
        self._explicit[ntp] = shard % self.n_shards

    def erase(self, ntp: NTP) -> None:
        self._explicit.pop(ntp, None)

    def shard_for(self, ntp: NTP) -> int:
        s = self._explicit.get(ntp)
        if s is not None:
            return s
        # default placement: jump hash of the ntp identity, the same scheme
        # connection_cache uses for peers (hashing/jump_consistent_hash.h)
        key = xxhash64(str(ntp).encode())
        return jump_consistent_hash(key, self.n_shards)

    def group_by_shard(self, ntps: list[NTP]) -> dict[int, list[NTP]]:
        out: dict[int, list[NTP]] = {}
        for ntp in ntps:
            out.setdefault(self.shard_for(ntp), []).append(ntp)
        return out
