"""Topic metadata table.

Parity with cluster::topic_table (cluster/topic_table.h): the in-memory
source of truth for topic/partition metadata plus a delta stream consumed by
reconciliation (controller_backend.cc:202). In single-node mode mutations
are applied locally; once the controller lands, mutations arrive as applied
controller commands.
"""

from __future__ import annotations

import asyncio
import enum
from dataclasses import dataclass, field

from redpanda_tpu.models.fundamental import NTP, DEFAULT_NAMESPACE, NodeId


@dataclass
class TopicConfig:
    name: str
    partition_count: int
    replication_factor: int = 1
    ns: str = DEFAULT_NAMESPACE
    cleanup_policy: str = "delete"
    retention_bytes: int | None = None
    retention_ms: int | None = None
    delete_retention_ms: int | None = None  # tombstone retention (compact)
    segment_size: int | None = None
    compression: str = "producer"
    # incarnation id: bumped on recreate so tiered-storage object paths
    # never collide with a deleted topic's uploads (partition_path _<rev>)
    revision: int = 0
    extra: dict[str, str] = field(default_factory=dict)

    def log_overrides(self, base):
        """Per-topic storage knobs → a LogConfig for this topic's logs
        (log_config overrides in log_manager::manage). Kafka's -1 sentinel
        means UNLIMITED retention, never 'delete everything'."""
        import dataclasses

        overrides = {}
        if self.segment_size is not None and self.segment_size > 0:
            overrides["max_segment_size"] = self.segment_size
        if self.retention_bytes is not None and self.retention_bytes >= 0:
            overrides["retention_bytes"] = self.retention_bytes
        if self.retention_ms is not None and self.retention_ms >= 0:
            overrides["retention_ms"] = self.retention_ms
        if self.cleanup_policy != "delete":
            overrides["cleanup_policy"] = self.cleanup_policy
        if self.delete_retention_ms is not None:
            overrides["delete_retention_ms"] = self.delete_retention_ms
        return dataclasses.replace(base, **overrides) if overrides else None

    def apply_override(self, key: str, value: str | None) -> None:
        """Kafka config key → typed field (alter_configs / controller
        update_topic_properties apply path)."""
        if value is None:
            return
        if key == "cleanup.policy":
            self.cleanup_policy = value
        elif key == "retention.bytes":
            self.retention_bytes = int(value)
        elif key == "retention.ms":
            self.retention_ms = int(value)
        elif key == "delete.retention.ms":
            self.delete_retention_ms = int(value)
        elif key == "segment.bytes":
            self.segment_size = int(value)
        elif key == "compression.type":
            self.compression = value
        else:
            self.extra[key] = value

    def config_map(self) -> dict[str, str | None]:
        m: dict[str, str | None] = {
            "cleanup.policy": self.cleanup_policy,
            "compression.type": self.compression,
            "retention.bytes": None if self.retention_bytes is None else str(self.retention_bytes),
            "retention.ms": None if self.retention_ms is None else str(self.retention_ms),
        }
        if self.segment_size is not None:
            m["segment.bytes"] = str(self.segment_size)
        m.update(self.extra)
        return m


@dataclass
class PartitionAssignment:
    ntp: NTP
    replicas: list[NodeId]
    leader: NodeId | None = None
    # raft group id, allocated by the controller leader and carried in the
    # create command so the apply is deterministic on every node
    # (cluster/partition_assignment.h `group`); -1 = single-node direct log.
    group: int = -1
    # replica set being moved to, while a move_partition_replicas is in
    # flight (topic_table in_progress updates)
    moving_to: list[NodeId] | None = None


@dataclass
class TopicMetadata:
    config: TopicConfig
    assignments: dict[int, PartitionAssignment] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.config.name


class DeltaType(enum.IntEnum):
    added = 0
    removed = 1
    updated = 2


@dataclass
class TopicDelta:
    type: DeltaType
    ntp: NTP
    assignment: PartitionAssignment | None = None


class TopicTable:
    def __init__(self):
        self._topics: dict[str, TopicMetadata] = {}
        self._waiters: list[asyncio.Future] = []
        self._deltas: list[TopicDelta] = []

    # ------------------------------------------------------------ mutate
    def add_topic(self, config: TopicConfig, replicas_for=lambda p: [0]) -> TopicMetadata:
        if config.name in self._topics:
            raise ValueError(f"topic exists: {config.name}")
        md = TopicMetadata(config)
        for p in range(config.partition_count):
            ntp = NTP(config.ns, config.name, p)
            reps = list(replicas_for(p))
            md.assignments[p] = PartitionAssignment(ntp, reps, leader=reps[0] if reps else None)
            self._push_delta(TopicDelta(DeltaType.added, ntp, md.assignments[p]))
        self._topics[config.name] = md
        return md

    def remove_topic(self, name: str) -> TopicMetadata:
        md = self._topics.pop(name)
        for pa in md.assignments.values():
            self._push_delta(TopicDelta(DeltaType.removed, pa.ntp))
        return md

    def add_partitions(self, name: str, new_count: int, replicas_for=lambda p: [0]) -> None:
        md = self._topics[name]
        old = md.config.partition_count
        if new_count <= old:
            raise ValueError("partition count can only grow")
        for p in range(old, new_count):
            ntp = NTP(md.config.ns, name, p)
            reps = list(replicas_for(p))
            md.assignments[p] = PartitionAssignment(ntp, reps, leader=reps[0] if reps else None)
            self._push_delta(TopicDelta(DeltaType.added, ntp, md.assignments[p]))
        md.config.partition_count = new_count

    def apply_create(self, config: TopicConfig, assignments: list[PartitionAssignment]) -> TopicMetadata:
        """Deterministic apply of a replicated create_topic command: the
        assignments (incl. raft group ids) were fixed by the leader.

        A DUPLICATE create in the log (two brokers raced the same name past
        the leader's pre-check; both commands committed) applies as a no-op
        keeping the FIRST winner's assignments — the command sits in the
        log forever, so raising here would also fail every restart replay."""
        if config.name in self._topics:
            import logging

            logging.getLogger("rptpu.cluster.topics").info(
                "ignoring duplicate create for existing topic %r", config.name
            )
            return self._topics[config.name]
        md = TopicMetadata(config)
        for pa in assignments:
            md.assignments[pa.ntp.partition] = pa
            self._push_delta(TopicDelta(DeltaType.added, pa.ntp, pa))
        config.partition_count = len(assignments)
        self._topics[config.name] = md
        return md

    def apply_add_partitions(self, name: str, assignments: list[PartitionAssignment]) -> None:
        md = self._topics[name]
        for pa in assignments:
            md.assignments[pa.ntp.partition] = pa
            self._push_delta(TopicDelta(DeltaType.added, pa.ntp, pa))
        md.config.partition_count = len(md.assignments)

    def update_properties(self, name: str, overrides: dict) -> None:
        md = self._topics[name]
        for k, v in overrides.items():
            md.config.apply_override(k, v)

    def begin_move(self, ntp: NTP, replicas: list[NodeId]) -> None:
        """move_partition_replicas: new set recorded, reconciliation begins
        (topic_table in-progress update + delta)."""
        pa = self._topics[ntp.topic].assignments[ntp.partition]
        pa.moving_to = list(replicas)
        self._push_delta(TopicDelta(DeltaType.updated, ntp, pa))

    def finish_move(self, ntp: NTP, replicas: list[NodeId]) -> None:
        """finish_moving_partition_replicas: the new replica set is caught
        up; old replicas can drop their copy."""
        pa = self._topics[ntp.topic].assignments[ntp.partition]
        pa.replicas = list(replicas)
        pa.moving_to = None
        self._push_delta(TopicDelta(DeltaType.updated, ntp, pa))

    def set_leader(self, ntp: NTP, leader: NodeId | None) -> None:
        md = self._topics.get(ntp.topic)
        if md and ntp.partition in md.assignments:
            md.assignments[ntp.partition].leader = leader
            self._push_delta(TopicDelta(DeltaType.updated, ntp, md.assignments[ntp.partition]))

    # ------------------------------------------------------------ query
    def get(self, name: str) -> TopicMetadata | None:
        return self._topics.get(name)

    def contains(self, name: str) -> bool:
        return name in self._topics

    def topics(self) -> dict[str, TopicMetadata]:
        return dict(self._topics)

    def all_ntps(self) -> list[NTP]:
        return [pa.ntp for md in self._topics.values() for pa in md.assignments.values()]

    # ------------------------------------------------------------ deltas
    def _push_delta(self, d: TopicDelta) -> None:
        self._deltas.append(d)
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            if not w.done():
                w.set_result(None)

    def drain_deltas(self) -> list[TopicDelta]:
        out, self._deltas = self._deltas, []
        return out

    async def wait_for_deltas(self) -> list[TopicDelta]:
        if not self._deltas:
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            await fut
        return self.drain_deltas()
