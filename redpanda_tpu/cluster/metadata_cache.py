"""Metadata cache: the kafka layer's one-stop metadata view.

Parity with cluster/metadata_cache.h (wired application.cc:611-617):
aggregates topic_table (topics/assignments), members_table (brokers) and
partition_leaders_table (who leads what) behind the queries the kafka
handlers need. Pure facade — no state of its own.
"""

from __future__ import annotations

from redpanda_tpu.cluster.leaders_table import PartitionLeadersTable
from redpanda_tpu.cluster.members import Broker, MembersTable
from redpanda_tpu.cluster.topic_table import TopicMetadata, TopicTable
from redpanda_tpu.models.fundamental import NTP, NodeId


class MetadataCache:
    def __init__(
        self,
        topic_table: TopicTable,
        members: MembersTable,
        leaders: PartitionLeadersTable,
    ) -> None:
        self.topic_table = topic_table
        self.members = members
        self.leaders = leaders

    # ------------------------------------------------------------ brokers
    def all_brokers(self) -> list[Broker]:
        return self.members.all_brokers()

    def get_broker(self, node_id: NodeId) -> Broker | None:
        return self.members.get(node_id)

    # ------------------------------------------------------------ topics
    def contains(self, topic: str) -> bool:
        return self.topic_table.contains(topic)

    def get_topic(self, topic: str) -> TopicMetadata | None:
        return self.topic_table.get(topic)

    def all_topics(self) -> dict[str, TopicMetadata]:
        return self.topic_table.topics()

    # ------------------------------------------------------------ leaders
    def get_leader(self, ntp: NTP) -> NodeId | None:
        leader = self.leaders.get_leader(ntp)
        if leader is not None:
            return leader
        md = self.topic_table.get(ntp.topic)
        if md and ntp.partition in md.assignments:
            pa = md.assignments[ntp.partition]
            if pa.leader is not None:
                return pa.leader
            if pa.group < 0:
                # materialized (non-replicable) partitions have no raft
                # leader; they are written and served by the SOURCE
                # partition's leader (materialized_partition fetch routing)
                from redpanda_tpu.models.fundamental import MaterializedNTP

                m = MaterializedNTP.parse(ntp)
                if m is not None:
                    return self.get_leader(m.source)
        return None

    async def wait_for_leader(self, ntp: NTP, timeout: float = 5.0) -> NodeId:
        return await self.leaders.wait_for_leader(ntp, timeout)
