"""Per-partition producer / transaction state machine.

Parity with cluster/rm_stm.h:45 + rm_stm.cc (1,388 LoC in the reference):
idempotent-producer sequence tracking, open-transaction ranges, commit/abort
control markers written to the log, aborted-range tracking for
read_committed fetches, and the last-stable-offset (LSO) clamp. State is
rebuilt by scanning the log on open (the reference snapshots via
persisted_stm at an offset and replays the suffix; a full scan is the
bootstrap path here, with the same replay logic).
"""

from __future__ import annotations

import logging
import struct
from dataclasses import dataclass, field

from redpanda_tpu.kafka.protocol.errors import ErrorCode as E
from redpanda_tpu.models.record import Record, RecordBatch

logger = logging.getLogger("rptpu.cluster.rm_stm")

# Kafka control-record key: version int16, type int16 (0 = abort, 1 = commit)
_ABORT_MARKER = 0
_COMMIT_MARKER = 1


def make_control_marker(
    marker_type: int, producer_id: int, producer_epoch: int, coordinator_epoch: int = 0
) -> RecordBatch:
    key = struct.pack(">hh", 0, marker_type)
    value = struct.pack(">hi", 0, coordinator_epoch)
    return RecordBatch.build(
        [Record(key=key, value=value)],
        producer_id=producer_id,
        producer_epoch=producer_epoch,
        transactional=True,
        control=True,
    )


def parse_control_marker(batch: RecordBatch) -> int | None:
    """Returns the marker type, or None when not a control batch."""
    if not batch.header.is_control:
        return None
    recs = batch.records()
    if not recs or recs[0].key is None or len(recs[0].key) < 4:
        return None
    (_version, mtype) = struct.unpack_from(">hh", recs[0].key, 0)
    return mtype


@dataclass
class ProducerState:
    epoch: int
    last_seq: int = -1


@dataclass
class AbortedTx:
    producer_id: int
    first_offset: int
    last_offset: int


class RmStm:
    """Attached to one partition by the broker (partition.h stm hooks)."""

    def __init__(self, partition) -> None:
        self.partition = partition
        self._producers: dict[int, ProducerState] = {}
        # pid -> first offset of the open transaction on THIS partition
        self._ongoing: dict[int, int] = {}
        # pids whose AddPartitionsToTxn arrived but no data yet (tx_fence)
        self._pending_begin: set[int] = set()
        self._aborted: list[AbortedTx] = []
        self._recovered = False
        self._recover_lock = None  # lazily created (needs a running loop)
        self._lock = None  # produce-path critical section, lazily created

    # ------------------------------------------------------------ recovery
    async def ensure_recovered(self) -> "RmStm":
        import asyncio

        if self._recovered:
            return self
        if self._recover_lock is None:
            self._recover_lock = asyncio.Lock()
        async with self._recover_lock:
            if not self._recovered:
                await self.recover()
                self._recovered = True
        return self

    async def recover(self) -> None:
        """Replay the log to rebuild producer/tx state (persisted_stm
        bootstrap; full-scan variant)."""
        start = self.partition.start_offset
        hwm = self.partition.high_watermark
        offset = start
        while offset < hwm:
            batches = await self.partition.make_reader(offset, 4 << 20)
            if not batches:
                break
            for b in batches:
                self._apply(b)
                offset = b.last_offset + 1

    def _apply(self, batch: RecordBatch) -> None:
        hdr = batch.header
        pid = hdr.producer_id
        if pid < 0:
            return
        mtype = parse_control_marker(batch)
        if mtype is not None:
            first = self._ongoing.pop(pid, None)
            if mtype == _ABORT_MARKER and first is not None:
                self._aborted.append(AbortedTx(pid, first, hdr.base_offset))
            return
        st = self._producers.get(pid)
        if st is None or hdr.producer_epoch > st.epoch:
            st = ProducerState(hdr.producer_epoch)
            self._producers[pid] = st
        if hdr.base_sequence >= 0:
            st.last_seq = hdr.base_sequence + hdr.record_count - 1
        if hdr.is_transactional and pid not in self._ongoing:
            self._ongoing[pid] = hdr.base_offset

    # ------------------------------------------------------------ produce path
    async def replicate(self, batches: list[RecordBatch], level: int):
        """Gate + append + state update, atomically per partition.

        The check and the append MUST be one critical section: two retried
        produces for the same pid would otherwise both pass the sequence
        check while the first is suspended in the log append, writing the
        duplicate idempotence exists to prevent (rm_stm does its checks
        inside replicate under op_lock for the same reason).

        Returns (errc, ReplicateResult | None); (none, None) = every batch
        was a duplicate and the request is acked without appending.
        """
        import asyncio

        if self._lock is None:
            self._lock = asyncio.Lock()
        async with self._lock:
            to_append: list[RecordBatch] = []
            sim: dict[int, int] = {}  # pid -> last_seq incl. earlier batches in THIS request
            for b in batches:
                code = self._check(b, sim)
                if code == E.duplicate_sequence_number:
                    continue  # retried batch: skip, ack the rest
                if code != E.none:
                    return code, None
                if b.header.producer_id >= 0 and b.header.base_sequence >= 0:
                    sim[b.header.producer_id] = (
                        b.header.base_sequence + b.header.record_count - 1
                    )
                to_append.append(b)
            if not to_append:
                return E.none, None
            res = await self.partition.replicate(to_append, level)  # pandalint: disable=LCK702 -- idempotency stm: sequence-check + replicate + note_appended must be one atom or dedup state races the log
            base = res.base_offset
            for b in to_append:
                self._note_appended(b, base)
                base += b.header.record_count
            return E.none, res

    def _check(self, batch: RecordBatch, sim: dict[int, int]) -> E:
        hdr = batch.header
        pid = hdr.producer_id
        if pid < 0:
            return E.none
        st = self._producers.get(pid)
        if st is not None and hdr.producer_epoch < st.epoch:
            return E.invalid_producer_epoch
        if hdr.is_transactional and pid not in self._ongoing and pid not in self._pending_begin:
            # transactional produce requires AddPartitionsToTxn first
            return E.invalid_txn_state
        if hdr.base_sequence >= 0:
            # earlier batches of THIS request count even for a brand-new
            # producer (st None) — a retried duplicate inside one request
            # must still dedup
            last = sim.get(pid)
            if last is None and st is not None and hdr.producer_epoch == st.epoch:
                last = st.last_seq if st.last_seq != -1 else None
            if last is not None:
                if hdr.base_sequence == last + 1:
                    return E.none
                if hdr.base_sequence <= last:
                    return E.duplicate_sequence_number
                return E.out_of_order_sequence_number
        return E.none

    def _note_appended(self, batch: RecordBatch, base_offset: int) -> None:
        hdr = batch.header
        pid = hdr.producer_id
        if pid < 0:
            return
        st = self._producers.get(pid)
        if st is None or hdr.producer_epoch > st.epoch:
            st = ProducerState(hdr.producer_epoch)
            self._producers[pid] = st
        if hdr.base_sequence >= 0:
            st.last_seq = hdr.base_sequence + hdr.record_count - 1
        if hdr.is_transactional:
            self._pending_begin.discard(pid)
            if pid not in self._ongoing:
                self._ongoing[pid] = base_offset

    # ------------------------------------------------------------ tx control
    def begin_tx(self, pid: int, epoch: int) -> E:
        """AddPartitionsToTxn landed here: open the tx gate for pid."""
        st = self._producers.get(pid)
        if st is not None and epoch < st.epoch:
            return E.invalid_producer_epoch
        if st is None:
            self._producers[pid] = ProducerState(epoch)
        self._pending_begin.add(pid)
        return E.none

    async def end_tx(self, pid: int, epoch: int, commit: bool) -> E:
        from redpanda_tpu.cluster.partition import ConsistencyLevel

        st = self._producers.get(pid)
        if st is not None and epoch < st.epoch:
            return E.invalid_producer_epoch
        self._pending_begin.discard(pid)
        if pid not in self._ongoing:
            return E.none  # no data written here; nothing to mark
        marker = make_control_marker(
            _COMMIT_MARKER if commit else _ABORT_MARKER, pid, epoch
        )
        res = await self.partition.replicate([marker], ConsistencyLevel.quorum_ack)
        first = self._ongoing.pop(pid)
        if not commit:
            self._aborted.append(AbortedTx(pid, first, res.last_offset))
        return E.none

    # ------------------------------------------------------------ fetch path
    @property
    def last_stable_offset(self) -> int:
        """Exclusive LSO: first offset of the earliest open tx, else HWM."""
        hwm = self.partition.high_watermark
        if not self._ongoing:
            return hwm
        return min(min(self._ongoing.values()), hwm)

    def aborted_ranges(self, fetch_offset: int, max_offset: int) -> list[AbortedTx]:
        return [
            a
            for a in self._aborted
            if a.last_offset >= fetch_offset and a.first_offset <= max_offset
        ]
