"""Partition allocator.

Parity with cluster/partition_allocator + cluster/scheduling/ (allocation
nodes, constraints; docs/rfcs/20191020_partition_allocator.md): the
controller leader assigns a replica set per partition subject to hard
constraints (distinct nodes, node not decommissioned, capacity) and a
soft objective (least-allocated node first). Deterministic given the same
table state, so tests can predict placements.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from redpanda_tpu.models.fundamental import NodeId


class AllocationError(Exception):
    pass


@dataclass
class AllocationNode:
    """Per-node allocation bookkeeping (cluster/scheduling/allocation_node)."""

    node_id: NodeId
    # One "core" ~ capacity for partition_capacity_per_core replicas; the
    # TPU build has no seastar shards, so capacity is a flat per-node count.
    max_capacity: int = 7000
    allocated: int = 0
    decommissioned: bool = False

    @property
    def free(self) -> int:
        return self.max_capacity - self.allocated

    def can_host(self) -> bool:
        return not self.decommissioned and self.free > 0


class PartitionAllocator:
    def __init__(self) -> None:
        self._nodes: dict[NodeId, AllocationNode] = {}

    # ------------------------------------------------------------ membership
    def register_node(self, node_id: NodeId, max_capacity: int = 7000) -> None:
        if node_id not in self._nodes:
            self._nodes[node_id] = AllocationNode(node_id, max_capacity)

    def unregister_node(self, node_id: NodeId) -> None:
        self._nodes.pop(node_id, None)

    def decommission_node(self, node_id: NodeId) -> None:
        n = self._nodes.get(node_id)
        if n:
            n.decommissioned = True

    def recommission_node(self, node_id: NodeId) -> None:
        n = self._nodes.get(node_id)
        if n:
            n.decommissioned = False

    def node(self, node_id: NodeId) -> AllocationNode | None:
        return self._nodes.get(node_id)

    def nodes(self) -> list[AllocationNode]:
        return list(self._nodes.values())

    # ------------------------------------------------------------ allocate
    def allocate(
        self, partition_count: int, replication_factor: int, *, commit: bool = False
    ) -> list[list[NodeId]]:
        """Replica sets for a new topic; raises if constraints unsatisfiable.

        With commit=False (the frontend path) the bookkeeping increments are
        rolled back: real accounting happens when the replicated command is
        APPLIED (note_allocated), so every node's allocator converges and a
        controller failover doesn't reset the load picture.
        """
        eligible = [n for n in self._nodes.values() if not n.decommissioned]
        if replication_factor > len(eligible):
            raise AllocationError(
                f"replication factor {replication_factor} > {len(eligible)} usable nodes"
            )
        out: list[list[NodeId]] = []
        try:
            for _ in range(partition_count):
                out.append(self._allocate_one(replication_factor))
        finally:
            if not commit:
                for s in out:
                    self.deallocate(s)
        return out

    def note_allocated(self, replicas: list[NodeId]) -> None:
        """Apply-path bookkeeping for a replicated assignment."""
        for r in replicas:
            n = self._nodes.get(r)
            if n is not None:
                n.allocated += 1

    def _allocate_one(
        self, replication_factor: int, exclude: set[NodeId] = frozenset()
    ) -> list[NodeId]:
        candidates = sorted(
            (
                n
                for n in self._nodes.values()
                if n.can_host() and n.node_id not in exclude
            ),
            # soft constraint: least allocated first; node id tiebreak for
            # determinism
            key=lambda n: (n.allocated, n.node_id),
        )
        if len(candidates) < replication_factor:
            raise AllocationError(
                f"cannot place {replication_factor} replicas on "
                f"{len(candidates)} candidate nodes"
            )
        chosen = candidates[:replication_factor]
        for n in chosen:
            n.allocated += 1
        return [n.node_id for n in chosen]

    def reallocate_replica(
        self, current: list[NodeId], leaving: NodeId
    ) -> list[NodeId]:
        """Replica set with `leaving` replaced (decommission path,
        members_backend semantics). Pure choice — accounting happens when
        finish_moving_partition_replicas is applied."""
        keep = [r for r in current if r != leaving]
        replacement = self._allocate_one(1, exclude=set(current))
        self.deallocate(replacement)  # roll back the selection increment
        return keep + replacement

    def deallocate(self, replicas: list[NodeId]) -> None:
        for r in replicas:
            n = self._nodes.get(r)
            if n and n.allocated > 0:
                n.allocated -= 1
