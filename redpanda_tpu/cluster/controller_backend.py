"""Controller backend: per-node reconciliation of topic-table deltas.

Parity with cluster/controller_backend.cc:202-225: a fiber per node watches
the (replicated) topic table's delta stream and converges local state —
create the raft group + partition for assignments that include this node,
tear down removed ones, and drive replica movement (create on new nodes,
joint-consensus config change on the leader, delete on old nodes after
finish). Combined with partition_manager.manage / raft group_manager, this
is the only component that turns metadata into running replicas.
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.cluster.partition import Partition
from redpanda_tpu.cluster.topic_table import DeltaType, TopicDelta, TopicTable
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.raft.types import VNode

logger = logging.getLogger("rptpu.cluster.backend")


class ControllerBackend:
    def __init__(
        self,
        self_node: VNode,
        topic_table: TopicTable,
        group_manager,  # raft.GroupManager
        partition_manager,  # cluster.PartitionManager
        leaders_table=None,
        shard_table=None,
        finish_move=None,  # async callable(ntp, replicas) — routes to controller leader
    ) -> None:
        self.self_node = self_node
        self.topic_table = topic_table
        self.gm = group_manager
        self.pm = partition_manager
        self.leaders = leaders_table
        self.shards = shard_table
        self._finish_move = finish_move
        self._task: asyncio.Task | None = None
        self._move_tasks: dict[NTP, asyncio.Task] = {}
        self.gm.register_leadership_notification(self._on_leadership)

    def _on_leadership(self, consensus) -> None:
        if self.leaders is not None:
            self.leaders.update(consensus.ntp, consensus.leader_id, consensus.term)
        # a move issued before this group had a leader parks until an
        # election lands here — re-kick it (controller_backend re-runs its
        # reconciliation loop on leadership change for the same reason)
        if consensus.is_leader():
            pa = self._assignment(consensus.ntp)
            if pa is not None and pa.moving_to is not None:
                ntp, group, target = consensus.ntp, pa.group, list(pa.moving_to)
                if ntp not in self._move_tasks:
                    self._move_tasks[ntp] = asyncio.create_task(
                        self._drive_move(ntp, group, target)
                    )

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "ControllerBackend":
        # bootstrap: apply everything already in the table (stm replay on
        # restart lands deltas before we start — calculate_bootstrap_deltas
        # controller_backend.cc:217)
        for d in self.topic_table.drain_deltas():
            await self._reconcile(d)
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        for t in self._move_tasks.values():
            t.cancel()
        self._move_tasks.clear()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                deltas = await self.topic_table.wait_for_deltas()
                for d in deltas:
                    try:
                        await self._reconcile(d)
                    except Exception:
                        logger.exception("reconcile failed for %s", d.ntp)
            except asyncio.CancelledError:
                return

    # ------------------------------------------------------------ reconcile
    def _assignment(self, ntp: NTP):
        md = self.topic_table.get(ntp.topic)
        if md is None:
            return None
        return md.assignments.get(ntp.partition)

    async def _reconcile(self, d: TopicDelta) -> None:
        me = self.self_node.id
        if d.type == DeltaType.added:
            pa = self._assignment(d.ntp) or d.assignment
            if pa is None or me not in pa.replicas:
                return
            await self._create_local(d.ntp, pa)
        elif d.type == DeltaType.removed:
            await self._remove_local(d.ntp)
        elif d.type == DeltaType.updated:
            pa = self._assignment(d.ntp)
            if pa is None:
                return
            if pa.moving_to is not None:
                await self._reconcile_move(d.ntp, pa)
            else:
                # move finished (or plain metadata update): drop our copy if
                # we are no longer a replica
                if me not in pa.replicas and self.pm.get(d.ntp) is not None:
                    await self._remove_local(d.ntp)

    def _log_overrides(self, ntp: NTP):
        md = self.topic_table.get(ntp.topic)
        if md is None:
            return None
        return md.config.log_overrides(self.gm.storage.log_mgr.config)

    async def _create_local(self, ntp: NTP, pa) -> None:
        if self.pm.get(ntp) is not None:
            return
        overrides = self._log_overrides(ntp)
        if pa.group < 0:
            # non-replicated (single-node direct log / materialized topic)
            await self.pm.manage(ntp, log_overrides=overrides)
            return
        if self.gm.consensus_for(pa.group) is None:
            voters = [VNode(r, 0) for r in pa.replicas]
            c = await self.gm.create_group(
                pa.group, ntp, voters, log_overrides=overrides
            )
            p = await Partition(ntp, c, c.log, kvs=self.pm.storage.kvs).start()
            self.pm.attach(ntp, p)

    async def _remove_local(self, ntp: NTP) -> None:
        t = self._move_tasks.pop(ntp, None)
        if t is not None:
            t.cancel()
        p = self.pm.get(ntp)
        if p is None:
            return
        consensus = getattr(p, "consensus", None)
        group = getattr(consensus, "group", None)
        if group is not None and self.gm.consensus_for(group) is not None:
            self.pm.detach(ntp)
            await self.gm.remove_group(group, delete_log=True)
        else:
            await self.pm.remove(ntp)
        if self.leaders is not None:
            self.leaders.remove(ntp)
        if self.shards is not None:
            self.shards.erase(ntp)

    async def _reconcile_move(self, ntp: NTP, pa) -> None:
        me = self.self_node.id
        target = pa.moving_to
        # 1. new replica: bootstrap the group locally with the OLD voter set;
        #    the leader's config change will add us and recovery catches us up
        if me in target and self.pm.get(ntp) is None:
            if self.gm.consensus_for(pa.group) is None:
                voters = [VNode(r, 0) for r in pa.replicas]
                c = await self.gm.create_group(
                    pa.group, ntp, voters, log_overrides=self._log_overrides(ntp)
                )
                p = await Partition(ntp, c, c.log, kvs=self.pm.storage.kvs).start()
                self.pm.attach(ntp, p)
        # 2. current leader: run the joint-consensus change + finish
        c = self.gm.consensus_for(pa.group)
        if c is not None and c.is_leader() and ntp not in self._move_tasks:
            self._move_tasks[ntp] = asyncio.create_task(
                self._drive_move(ntp, pa.group, list(target))
            )

    async def _drive_move(self, ntp: NTP, group: int, target: list[int]) -> None:
        """Retry until the move lands or this node stops leading: a single
        change_configuration can time out while the destination node is
        still bootstrapping the group, and nothing else re-kicks the move."""
        try:
            while True:
                c = self.gm.consensus_for(group)
                pa = self._assignment(ntp)
                if c is None or pa is None or pa.moving_to is None:
                    return  # move finished or partition gone
                if not c.is_leader():
                    return  # new leader's backend takes over via notification
                try:
                    cfg = c.config()
                    already = cfg.old_voters is None and sorted(
                        v.id for v in cfg.voters
                    ) == sorted(target)
                    if not already:
                        await c.change_configuration([VNode(r, 0) for r in target])
                    if self._finish_move is not None:
                        await self._finish_move(ntp, target)
                    return
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.warning(
                        "replica move attempt failed for %s -> %s; retrying",
                        ntp, target, exc_info=True,
                    )
                    await asyncio.sleep(0.5)
        finally:
            self._move_tasks.pop(ntp, None)
