"""Cluster controller: raft group 0 + command STM + frontends.

Parity with cluster/controller.h:31-79: one raft group (id 0, ntp
{redpanda/controller/0}) spanning the seed brokers replicates typed
``Command`` batches; every node's ``ControllerStm`` (a mux state machine,
controller_stm.h) applies them to the same in-memory tables
(topic_table, members_table, credential/acl stores), and each node's
``ControllerBackend`` (controller_backend.py) reconciles the deltas into
local partitions. Frontends (topics_frontend, members_frontend,
security_frontend) build commands and ``replicate_and_wait`` them,
forwarding to the current controller leader when invoked elsewhere
(cluster/service.cc forwarding pattern).
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.cluster import commands as cmds
from redpanda_tpu.cluster.allocator import PartitionAllocator
from redpanda_tpu.cluster.commands import Command, CommandType
from redpanda_tpu.cluster.members import Broker, MembersTable, MembershipState
from redpanda_tpu.cluster.topic_table import (
    PartitionAssignment,
    TopicConfig,
    TopicTable,
)
from redpanda_tpu.models.fundamental import NTP, INTERNAL_NAMESPACE, NodeId
from redpanda_tpu.models.record import RecordBatchType
from redpanda_tpu.raft.state_machine import MuxStateMachine
from redpanda_tpu.raft.types import ConsistencyLevel, Errc, RaftError, VNode

logger = logging.getLogger("rptpu.cluster.controller")

CONTROLLER_GROUP = 0
CONTROLLER_NTP = NTP(INTERNAL_NAMESPACE, "controller", 0)


class ClusterError(Exception):
    def __init__(self, msg: str, *, retriable: bool = False) -> None:
        super().__init__(msg)
        self.retriable = retriable


class NotControllerError(ClusterError):
    def __init__(self, leader: NodeId | None) -> None:
        super().__init__(f"not the controller leader (leader={leader})", retriable=True)
        self.leader = leader


class TopicExistsError(ClusterError):
    """Typed so the RPC/dispatcher layers map it to the single-node
    contract (ValueError from topic_table.add_topic) instead of pattern-
    matching error strings."""

    def __init__(self, name: str) -> None:
        super().__init__(f"topic exists: {name}")
        self.topic = name


class ControllerStm(MuxStateMachine):
    """Applies replicated commands to the node-local tables.

    Mirrors controller_stm.h's mux over {topic_updates_dispatcher,
    members_manager, security_manager, data_policy_manager}; security and
    data-policy applies are pluggable callbacks so those layers attach
    without a dependency cycle.
    """

    def __init__(self, controller: "Controller", consensus) -> None:
        handlers = {
            RecordBatchType.topic_management_cmd: self._apply_cmd_batch,
            RecordBatchType.user_management_cmd: self._apply_cmd_batch,
            RecordBatchType.acl_management_cmd: self._apply_cmd_batch,
            RecordBatchType.node_management_cmd: self._apply_cmd_batch,
            RecordBatchType.data_policy_management_cmd: self._apply_cmd_batch,
        }
        super().__init__(consensus, handlers)
        self.controller = controller
        # offset -> error string, so replicate_and_wait can surface apply
        # failures to the caller instead of reporting false success
        # (bounded: controller command rates are tiny)
        self._apply_errors: dict[int, str] = {}

    def error_at(self, offset: int) -> str | None:
        return self._apply_errors.get(offset)

    async def _apply_cmd_batch(self, batch) -> None:
        for rec in batch.records():
            try:
                cmd = Command.from_record(rec)
            except Exception:
                logger.exception("undecodable controller command, skipping")
                self._record_error(batch.last_offset, "undecodable command")
                continue
            try:
                await self.controller.apply_command(cmd)
            except Exception as e:
                # Apply must never wedge the loop; a deterministic command
                # that fails here fails identically on every node — record
                # it so the issuing frontend can report the failure.
                logger.exception("controller command apply failed: %s", cmd.type)
                self._record_error(batch.last_offset, f"{cmd.type.name}: {e}")

    def _record_error(self, offset: int, msg: str) -> None:
        self._apply_errors[offset] = msg
        if len(self._apply_errors) > 1024:
            for k in sorted(self._apply_errors)[:512]:
                del self._apply_errors[k]


class Controller:
    def __init__(
        self,
        self_node: VNode,
        group_manager,  # raft.GroupManager
        connection_cache,  # rpc.ConnectionCache
    ) -> None:
        self.self_node = self_node
        self.gm = group_manager
        self.connections = connection_cache
        self.topic_table = TopicTable()
        self.members = MembersTable()
        self.allocator = PartitionAllocator()
        self.consensus = None
        self.stm: ControllerStm | None = None
        self._next_group = CONTROLLER_GROUP + 1
        # pluggable appliers: CommandType -> async callable(cmd)
        self._extra_appliers: dict[CommandType, object] = {}
        # strong refs for background fibers (drain watchers): the loop only
        # holds weak refs, so an unreferenced task can be GC'd mid-flight
        self._bg_tasks: set[asyncio.Task] = set()
        # keep connection cache in sync with membership
        self.members.register_change_callback(self._on_member_change)

    # ------------------------------------------------------------ wiring
    def register_applier(self, types: list[CommandType], fn) -> None:
        """Attach an apply function for command types owned by another
        subsystem (security, data policy)."""
        for t in types:
            self._extra_appliers[t] = fn

    def _on_member_change(self, b: Broker) -> None:
        if b.node_id == self.self_node.id:
            return
        if b.state == MembershipState.removed:
            # deferred close happens inside the cache on next touch
            pass
        else:
            self.connections.register(b.node_id, b.host, b.port)

    # ------------------------------------------------------------ lifecycle
    async def start(self, seed_nodes: list[VNode]) -> "Controller":
        """Create/join raft0 across the seed set (controller.cc bootstrap:
        every seed broker starts group 0 with the same voter set)."""
        self.consensus = await self.gm.create_group(
            CONTROLLER_GROUP, CONTROLLER_NTP, seed_nodes
        )
        self.stm = ControllerStm(self, self.consensus)
        await self.stm.start()
        return self

    async def stop(self) -> None:
        for t in list(self._bg_tasks):
            t.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        self._bg_tasks.clear()
        if self.stm is not None:
            await self.stm.stop()
            self.stm = None

    # ------------------------------------------------------------ state
    def is_leader(self) -> bool:
        return self.consensus is not None and self.consensus.is_leader()

    @property
    def leader_id(self) -> NodeId | None:
        return self.consensus.leader_id if self.consensus else None

    async def wait_for_leader(self, timeout: float = 8.0) -> NodeId:
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            lid = self.leader_id
            if lid is not None:
                return lid
            if asyncio.get_running_loop().time() > deadline:
                raise ClusterError("no controller leader", retriable=True)
            await asyncio.sleep(0.05)

    # ------------------------------------------------------------ replicate
    async def replicate_and_wait(self, cmd: Command, timeout: float = 10.0) -> None:
        """Leader: replicate with quorum ack and wait until OUR stm applied
        it. Non-leader: raise NotControllerError (the cluster service /
        frontends forward)."""
        if not self.is_leader():
            raise NotControllerError(self.leader_id)
        try:
            res = await self.consensus.replicate(
                [cmd.to_batch()], ConsistencyLevel.quorum_ack
            )
        except RaftError as e:
            if e.errc == Errc.not_leader:
                raise NotControllerError(self.leader_id) from e
            raise ClusterError(str(e), retriable=True) from e
        await self.stm.wait_applied(res.last_offset, timeout)
        err = self.stm.error_at(res.last_offset)
        if err is not None:
            raise ClusterError(f"command apply failed: {err}")

    # ------------------------------------------------------------ apply
    async def apply_command(self, cmd: Command) -> None:
        d = cmd.data
        t = cmd.type
        if t == CommandType.create_topic:
            cfg = TopicConfig(name=d["config"]["name"], partition_count=0)
            for k, v in d["config"].get("overrides", {}).items():
                cfg.apply_override(k, v)
            cfg.replication_factor = int(d["config"].get("replication_factor", 1))
            cfg.ns = d["config"].get("ns", cfg.ns)
            assignments = [self._pa(a) for a in d["assignments"]]
            self.topic_table.apply_create(cfg, assignments)
            self._track_groups(assignments)
            for pa in assignments:
                self.allocator.note_allocated(pa.replicas)
        elif t == CommandType.delete_topic:
            md = self.topic_table.remove_topic(d["topic"])
            for pa in md.assignments.values():
                self.allocator.deallocate(pa.replicas)
        elif t == CommandType.create_partition:
            assignments = [self._pa(a) for a in d["assignments"]]
            self.topic_table.apply_add_partitions(d["topic"], assignments)
            self._track_groups(assignments)
            for pa in assignments:
                self.allocator.note_allocated(pa.replicas)
        elif t == CommandType.update_topic_properties:
            self.topic_table.update_properties(d["topic"], d["overrides"])
        elif t == CommandType.move_partition_replicas:
            self.topic_table.begin_move(
                NTP(d["ns"], d["topic"], d["partition"]), d["replicas"]
            )
        elif t == CommandType.finish_moving_partition_replicas:
            ntp = NTP(d["ns"], d["topic"], d["partition"])
            md = self.topic_table.get(ntp.topic)
            old = (
                list(md.assignments[ntp.partition].replicas)
                if md and ntp.partition in md.assignments
                else []
            )
            self.topic_table.finish_move(ntp, d["replicas"])
            new = list(d["replicas"])
            self.allocator.note_allocated([r for r in new if r not in old])
            self.allocator.deallocate([r for r in old if r not in new])
        elif t == CommandType.create_non_replicable_topic:
            src = self.topic_table.get(d["source_topic"])
            if src is None:
                raise ClusterError(f"source topic missing: {d['source_topic']}")
            cfg = TopicConfig(
                name=d["name"], partition_count=0, ns=src.config.ns,
                replication_factor=1,
            )
            # materialized topics mirror the source's partitioning but are
            # NOT raft-replicated (coproc writes bypass raft) — group -1
            assignments = [
                PartitionAssignment(
                    NTP(cfg.ns, cfg.name, pa.ntp.partition), list(pa.replicas), group=-1
                )
                for pa in src.assignments.values()
            ]
            self.topic_table.apply_create(cfg, assignments)
        elif t == CommandType.register_node:
            self.members.apply_register(
                Broker(
                    d["node_id"], d["host"], d["port"],
                    d.get("kafka_host", d["host"]), d.get("kafka_port", 9092),
                    admin_port=d.get("admin_port", 0),
                )
            )
            self.allocator.register_node(d["node_id"])
        elif t == CommandType.decommission_node:
            self.members.apply_state(d["node_id"], MembershipState.draining)
            self.allocator.decommission_node(d["node_id"])
        elif t == CommandType.recommission_node:
            self.members.apply_state(d["node_id"], MembershipState.active)
            self.allocator.recommission_node(d["node_id"])
        elif t == CommandType.finish_reallocations:
            self.members.apply_state(d["node_id"], MembershipState.removed)
            self.allocator.unregister_node(d["node_id"])
        elif t in self._extra_appliers:
            await self._extra_appliers[t](cmd)
        else:
            logger.warning("no applier for controller command %s", t)

    def _pa(self, a: dict) -> PartitionAssignment:
        return PartitionAssignment(
            NTP(a["ns"], a["topic"], a["partition"]), list(a["replicas"]),
            leader=None, group=a.get("group", -1),
        )

    def _track_groups(self, assignments: list[PartitionAssignment]) -> None:
        for pa in assignments:
            if pa.group >= self._next_group:
                self._next_group = pa.group + 1

    # ------------------------------------------------------------ topics frontend
    async def create_topic(self, cfg: TopicConfig) -> None:
        if not self.is_leader():
            raise NotControllerError(self.leader_id)
        if self.topic_table.contains(cfg.name):
            raise TopicExistsError(cfg.name)
        replica_sets = self.allocator.allocate(
            cfg.partition_count, cfg.replication_factor
        )
        assignments = []
        for p, replicas in enumerate(replica_sets):
            ntp = NTP(cfg.ns, cfg.name, p)
            assignments.append(
                cmds.assignment_payload(ntp, self._alloc_group(), replicas)
            )
        overrides = {k: v for k, v in cfg.config_map().items() if v is not None}
        # concurrent same-name creates that both pass the contains() check
        # apply as first-wins no-ops (see topic_table.apply_create), so the
        # loser observes success with the winner's assignments
        await self.replicate_and_wait(
            cmds.create_topic_cmd(
                {
                    "name": cfg.name,
                    "ns": cfg.ns,
                    "replication_factor": cfg.replication_factor,
                    "overrides": overrides,
                },
                assignments,
            )
        )

    def _alloc_group(self) -> int:
        g = self._next_group
        self._next_group += 1
        return g

    async def delete_topic(self, name: str, ns: str = "kafka") -> None:
        if not self.topic_table.contains(name):
            raise ClusterError(f"unknown topic: {name}")
        await self.replicate_and_wait(cmds.delete_topic_cmd(ns, name))

    async def create_partitions(self, name: str, new_total: int) -> None:
        md = self.topic_table.get(name)
        if md is None:
            raise ClusterError(f"unknown topic: {name}")
        if new_total <= md.config.partition_count:
            raise ClusterError("partition count can only grow")
        n_new = new_total - md.config.partition_count
        replica_sets = self.allocator.allocate(n_new, md.config.replication_factor)
        assignments = []
        for i, replicas in enumerate(replica_sets):
            p = md.config.partition_count + i
            ntp = NTP(md.config.ns, name, p)
            assignments.append(
                cmds.assignment_payload(ntp, self._alloc_group(), replicas)
            )
        await self.replicate_and_wait(
            cmds.create_partition_cmd(md.config.ns, name, assignments)
        )

    async def update_topic_properties(self, name: str, overrides: dict) -> None:
        if not self.topic_table.contains(name):
            raise ClusterError(f"unknown topic: {name}")
        await self.replicate_and_wait(
            cmds.update_topic_properties_cmd("kafka", name, overrides)
        )

    async def move_partition_replicas(self, ntp: NTP, replicas: list[NodeId]) -> None:
        md = self.topic_table.get(ntp.topic)
        if md is None or ntp.partition not in md.assignments:
            raise ClusterError(f"unknown partition: {ntp}")
        for r in replicas:
            if not self.members.contains(r) and r != self.self_node.id:
                raise ClusterError(f"unknown node: {r}")
        await self.replicate_and_wait(cmds.move_partition_replicas_cmd(ntp, replicas))

    async def finish_move(self, ntp: NTP, replicas: list[NodeId]) -> None:
        await self.replicate_and_wait(cmds.finish_moving_cmd(ntp, replicas))

    async def create_non_replicable_topic(
        self, source: str, name: str, ns: str = "kafka"
    ) -> None:
        if self.topic_table.contains(name):
            return  # idempotent: coproc recreates on redeploy
        await self.replicate_and_wait(
            cmds.create_non_replicable_topic_cmd(ns, source, name)
        )

    # ------------------------------------------------------------ members frontend
    async def register_broker(self, b: Broker) -> None:
        await self.replicate_and_wait(
            cmds.register_node_cmd(
                b.node_id, b.host, b.port, b.kafka_host, b.kafka_port,
                admin_port=b.admin_port,
            )
        )

    async def decommission_node(self, node_id: NodeId) -> None:
        if not self.members.contains(node_id):
            raise ClusterError(f"unknown node: {node_id}")
        # validate BEFORE replicating anything: every replica on the node
        # must have somewhere to go, or the cluster would be left half-
        # drained (the reference refuses with "not enough nodes")
        survivors = sum(
            1
            for b in self.members.all_brokers()
            if b.node_id != node_id and b.state.name == "active"
        )
        for md in self.topic_table.topics().values():
            for pa in md.assignments.values():
                if node_id in pa.replicas and pa.group >= 0:
                    if survivors < len(pa.replicas):
                        raise ClusterError(
                            f"cannot decommission node {node_id}: "
                            f"{pa.ntp} needs {len(pa.replicas)} replicas but "
                            f"only {survivors} active nodes would remain"
                        )
        await self.replicate_and_wait(cmds.decommission_node_cmd(node_id))
        # kick replica drain: every partition hosted on the node gets a
        # move command to a reallocated set (members_backend semantics)
        for md in self.topic_table.topics().values():
            for pa in md.assignments.values():
                if node_id in pa.replicas and pa.group >= 0:
                    new_set = self.allocator.reallocate_replica(pa.replicas, node_id)
                    await self.replicate_and_wait(
                        cmds.move_partition_replicas_cmd(pa.ntp, new_set)
                    )
        # watch the drain and seal it with finish_reallocations so the node
        # transitions draining -> removed (members_backend completion)
        t = asyncio.create_task(self._watch_drain(node_id))
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    def _node_is_drained(self, node_id: NodeId) -> bool:
        for md in self.topic_table.topics().values():
            for pa in md.assignments.values():
                if node_id in pa.replicas or (
                    pa.moving_to is not None and node_id in pa.moving_to
                ):
                    return False
        return True

    async def _watch_drain(self, node_id: NodeId, timeout: float = 120.0) -> None:
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            if not self.is_leader():
                return  # the new leader's operator re-drives; state is replicated
            if self._node_is_drained(node_id):
                try:
                    await self.replicate_and_wait(
                        Command(CommandType.finish_reallocations, {"node_id": node_id})
                    )
                except ClusterError:
                    logger.exception("finish_reallocations failed for node %d", node_id)
                return
            await asyncio.sleep(0.25)
        logger.warning("drain of node %d did not finish within %ss", node_id, timeout)

    async def recommission_node(self, node_id: NodeId) -> None:
        await self.replicate_and_wait(cmds.recommission_node_cmd(node_id))
