"""Cross-node transaction gateway: marker fan-out + staged-offset routing.

The reference's tx_gateway (tx_gateway.json, cluster/tx_gateway_frontend.cc)
lets the transaction coordinator finish a transaction whose data partitions
and consumer groups live on OTHER brokers: commit/abort control markers go
to each partition LEADER, and staged group offsets go to the GROUP
coordinator. Without it, EOS only works when everything is co-located on
one broker.

Two RPC methods on the internal mesh, plus a router the TxCoordinator uses:

- ``tx_marker``: write the control marker through the leader's rm_stm
  (rm_stm prepare/commit/abort batches, rm_stm.cc).
- ``tx_group_offsets``: fold a committed transaction's staged offsets into
  group state on the group coordinator (group_commit_tx semantics).

The router resolves leadership from the metadata cache and falls back to
local execution when the target is this broker — the single-node path has
zero RPC overhead and identical semantics.
"""

from __future__ import annotations

import json
import logging

from redpanda_tpu import rpc
from redpanda_tpu.rpc import serde

logger = logging.getLogger("rptpu.cluster.txgw")

TX_MARKER_REQUEST = serde.S(
    ("topic", serde.STRING),
    ("partition", serde.I32),
    ("pid", serde.I64),
    ("epoch", serde.I32),
    ("commit", serde.I32),
)
TX_MARKER_REPLY = serde.S(("errc", serde.I32))  # kafka ErrorCode value
TX_GROUP_OFFSETS_REQUEST = serde.S(
    ("group_id", serde.STRING),
    ("commits_json", serde.BYTES),
)
TX_GROUP_OFFSETS_REPLY = serde.S(("errc", serde.I32))

tx_gateway_service = rpc.ServiceDef(
    "cluster",
    "tx_gateway",
    [
        rpc.MethodDef("tx_begin", TX_MARKER_REQUEST, TX_MARKER_REPLY),
        rpc.MethodDef("tx_marker", TX_MARKER_REQUEST, TX_MARKER_REPLY),
        rpc.MethodDef(
            "tx_group_offsets", TX_GROUP_OFFSETS_REQUEST, TX_GROUP_OFFSETS_REPLY
        ),
    ],
)

_UNKNOWN_SERVER_ERROR = -1
_NOT_LEADER = 6
_COORDINATOR_NOT_AVAILABLE = 15


def encode_commits(commits: dict) -> bytes:
    """dict[(topic, partition) -> OffsetCommit] -> wire JSON."""
    return json.dumps([
        {
            "topic": t,
            "partition": p,
            "offset": oc.offset,
            "leader_epoch": oc.leader_epoch,
            "metadata": oc.metadata,
        }
        for (t, p), oc in commits.items()
    ]).encode()


def decode_commits(blob: bytes) -> dict:
    from redpanda_tpu.kafka.server.group import OffsetCommit

    return {
        (d["topic"], d["partition"]): OffsetCommit(
            d["offset"], d.get("leader_epoch", -1), d.get("metadata")
        )
        for d in json.loads(blob.decode())
    }


class TxGatewayService:
    """Server side, bound on every broker."""

    def __init__(self, broker) -> None:
        self.broker = broker

    def register(self, protocol: rpc.SimpleProtocol) -> None:
        protocol.register_service(rpc.ServiceHandler(tx_gateway_service, self))

    async def tx_begin(self, req: dict) -> dict:
        """rm_stm.begin_tx on the partition leader (AddPartitionsToTxn)."""
        p = self.broker.get_partition(req["topic"], req["partition"])
        if p is None or not p.is_leader():
            return {"errc": _NOT_LEADER}
        try:
            rm = await self.broker.recovered_rm_stm(p)
            return {"errc": int(rm.begin_tx(req["pid"], req["epoch"]))}
        except Exception:
            logger.exception("tx_begin failed for %s/%d", req["topic"], req["partition"])
            return {"errc": _UNKNOWN_SERVER_ERROR}

    async def tx_marker(self, req: dict) -> dict:
        p = self.broker.get_partition(req["topic"], req["partition"])
        if p is None or not p.is_leader():
            return {"errc": _NOT_LEADER}
        try:
            rm = await self.broker.recovered_rm_stm(p)
            code = await rm.end_tx(req["pid"], req["epoch"], bool(req["commit"]))
            return {"errc": int(code)}
        except Exception:
            logger.exception("tx_marker failed for %s/%d", req["topic"], req["partition"])
            return {"errc": _UNKNOWN_SERVER_ERROR}

    async def tx_group_offsets(self, req: dict) -> dict:
        gm = self.broker.group_coordinator
        group_id = req["group_id"]
        await gm.start()
        if not gm.is_coordinator(group_id):
            return {"errc": _COORDINATOR_NOT_AVAILABLE}
        try:
            commits = decode_commits(req["commits_json"])
            code = await gm.commit_offsets(group_id, "", -1, commits, trusted=True)
            return {"errc": int(code)}
        except Exception:
            logger.exception("tx_group_offsets failed for group %s", group_id)
            return {"errc": _UNKNOWN_SERVER_ERROR}


class TxRouter:
    """Coordinator-side routing: local fast path, RPC to the owner else.

    ``None`` router members (standalone broker) degrade to local-only —
    exactly the previous behavior."""

    def __init__(self, broker, metadata_cache=None, connections=None) -> None:
        self.broker = broker
        self.mdc = metadata_cache
        self.connections = connections

    def _leader_for(self, topic: str, partition: int):
        if self.mdc is None:
            return None
        from redpanda_tpu.models.fundamental import NTP

        return self.mdc.get_leader(NTP.kafka(topic, partition))

    async def _route(
        self, method: str, topic: str, partition: int, pid: int, epoch: int,
        commit: bool = False,
    ) -> int:
        leader = self._leader_for(topic, partition)
        if leader is None or self.connections is None:
            return _NOT_LEADER
        client = rpc.Client(tx_gateway_service, self.connections.get(leader))
        reply = await getattr(client, method)(
            {
                "topic": topic,
                "partition": partition,
                "pid": pid,
                "epoch": epoch,
                "commit": int(commit),
            },
            timeout=10.0,
        )
        return reply["errc"]

    async def begin_tx(
        self, topic: str, partition: int, pid: int, epoch: int
    ) -> int:
        p = self.broker.get_partition(topic, partition)
        if p is not None and p.is_leader():
            rm = await self.broker.recovered_rm_stm(p)
            return int(rm.begin_tx(pid, epoch))
        return await self._route("tx_begin", topic, partition, pid, epoch)

    async def write_marker(
        self, topic: str, partition: int, pid: int, epoch: int, commit: bool
    ) -> int:
        """Returns a kafka ErrorCode VALUE; negative/6/15 are retriable by
        the coordinator's prepare_* re-drive loop."""
        p = self.broker.get_partition(topic, partition)
        if p is not None and p.is_leader():
            rm = await self.broker.recovered_rm_stm(p)
            return int(await rm.end_tx(pid, epoch, commit))
        return await self._route("tx_marker", topic, partition, pid, epoch, commit)

    async def commit_group_offsets(self, group_id: str, commits: dict) -> int:
        gm = self.broker.group_coordinator
        await gm.start()
        if gm.is_coordinator(group_id):
            return int(
                await gm.commit_offsets(group_id, "", -1, commits, trusted=True)
            )
        if self.mdc is None or self.connections is None:
            return _COORDINATOR_NOT_AVAILABLE
        from redpanda_tpu.kafka.server.group_manager import GROUP_TOPIC

        leader = self._leader_for(GROUP_TOPIC, gm.partition_for(group_id))
        if leader is None:
            return _COORDINATOR_NOT_AVAILABLE
        client = rpc.Client(tx_gateway_service, self.connections.get(leader))
        reply = await client.tx_group_offsets(
            {"group_id": group_id, "commits_json": encode_commits(commits)},
            timeout=10.0,
        )
        return reply["errc"]
