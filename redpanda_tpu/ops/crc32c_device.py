"""CRC-32C on TPU: one MXU matmul + a per-record unwind.

CRC is linear over GF(2): after processing R bytes, the state is

    s_R = A^R(s_0)  XOR  Lin(message)

where A is the one-byte shift matrix and ``Lin`` is a fixed linear map of the
message bits — i.e. a 0/1 matrix W of shape [R*8, 32]. Zero bytes contribute
nothing to Lin, so right-padding rows to R leaves Lin untouched, and the true
state at each record's actual length n is recovered by multiplying with
A^-(R-n) (gathered from a precomputed table).

So CRC-32C of N padded records = bit-unpack -> [N, R*8] @ W (MXU, bf16 in /
f32 accumulate, exact for 0/1 data) -> mod 2 -> XOR constant -> unwind ->
final xor. Everything is static-shaped and fuses under jit; this is the
batched kernel the produce path, recovery scan, and coproc engine share
(reference call sites: kafka_batch_adapter.cc:93, parser.cc:159,
record_utils.cc:82 — each a scalar per-batch CRC there, one [P*B] kernel
here).
"""

from __future__ import annotations

import functools

import numpy as np

from redpanda_tpu.hashing.crc32c import TABLE
from redpanda_tpu.ops import gf2


# ------------------------------------------------------------ host precompute
@functools.lru_cache(maxsize=16)
def _plan(r: int):
    """Precompute (W bits [r*8, 32], K_R const, unwind table [r+1, 32])."""
    a = gf2.byte_matrix()
    # Column images of T for each bit of a byte.
    tcols = np.array([TABLE[1 << m] for m in range(8)], dtype=np.uint32)  # [8]
    # W rows: byte position p (0-based), bit m -> A^(r-1-p)(T[2^m]).
    # Build by iterating p from r-1 down to 0, applying A as we go up.
    w_vals = np.zeros((r, 8), dtype=np.uint32)
    cur = tcols.copy()  # A^0 applied
    for p in range(r - 1, -1, -1):
        w_vals[p] = cur
        cur = _apply_many(a, cur)
    w_bits = ((w_vals.reshape(r * 8, 1) >> np.arange(32, dtype=np.uint32)) & 1).astype(np.uint8)
    # K_R = A^r(0xFFFFFFFF)
    k_r = int(0xFFFFFFFF)
    a_r = gf2.mat_pow(a, r)
    k_r = gf2.mat_apply(a_r, k_r)
    # Unwind: A^-k for k = 0..r, stored as column sets.
    ainv = gf2.mat_inv(a)
    unwind = np.zeros((r + 1, 32), dtype=np.uint32)
    cur_m = gf2.identity_mat()
    for k in range(r + 1):
        unwind[k] = cur_m
        cur_m = _mul(ainv, cur_m)
    return w_bits, np.uint32(k_r), unwind


def _apply_many(m: np.ndarray, xs: np.ndarray) -> np.ndarray:
    """Apply columns-matrix m to a batch of uint32 values."""
    bits = ((xs[:, None] >> np.arange(32, dtype=np.uint32)) & 1).astype(bool)  # [K, 32]
    return np.bitwise_xor.reduce(np.where(bits, m[None, :], np.uint32(0)), axis=1)


def _mul(m2: np.ndarray, m1: np.ndarray) -> np.ndarray:
    return _apply_many(m2, m1)


# ------------------------------------------------------------ device kernel
@functools.lru_cache(maxsize=16)
def make_crc_fn(r: int):
    """Build a jitted fn(data uint8 [N, r], lengths int32 [N]) -> uint32 [N]."""
    import jax
    import jax.numpy as jnp

    w_bits, k_r, unwind = _plan(r)
    w_dev = jnp.asarray(w_bits, dtype=jnp.bfloat16)  # [r*8, 32]
    unwind_dev = jnp.asarray(unwind)  # [r+1, 32] uint32
    k_r_dev = jnp.uint32(k_r)

    @jax.jit
    def crc_fn(data, lengths):
        n = data.shape[0]
        # Zero out bytes beyond each record's length: the GF(2) linear part
        # only ignores padding if the padding is zero.
        valid = jnp.arange(r, dtype=jnp.int32)[None, :] < lengths[:, None]
        data = jnp.where(valid, data, jnp.uint8(0))
        # bit-unpack: [N, r] uint8 -> [N, r*8] (bit m of byte p at p*8+m)
        bits = (data[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
        bits = bits.reshape(n, r * 8).astype(jnp.bfloat16)
        # MXU: exact 0/1 matmul with f32 accumulation.
        counts = jax.lax.dot_general(
            bits,
            w_dev,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lin_bits = counts.astype(jnp.int32) & 1  # [N, 32]
        lin = jnp.sum(
            lin_bits.astype(jnp.uint32) << jnp.arange(32, dtype=jnp.uint32), axis=1
        ).astype(jnp.uint32)
        s_r = lin ^ k_r_dev
        # Unwind trailing zeros: s_n = A^-(r - len)(s_R)
        k = jnp.clip(r - jnp.clip(lengths, 0, r), 0, r)
        cols = unwind_dev[k]  # [N, 32]
        sbits = ((s_r[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1).astype(bool)
        picked = jnp.where(sbits, cols, jnp.uint32(0))
        # XOR-reduce the 32 picked columns in 5 halving rounds.
        v = picked
        for _ in range(5):
            v = v[:, 0::2] ^ v[:, 1::2]
        s_n = v[:, 0]
        return s_n ^ jnp.uint32(0xFFFFFFFF)

    return crc_fn


def crc32c_device(data, lengths):
    """CRC-32C of N zero-padded records on the default backend.

    data: uint8 [N, R] (or any leading shape collapsible to N), lengths int32.
    """
    import jax.numpy as jnp

    data = jnp.asarray(data, dtype=jnp.uint8)
    lengths = jnp.asarray(lengths, dtype=jnp.int32)
    lead = data.shape[:-1]
    r = data.shape[-1]
    fn = make_crc_fn(r)
    flat = fn(data.reshape(-1, r), lengths.reshape(-1))
    return flat.reshape(lead)
