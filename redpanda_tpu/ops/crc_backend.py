"""Produce-path CRC validation backend: a measured adapter-boundary choice.

The reference verifies the Kafka CRC-32C of every produced batch inline in
its wire adapter (kafka_batch_adapter.cc:93-121, castagnoli over
attributes..records). SURVEY §7 phase 3 planned to swap that call site for a
TPU kernel; this module is where the swap would happen — and where the
measurements say it must not, on tunneled devices:

- The MXU CRC kernel (ops/crc32c_device.py) is bit-exact but needs the wire
  bytes ON DEVICE; the produce path's bytes arrive on the host NIC, so the
  kernel's cost includes shipping every region across the device link.
- Measured on the axon tunnel (BENCH_r03/r04, tools/link_probe.py): device
  validation lands at ~0.05x of ONE host core running the native SSE4.2
  loop (native/redpanda_native.cc rp_crc32c, ~1.5 GB/s); the link moves
  ~15-70 MB/s. The device can never win by >20x deficit on bandwidth alone.

So the adapter boundary *chooses per process*: `CrcBackend.pick()` probes
both paths once on representative rows and selects the faster one; on
co-located hardware (PCIe/ICI, where bytes may already be device-resident)
the device path can win and is selected automatically. The produce handler
(kafka/server/handlers.py) and the bench (config 1) consume this decision
instead of hard-coding either side.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from redpanda_tpu.hashing.crc32c import crc32c


@dataclass
class CrcDecision:
    backend: str  # "host" | "device"
    host_batches_per_sec: float
    device_batches_per_sec: float

    @property
    def ratio_device_vs_host(self) -> float:
        return self.device_batches_per_sec / max(self.host_batches_per_sec, 1e-9)


class CrcBackend:
    """Validate claimed batch CRCs over many batches, host or device."""

    def __init__(self, backend: str = "host", decision: CrcDecision | None = None):
        assert backend in ("host", "device")
        self.backend = backend
        self.decision = decision
        self._validators: dict[int, object] = {}

    # ------------------------------------------------------------ validate
    def validate(self, regions: list[bytes], claimed) -> np.ndarray:
        """ok[i] = crc32c(regions[i]) == claimed[i]."""
        claimed = np.asarray(claimed, dtype=np.uint32)
        if self.backend == "host":
            return np.fromiter(
                (crc32c(r) == int(c) for r, c in zip(regions, claimed)),
                dtype=bool,
                count=len(regions),
            )
        return self._validate_device(regions, claimed)

    def _validate_device(self, regions: list[bytes], claimed) -> np.ndarray:
        from redpanda_tpu.ops.packing import pack_rows
        from redpanda_tpu.ops.pipeline import make_batch_validator

        n = len(regions)
        r = max((len(x) for x in regions), default=1)
        r = 1 << (r - 1).bit_length()  # shape-bucketed stride
        rows, lens = pack_rows(regions, r)
        validate = self._validators.setdefault(r, make_batch_validator(r))
        return np.asarray(validate(rows, lens, claimed))[:n]

    # ------------------------------------------------------------ probing
    @classmethod
    def pick(
        cls,
        sample_regions: list[bytes] | None = None,
        reps: int = 3,
        probe_device: bool = True,
    ) -> "CrcBackend":
        """Measure both paths on sample rows; return the faster backend.

        Device probe failures (no device, no jax) fall back to host
        silently — correctness never depends on the device. With
        ``probe_device=False`` only the host rate is measured (a device
        probe costs a jit compile, ~20-40 s on a cold tunneled TPU — too
        much for broker startup; the bench records the full measurement
        every round instead).
        """
        if sample_regions is None:
            rng = np.random.default_rng(0)
            sample_regions = [rng.bytes(1536) for _ in range(64)]
        claimed = np.array([crc32c(r) for r in sample_regions], dtype=np.uint32)

        host = cls("host")
        t0 = time.perf_counter()
        for _ in range(reps):
            ok = host.validate(sample_regions, claimed)
        host_rate = reps * len(sample_regions) / (time.perf_counter() - t0)
        assert ok.all()

        dev = None
        dev_rate = 0.0
        if probe_device:
            try:
                dev = cls("device")
                dev.validate(sample_regions, claimed)  # compile off the clock
                t0 = time.perf_counter()
                for _ in range(reps):
                    ok = dev.validate(sample_regions, claimed)
                dev_rate = reps * len(sample_regions) / (time.perf_counter() - t0)
                if not ok.all():
                    raise RuntimeError("device CRC mismatch on probe rows")
            except Exception:
                dev = None
                dev_rate = 0.0

        decision = CrcDecision(
            "device" if dev_rate > host_rate else "host", host_rate, dev_rate
        )
        chosen = dev if (decision.backend == "device" and dev is not None) else cls("host")
        chosen.decision = decision
        return chosen


_default: CrcBackend | None = None
_default_lock = __import__("threading").Lock()


def default_backend() -> CrcBackend:
    """Process-wide backend for the produce path, probed lazily on first use.

    Device probing is opt-in via RP_CRC_PROBE_DEVICE=1 (see pick()); the
    measured comparison ships in every round's BENCH artifact (config 1).
    """
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                import os

                _default = CrcBackend.pick(
                    probe_device=os.environ.get("RP_CRC_PROBE_DEVICE") == "1"
                )
    return _default


async def default_backend_async() -> CrcBackend:
    """Async-safe accessor: the first call's probe (and, with
    RP_CRC_PROBE_DEVICE=1, a 20-40s device jit compile) runs in a worker
    thread so the event loop keeps serving raft heartbeats; later calls
    return the cached instance without a thread hop."""
    if _default is not None:
        return _default
    import asyncio

    return await asyncio.to_thread(default_backend)
