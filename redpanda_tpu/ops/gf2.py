"""GF(2) linear algebra over 32-bit CRC states (host-side precompute).

A CRC update by one byte is the affine map  s' = A(s) ^ T[b]  where
``A(s) = T[s & 0xFF] ^ (s >> 8)`` and T is the (linear) CRC table. Every
multi-byte update is therefore a GF(2) matrix acting on the 32-bit state,
which is what lets the device kernel express CRC-32C of thousands of records
as two 0/1 matmuls on the MXU (see crc32c_device.py).

Matrices are represented column-wise: ``M`` is a uint32[32] array with
``M[j] = M(e_j)`` (image of basis bit j). Applying M to a state XORs the
columns selected by the state's set bits.
"""

from __future__ import annotations

import numpy as np

from redpanda_tpu.hashing.crc32c import TABLE


def identity_mat() -> np.ndarray:
    return (np.uint32(1) << np.arange(32, dtype=np.uint32)).astype(np.uint32)


def mat_apply(m: np.ndarray, x: int) -> int:
    out = np.uint32(0)
    x = int(x)
    for j in range(32):
        if (x >> j) & 1:
            out ^= m[j]
    return int(out)


def mat_mul(m2: np.ndarray, m1: np.ndarray) -> np.ndarray:
    """(m2 @ m1): first apply m1, then m2."""
    return np.array([mat_apply(m2, int(c)) for c in m1], dtype=np.uint32)


def mat_pow(m: np.ndarray, k: int) -> np.ndarray:
    result = identity_mat()
    base = m.copy()
    while k:
        if k & 1:
            result = mat_mul(base, result)
        base = mat_mul(base, base)
        k >>= 1
    return result


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a 32x32 GF(2) matrix (columns representation) by Gaussian
    elimination on [M | I] expressed as 32 column bitmasks."""
    # Convert to row-major bit matrix: rows[i] bit j = bit i of column j.
    rows = np.zeros(32, dtype=np.uint64)  # each row: 64 bits = [M row | I row]
    for i in range(32):
        r = 0
        for j in range(32):
            if (int(m[j]) >> i) & 1:
                r |= 1 << j
        r |= 1 << (32 + i)  # identity part
        rows[i] = r
    # Forward elimination
    for col in range(32):
        pivot = None
        for r in range(col, 32):
            if (int(rows[r]) >> col) & 1:
                pivot = r
                break
        if pivot is None:
            raise ValueError("matrix not invertible")
        rows[col], rows[pivot] = rows[pivot], rows[col]
        for r in range(32):
            if r != col and (int(rows[r]) >> col) & 1:
                rows[r] ^= rows[col]
    # Extract inverse columns: inv rows are the right half.
    inv = np.zeros(32, dtype=np.uint32)
    for j in range(32):
        col = 0
        for i in range(32):
            if (int(rows[i]) >> (32 + j)) & 1:
                col |= 1 << i
        inv[j] = col
    return inv


def byte_matrix() -> np.ndarray:
    """A: the one-(zero-)byte state advance  A(s) = T[s & 0xFF] ^ (s >> 8)."""
    cols = np.zeros(32, dtype=np.uint32)
    for j in range(32):
        e = np.uint32(1) << np.uint32(j)
        cols[j] = TABLE[int(e) & 0xFF] ^ (e >> np.uint32(8))
    return cols


