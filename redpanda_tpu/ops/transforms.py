"""User map/filter transform DSL, compiled to jitted device functions.

The reference's coproc engine runs arbitrary user JS per record in a Node.js
sidecar (src/js/modules/public/Coprocessor.ts apply()); a TPU cannot run
arbitrary JS, and the TPU-first answer is not an interpreter but a
*declarative transform spec* compiled once into a fused XLA program that
processes every record of every partition in one launch:

    spec = filter_field_eq("level", "error") | map_project(
        Int("ts"), Str("msg", 64))
    fn = compile_transform(spec, r_in=1024)
    out, out_len, keep = fn(data, lengths)     # data: uint8 [N, r_in]

Semantics notes (documented limits of v1, see tests):
- JSON matching is canonical-form (no whitespace around ':'): field
  predicates compile to substring scans for '"key":'. Records are assumed
  to hold one JSON object per record value, as the reference's example
  transforms do.
- ``map_project`` emits a fixed-width binary struct per record ("flatbuffer"
  layout of the north-star config 4): int fields as little-endian int32,
  string fields as uint16 length + fixed-width padded bytes. Records missing
  a projected field are dropped (keep=False).

Every primitive is static-shape, branch-free, and vmap/shard_map friendly:
partitions ride the leading axis and shard over the mesh 'p' axis
(redpanda_tpu.parallel).
"""

from __future__ import annotations

import functools
import json
from dataclasses import dataclass
from typing import Sequence


# ----------------------------------------------------------------- spec types
@dataclass(frozen=True)
class Int:
    key: str


@dataclass(frozen=True)
class Str:
    key: str
    max_len: int = 64


@dataclass(frozen=True)
class Float:
    """Project a JSON number as little-endian float32 (4 bytes)."""

    key: str


@dataclass(frozen=True)
class Substr:
    """Project value[start : start+length] of a string field, padded."""

    key: str
    start: int
    length: int


@dataclass(frozen=True)
class Concat:
    """Project two string fields joined (a + b), truncated to max_len."""

    a: str
    b: str
    max_len: int = 64


@dataclass(frozen=True)
class _FilterContains:
    pattern: bytes
    negate: bool = False
    # Numeric-equality support: the byte following the match must not extend
    # the number (digit, '.', exponent char, sign), so '"code":42' does not
    # match {"code":420}.
    require_nonnum_suffix: bool = False


@dataclass(frozen=True)
class _MapProject:
    fields: tuple


@dataclass(frozen=True)
class _MapUppercase:
    pass


@dataclass(frozen=True)
class TransformSpec:
    """Filters (legacy raw-byte) and/or a predicate tree, plus one map.

    ``filters`` are v1 raw-payload substring ops (compiled to the payload
    device pipeline); ``where`` is a v2 field-anchored expression tree
    (redpanda_tpu.ops.exprs) compiled to the columnar pushdown path. The
    engine picks the execution mode per spec (coproc/column_plan.py).
    """

    filters: tuple = ()
    mapper: object = None
    name: str = "identity"
    where: object = None  # exprs.Expr | None

    def __or__(self, other: "TransformSpec") -> "TransformSpec":
        if self.mapper is not None and other.mapper is not None:
            raise ValueError("only one map stage per transform")
        w = self.where
        if other.where is not None:
            from redpanda_tpu.ops.exprs import And

            w = And(w, other.where) if w is not None else other.where
        return TransformSpec(
            filters=self.filters + other.filters,
            mapper=self.mapper or other.mapper,
            name=f"{self.name}|{other.name}",
            where=w,
        )

    # ------------------------------------------------------------- serde
    def to_json(self) -> str:
        """Wire form for deploy events (coproc internal topic)."""
        ops = []
        for f in self.filters:
            ops.append(
                {
                    "op": "filter_contains",
                    "pattern": f.pattern.decode("latin1"),
                    "negate": f.negate,
                    "nonnum_suffix": f.require_nonnum_suffix,
                }
            )
        if isinstance(self.mapper, _MapProject):
            fields = []
            for f in self.mapper.fields:
                if isinstance(f, Int):
                    fields.append({"kind": "int", "key": f.key})
                elif isinstance(f, Float):
                    fields.append({"kind": "float", "key": f.key})
                elif isinstance(f, Substr):
                    fields.append(
                        {"kind": "substr", "key": f.key, "start": f.start, "length": f.length}
                    )
                elif isinstance(f, Concat):
                    fields.append(
                        {"kind": "concat", "a": f.a, "b": f.b, "max_len": f.max_len}
                    )
                else:
                    fields.append({"kind": "str", "key": f.key, "max_len": f.max_len})
            ops.append({"op": "map_project", "fields": fields})
        elif isinstance(self.mapper, _MapUppercase):
            ops.append({"op": "map_uppercase"})
        doc = {"name": self.name, "ops": ops}
        if self.where is not None:
            doc["where"] = self.where.to_dict()
        return json.dumps(doc)

    @staticmethod
    def from_json(blob: str | bytes) -> "TransformSpec":
        doc = json.loads(blob)
        spec = TransformSpec(name=doc.get("name", "anon"))
        for op in doc.get("ops", []):
            kind = op["op"]
            if kind == "filter_contains":
                spec = spec | TransformSpec(
                    filters=(
                        _FilterContains(
                            op["pattern"].encode("latin1"),
                            op.get("negate", False),
                            op.get("nonnum_suffix", False),
                        ),
                    ),
                    name="",
                )
            elif kind == "map_project":
                fields = []
                for f in op["fields"]:
                    fk = f["kind"]
                    if fk == "int":
                        fields.append(Int(f["key"]))
                    elif fk == "float":
                        fields.append(Float(f["key"]))
                    elif fk == "substr":
                        fields.append(Substr(f["key"], f["start"], f["length"]))
                    elif fk == "concat":
                        fields.append(Concat(f["a"], f["b"], f["max_len"]))
                    else:
                        fields.append(Str(f["key"], f["max_len"]))
                spec = spec | TransformSpec(mapper=_MapProject(tuple(fields)), name="")
            elif kind == "map_uppercase":
                spec = spec | TransformSpec(mapper=_MapUppercase(), name="")
            else:
                raise ValueError(f"unknown transform op {kind!r}")
        w = None
        if "where" in doc:
            from redpanda_tpu.ops.exprs import Expr

            w = Expr.from_dict(doc["where"])
        return TransformSpec(spec.filters, spec.mapper, doc.get("name", "anon"), w)


# ----------------------------------------------------------------- public DSL
def identity() -> TransformSpec:
    return TransformSpec(name="identity")


def filter_contains(pattern: bytes, negate: bool = False) -> TransformSpec:
    return TransformSpec(filters=(_FilterContains(bytes(pattern), negate),), name="contains")


def filter_field_eq(key: str, value) -> TransformSpec:
    """Canonical-JSON field equality: substring match of '"key":<value>'."""
    nonnum = False
    if isinstance(value, str):
        pat = f'"{key}":"{value}"'
    elif isinstance(value, bool):
        pat = f'"{key}":{"true" if value else "false"}'
    else:
        pat = f'"{key}":{value}'
        nonnum = True  # prevent prefix matches like 42 matching 420
    return TransformSpec(
        filters=(_FilterContains(pat.encode(), require_nonnum_suffix=nonnum),),
        name=f"eq:{key}",
    )


def map_project(*fields) -> TransformSpec:
    return TransformSpec(mapper=_MapProject(tuple(fields)), name="project")


def map_uppercase() -> TransformSpec:
    return TransformSpec(mapper=_MapUppercase(), name="upper")


def where(expr) -> TransformSpec:
    """v2 predicate: a field-anchored expression tree (ops.exprs).

    Compiled to the columnar pushdown path: only referenced fields cross
    the device link, the device evaluates the tree, one bit returns per
    record. Combine with ``|`` like any other stage::

        where((field("level") == "error") & (field("code") >= 500))
            | map_project(Int("code"), Str("msg", 64))
    """
    from redpanda_tpu.ops.exprs import _as_expr

    return TransformSpec(where=_as_expr(expr), name="where")


def project_out_width(fields: Sequence) -> int:
    w = 0
    for f in fields:
        if isinstance(f, (Int, Float)):
            w += 4
        elif isinstance(f, Substr):
            w += 2 + f.length
        elif isinstance(f, Concat):
            w += 2 + f.max_len
        else:
            w += 2 + f.max_len
    return w


# ------------------------------------------------------------ device primitives
def _find_pattern(jnp, data, lengths, pat: bytes, require_nonnum_suffix: bool = False):
    """First start index of `pat` within each row's valid prefix, else -1.

    With require_nonnum_suffix, a match is only valid when the byte after it
    is not a number-continuation character (digit, '.', 'e', 'E', '+', '-')
    or the match ends exactly at the record's length.
    """
    n, r = data.shape
    l = len(pat)
    if l == 0 or l > r:
        return jnp.full((n,), -1, dtype=jnp.int32)
    w = r - l + 1
    match = jnp.ones((n, w), dtype=bool)
    for i, byte in enumerate(pat):
        match = match & (data[:, i : i + w] == jnp.uint8(byte))
    starts = jnp.arange(w, dtype=jnp.int32)
    match = match & (starts[None, :] <= (lengths - l)[:, None])
    if require_nonnum_suffix:
        # Byte at start+l for each start (0 for the final start, which is
        # past the row end).
        nxt = jnp.concatenate(
            [data[:, l:], jnp.zeros((n, 1), dtype=data.dtype)], axis=1
        )  # [N, w]
        is_num = (
            ((nxt >= ord("0")) & (nxt <= ord("9")))
            | (nxt == ord("."))
            | (nxt == ord("e"))
            | (nxt == ord("E"))
            | (nxt == ord("+"))
            | (nxt == ord("-"))
        )
        at_end = (starts[None, :] + l) >= lengths[:, None]
        match = match & (at_end | ~is_num)
    idx = jnp.argmax(match, axis=1).astype(jnp.int32)
    return jnp.where(match.any(axis=1), idx, jnp.int32(-1))


def _gather_window(jnp, data, pos, width: int):
    """data[i, pos[i] : pos[i]+width], zero-filled out of range. pos<0 -> zeros."""
    n, r = data.shape
    cols = pos[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    valid = (cols >= 0) & (cols < r) & (pos >= 0)[:, None]
    window = jnp.take_along_axis(data, jnp.clip(cols, 0, r - 1), axis=1)
    return jnp.where(valid, window, jnp.uint8(0))


_INT_WINDOW = 12  # sign + 9 digits + terminator fits comfortably


def _parse_int_at(jnp, data, pos):
    """Parse a decimal integer starting at pos[i]; returns (val int32, ok).

    v1 limits (documented): at most 9 digits (|val| <= 999,999,999 — always
    int32-safe); a non-digit terminator must appear within the window, so
    longer numbers are rejected (ok=False) rather than silently truncated.
    """
    win = _gather_window(jnp, data, pos, _INT_WINDOW).astype(jnp.int32)
    neg = win[:, 0] == ord("-")
    val = jnp.zeros(win.shape[0], dtype=jnp.int32)
    ndigits = jnp.zeros(win.shape[0], dtype=jnp.int32)
    seen = jnp.zeros(win.shape[0], dtype=bool)
    stopped = jnp.zeros(win.shape[0], dtype=bool)
    for i in range(_INT_WINDOW):
        d = win[:, i] - ord("0")
        isdig = (d >= 0) & (d <= 9)
        skip_sign = (i == 0) & neg
        stopped = stopped | (~isdig & ~skip_sign)
        active = ~stopped & isdig
        val = jnp.where(active, val * 10 + d, val)
        ndigits = ndigits + active.astype(jnp.int32)
        seen = seen | active
    val = jnp.where(neg, -val, val)
    ok = seen & stopped & (ndigits <= 9) & (pos >= 0)
    return val, ok


def _find_byte_from(jnp, window, byte: int):
    """First index of `byte` in each row of window, else width (=miss)."""
    n, w = window.shape
    hit = window == jnp.uint8(byte)
    idx = jnp.argmax(hit, axis=1).astype(jnp.int32)
    return jnp.where(hit.any(axis=1), idx, jnp.int32(w))


# ------------------------------------------------------------ compiler
@functools.lru_cache(maxsize=64)
def _compile_cached(spec_json: str, r_in: int):
    import jax
    import jax.numpy as jnp

    spec = TransformSpec.from_json(spec_json)
    if spec.where is not None:
        raise ValueError(
            "where-expression specs compile to the columnar path "
            "(coproc/column_plan.py), not the raw-payload pipeline"
        )
    mapper = spec.mapper
    if isinstance(mapper, _MapProject):
        if any(not isinstance(f, (Int, Str)) for f in mapper.fields):
            raise ValueError(
                "Float/Substr/Concat projections require the columnar path"
            )
        r_out = project_out_width(mapper.fields)
        if r_out > r_in:
            raise ValueError("projected width exceeds input width")
    else:
        r_out = r_in

    @jax.jit
    def fn(data, lengths):
        data = data.astype(jnp.uint8)
        lengths = lengths.astype(jnp.int32)
        keep = lengths > 0
        for f in spec.filters:
            idx = _find_pattern(jnp, data, lengths, f.pattern, f.require_nonnum_suffix)
            hit = idx >= 0
            keep = keep & (~hit if f.negate else hit)

        if isinstance(mapper, _MapUppercase):
            is_lower = (data >= ord("a")) & (data <= ord("z"))
            out = jnp.where(is_lower, data - 32, data)
            return out, lengths, keep
        if isinstance(mapper, _MapProject):
            n = data.shape[0]
            parts = []
            ok_all = jnp.ones(n, dtype=bool)
            for f in mapper.fields:
                if isinstance(f, Int):
                    pat = f'"{f.key}":'.encode()
                    pos = _find_pattern(jnp, data, lengths, pat)
                    vpos = jnp.where(pos >= 0, pos + len(pat), jnp.int32(-1))
                    val, ok = _parse_int_at(jnp, data, vpos)
                    ok_all = ok_all & ok
                    le = val.astype(jnp.uint32)
                    parts.append(
                        jnp.stack(
                            [(le >> (8 * k)).astype(jnp.uint8) for k in range(4)], axis=1
                        )
                    )
                else:
                    pat = f'"{f.key}":"'.encode()
                    pos = _find_pattern(jnp, data, lengths, pat)
                    spos = jnp.where(pos >= 0, pos + len(pat), jnp.int32(-1))
                    win = _gather_window(jnp, data, spos, f.max_len + 1)
                    slen = _find_byte_from(jnp, win, ord('"'))
                    found_quote = slen <= f.max_len
                    slen = jnp.minimum(slen, f.max_len)
                    ok_all = ok_all & (pos >= 0) & found_quote
                    body = win[:, : f.max_len]
                    mask = jnp.arange(f.max_len, dtype=jnp.int32)[None, :] < slen[:, None]
                    body = jnp.where(mask, body, jnp.uint8(0))
                    lenhdr = jnp.stack(
                        [
                            (slen & 0xFF).astype(jnp.uint8),
                            ((slen >> 8) & 0xFF).astype(jnp.uint8),
                        ],
                        axis=1,
                    )
                    parts.append(jnp.concatenate([lenhdr, body], axis=1))
            out = jnp.concatenate(parts, axis=1)
            keep2 = keep & ok_all
            out_len = jnp.where(keep2, jnp.int32(r_out), 0)
            return out, out_len, keep2
        # identity map
        return data, lengths, keep

    return fn, r_out


def compile_transform(spec: TransformSpec, r_in: int):
    """Compile to fn(data uint8 [N, r_in], lengths [N]) -> (out, out_len, keep).

    The compiled callable is cached per (spec, r_in); output rows for dropped
    records are undefined (mask with `keep`).
    """
    fn, _ = _compile_cached(spec.to_json(), int(r_in))
    return fn


def transform_out_width(spec: TransformSpec, r_in: int) -> int:
    if isinstance(spec.mapper, _MapProject):
        return project_out_width(spec.mapper.fields)
    return r_in
