"""Device-side LZ4 block decoding: the measured experiment, kept as data.

SURVEY §7 has carried "vmapped zstd/lz4 block stages where feasible —
measure first" since round 1; this module IS the measurement (VERDICT r3
item #7). It implements a correct, bit-exact LZ4 *block* decoder as a pure
XLA program (a vectorized byte-machine under ``lax.while_loop``: all
records advance in lockstep, one output byte or one control byte per step)
and the bench records its throughput against host liblz4.

Verdict (run on both backends; see BENCH_r04 "device_lz4_probe"):
LZ4 decoding is an inherently sequential byte-serial dependency chain —
each match copy reads bytes the same stream just produced — so the TPU's
vector lanes parallelize only ACROSS records while every lane performs
dynamic 1-byte gathers+scatters per step, the single worst access pattern
for the MXU/VPU memory system. Measured ~3-4 orders of magnitude below
host liblz4 (MB/s vs GB/s), before even paying the tunnel. Decision:
**(de)compression stays host-side** (compression/codecs.py); the codec
registry's pluggable boundary (compression.cc:18-54) is the permanent
seam, and the engine's columnar pushdown (coproc/column_plan.py) is the
mechanism that keeps compressed payload bytes off the device link
entirely. The decoder stays in-tree as the reproducible experiment and a
worked example of data-dependent control flow under jit.

Format (LZ4 block, lz4_Block_format.md): sequences of
  token(1B: lit_len<<4 | match_len) [lit_len ext 255*] literals
  offset(2B LE) [match_len ext 255*]; match copies match_len+4 bytes from
  `out[op-offset:]` (overlap-safe = RLE when offset < length); the final
  sequence ends after its literals with no match.
"""

from __future__ import annotations

import functools

import numpy as np

# byte-machine phases
_TOKEN, _LIT_EXT, _LIT_COPY, _OFF_LO, _OFF_HI, _M_EXT, _M_COPY, _DONE = range(8)


@functools.lru_cache(maxsize=8)
def make_block_decoder(max_in: int, max_out: int):
    """jit fn(comp uint8 [n, max_in], comp_len int32 [n]) ->
    (out uint8 [n, max_out], out_len int32 [n], ok bool [n]).

    ok=False when a record's stream is malformed or overflows max_out.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def decode(comp, comp_len):
        n = comp.shape[0]
        comp = comp.astype(jnp.uint8)
        comp_len = comp_len.astype(jnp.int32)

        def byte_at(buf, idx):
            return jnp.take_along_axis(
                buf, jnp.clip(idx, 0, buf.shape[1] - 1)[:, None], axis=1
            )[:, 0].astype(jnp.int32)

        state = dict(
            out=jnp.zeros((n, max_out), jnp.uint8),
            ip=jnp.zeros(n, jnp.int32),
            op=jnp.zeros(n, jnp.int32),
            phase=jnp.where(comp_len > 0, _TOKEN, _DONE).astype(jnp.int32),
            lit=jnp.zeros(n, jnp.int32),
            mlen=jnp.zeros(n, jnp.int32),
            moff=jnp.zeros(n, jnp.int32),
            ok=jnp.ones(n, bool),
        )

        def cond(s):
            return jnp.any((s["phase"] != _DONE) & s["ok"])

        def step(s):
            ph = s["phase"]
            ip, op = s["ip"], s["op"]
            cur = byte_at(comp, ip)
            active = (ph != _DONE) & s["ok"]

            # ---- phase TOKEN: token byte
            is_tok = active & (ph == _TOKEN)
            lit0 = cur >> 4
            ml0 = cur & 15
            # ---- phase LIT_EXT
            is_lext = active & (ph == _LIT_EXT)
            # ---- phase LIT_COPY: one literal byte (or transition out)
            is_lcpy = active & (ph == _LIT_COPY)
            has_lit = is_lcpy & (s["lit"] > 0)
            end_of_input = is_lcpy & (s["lit"] == 0) & (ip >= comp_len)
            to_offset = is_lcpy & (s["lit"] == 0) & (ip < comp_len)
            # ---- phase OFF_LO / OFF_HI
            is_olo = active & (ph == _OFF_LO)
            is_ohi = active & (ph == _OFF_HI)
            # ---- phase M_EXT
            is_mext = active & (ph == _M_EXT)
            # ---- phase M_COPY: one match byte
            is_mcpy = active & (ph == _M_COPY)
            src = byte_at(s["out"], op - s["moff"])

            # next phase
            nph = ph
            nph = jnp.where(is_tok & (lit0 == 15), _LIT_EXT, nph)
            nph = jnp.where(is_tok & (lit0 != 15), _LIT_COPY, nph)
            nph = jnp.where(is_lext & (cur != 255), _LIT_COPY, nph)
            nph = jnp.where(end_of_input, _DONE, nph)
            nph = jnp.where(to_offset, _OFF_LO, nph)
            nph = jnp.where(is_olo, _OFF_HI, nph)
            nph = jnp.where(is_ohi & (s["mlen"] == 15), _M_EXT, nph)
            nph = jnp.where(is_ohi & (s["mlen"] != 15), _M_COPY, nph)
            nph = jnp.where(is_mext & (cur != 255), _M_COPY, nph)
            mcpy_done = is_mcpy & (s["mlen"] == 1)
            nph = jnp.where(mcpy_done, _TOKEN, nph)

            # counters
            nlit = s["lit"]
            nlit = jnp.where(is_tok, lit0, nlit)
            nlit = jnp.where(is_lext, nlit + cur, nlit)
            nlit = jnp.where(has_lit, nlit - 1, nlit)
            nml = s["mlen"]
            nml = jnp.where(is_tok, ml0, nml)
            # +4 minimum match applied when entering M_COPY
            enter_mcpy = (is_ohi & (s["mlen"] != 15)) | (is_mext & (cur != 255))
            nml = jnp.where(is_mext, nml + jnp.where(cur == 255, 255, cur), nml)
            nml = jnp.where(enter_mcpy, nml + 4, nml)
            nml = jnp.where(is_mcpy, nml - 1, nml)
            nmoff = s["moff"]
            nmoff = jnp.where(is_olo, cur, nmoff)
            nmoff = jnp.where(is_ohi, nmoff | (cur << 8), nmoff)

            # pointer advance
            consumed = is_tok | is_lext | has_lit | is_olo | is_ohi | is_mext
            nip = ip + consumed.astype(jnp.int32)
            wrote = has_lit | is_mcpy
            nop = op + wrote.astype(jnp.int32)

            # output write: literal byte or match byte
            wbyte = jnp.where(has_lit, cur, src).astype(jnp.uint8)
            out = s["out"]
            widx = jnp.clip(op, 0, max_out - 1)
            cols = jnp.arange(max_out, dtype=jnp.int32)[None, :]
            mask = wrote[:, None] & (cols == widx[:, None])
            out = jnp.where(mask, wbyte[:, None].astype(jnp.uint8), out)

            # validity: overruns, reads past the input, bad match offsets
            ok = s["ok"]
            ok = ok & ~(wrote & (op >= max_out))
            ok = ok & ~(consumed & (ip >= comp_len))
            ok = ok & ~(is_mcpy & ((s["moff"] <= 0) | (s["moff"] > op)))

            return dict(out=out, ip=nip, op=nop, phase=nph, lit=nlit,
                        mlen=nml, moff=nmoff, ok=ok)

        final = lax.while_loop(cond, step, state)
        done_ok = final["ok"] & (final["phase"] == _DONE)
        return final["out"], final["op"], done_ok

    import jax

    return jax.jit(decode)


# ------------------------------------------------------------------ host refs
def lz4_block_compress(data: bytes) -> bytes:
    """Raw LZ4 block via liblz4 (the format the device decoder speaks)."""
    import ctypes

    from redpanda_tpu.compression.codecs import _lz4_handle

    lib = _lz4_handle()
    if not hasattr(lib.LZ4_compress_default, "_rp_typed"):
        lib.LZ4_compress_default.restype = ctypes.c_int
        lib.LZ4_compress_default.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int
        ]
        lib.LZ4_compress_default._rp_typed = True
    bound = len(data) + len(data) // 255 + 32
    dst = ctypes.create_string_buffer(bound)
    n = lib.LZ4_compress_default(data, dst, len(data), bound)
    if n <= 0:
        raise RuntimeError("LZ4_compress_default failed")
    return dst.raw[:n]


def lz4_block_decompress(data: bytes, max_out: int) -> bytes:
    import ctypes

    from redpanda_tpu.compression.codecs import _lz4_handle

    lib = _lz4_handle()
    if not hasattr(lib.LZ4_decompress_safe, "_rp_typed"):
        lib.LZ4_decompress_safe.restype = ctypes.c_int
        lib.LZ4_decompress_safe.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int
        ]
        lib.LZ4_decompress_safe._rp_typed = True
    dst = ctypes.create_string_buffer(max_out)
    n = lib.LZ4_decompress_safe(data, dst, len(data), max_out)
    if n < 0:
        raise RuntimeError("LZ4_decompress_safe failed")
    return dst.raw[:n]


def measure_probe(n_records: int = 64, record_size: int = 512, reps: int = 2) -> dict:
    """The keep-or-kill numbers: device vs host block-decode MB/s."""
    import time

    import jax

    rng = np.random.default_rng(3)
    outs = []
    for i in range(n_records):
        # compressible-but-not-trivial payloads (text-ish with repeats)
        words = rng.choice(
            [b"error", b"warn", b"info", b"trace", b"x" * 16, rng.bytes(8)], 96
        )
        outs.append(b" ".join(words)[:record_size].ljust(record_size, b"."))
    comp = [lz4_block_compress(o) for o in outs]
    max_in = 1 << (max(len(c) for c in comp) - 1).bit_length()
    rows = np.zeros((n_records, max_in), np.uint8)
    lens = np.zeros(n_records, np.int32)
    for i, c in enumerate(comp):
        rows[i, : len(c)] = np.frombuffer(c, np.uint8)
        lens[i] = len(c)
    fn = make_block_decoder(max_in, record_size)
    out, out_len, ok = jax.block_until_ready(fn(rows, lens))  # compile + check
    out = np.asarray(out)
    assert np.asarray(ok).all(), "device decoder rejected valid streams"
    for i, o in enumerate(outs):
        assert out[i, : len(o)].tobytes() == o, f"device decode mismatch @{i}"
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(rows, lens))
    dev_s = (time.perf_counter() - t0) / reps
    total = n_records * record_size
    t0 = time.perf_counter()
    for _ in range(20):
        for c in comp:
            lz4_block_decompress(c, record_size)
    host_s = (time.perf_counter() - t0) / 20
    probe = {
        "device_mb_s": round(total / 1e6 / dev_s, 3),
        "host_mb_s": round(total / 1e6 / host_s, 1),
        "ratio_device_vs_host": round(host_s / dev_s, 6),
        "decision": "host",
    }
    # keep-or-kill is a governed decision like every other measured probe:
    # it lands in the process decision journal (coproc/governor.py) so a
    # BENCH artifact's device_lz4 verdict is reconstructible from
    # /v1/governor alone. Imported here, not at module top: ops/ must not
    # import coproc/ at import time.
    from redpanda_tpu.coproc import governor

    governor.journal_record(
        governor.DEVICE_LZ4,
        probe["decision"],
        f"device block decode {probe['device_mb_s']} MB/s vs host liblz4 "
        f"{probe['host_mb_s']} MB/s (ratio {probe['ratio_device_vs_host']}x)",
        dict(probe),
    )
    return probe
