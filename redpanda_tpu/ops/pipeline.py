"""Fused coproc data-plane pipelines.

Two device programs cover the engine's steady-state loop (SURVEY §3.4):

1. ``make_batch_validator(r)`` — batch-level Kafka-CRC validation over
   ``[N, r]`` prefixed batch rows (replaces the reference's per-batch
   record_batch_crc_checker, record.h:699-721).
2. ``make_record_pipeline(spec, r_in)`` — CRC-agnostic record-value
   transform: filters + map fused into one XLA program, plus CRC-32C of the
   transformed values so the host can reseal output batches without
   re-scanning payload bytes.

Both are shape-specialized and cached; the bridge calls them with
``[P*B, R]`` staging arrays and overlaps H2D/compute/D2H via JAX async
dispatch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from redpanda_tpu.ops.crc32c_device import make_crc_fn
from redpanda_tpu.ops.transforms import TransformSpec, compile_transform, transform_out_width


@functools.lru_cache(maxsize=16)
def make_batch_validator(r: int):
    """fn(rows uint8 [N, r], lens int32 [N], claimed uint32 [N]) -> ok bool [N]."""
    crc = make_crc_fn(r)

    @jax.jit
    def validate(rows, lens, claimed):
        got = crc(rows, lens)
        return (got == claimed) & (lens > 0)

    return validate


@functools.lru_cache(maxsize=64)
def _record_pipeline_cached(spec_json: str, r_in: int):
    spec = TransformSpec.from_json(spec_json)
    tfn = compile_transform(spec, r_in)
    r_out = transform_out_width(spec, r_in)
    out_crc_fn = make_crc_fn(r_out)

    @jax.jit
    def run(data, lengths):
        out, out_len, keep = tfn(data, lengths)
        masked_len = jnp.where(keep, out_len, 0)
        out_crc = out_crc_fn(out, masked_len)
        return out, masked_len, keep, out_crc

    return run, r_out


def make_record_pipeline(spec: TransformSpec, r_in: int):
    """fn(data uint8 [N, r_in], lens [N]) -> (out [N, r_out], out_len, keep, out_crc)."""
    return _record_pipeline_cached(spec.to_json(), int(r_in))
