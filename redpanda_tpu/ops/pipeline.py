"""Fused coproc data-plane pipelines.

Two device programs cover the engine's steady-state loop (SURVEY §3.4):

1. ``make_batch_validator(r)`` — batch-level Kafka-CRC validation over
   ``[N, r]`` prefixed batch rows (replaces the reference's per-batch
   record_batch_crc_checker, record.h:699-721). This is where the device
   CRC kernel earns its keep: the produce path ships claimed wire CRCs up
   with the payload and gets one ok-bit back per batch.
2. ``make_packed_pipeline(spec, r_in)`` — the engine's record transform as a
   single-buffer program: one uint8 staging array in, one uint8 packed
   result out. The tunnel/PCIe link between the broker runtime and the
   device charges per *transfer*, not per byte, so lengths ride in trailing
   metadata columns of the input array and (out_len, keep) ride in trailing
   columns of the output — exactly one H2D and one D2H per launch.

The transform output is deliberately CRC-free: output batches are sealed
host-side after framing + optional compression (the Kafka CRC covers the
compressed payload, which only exists after the host codec runs —
script_context_backend.cc:40-68 re-compresses before the CRC for the same
reason). A per-record value CRC computed on device cannot become the batch
CRC, so we don't compute one.

Both programs are shape-specialized and cached; the bridge calls them with
``[P*B, R]`` staging arrays and overlaps H2D/compute/D2H via JAX async
dispatch (see coproc/engine.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from redpanda_tpu.ops.crc32c_device import make_crc_fn
from redpanda_tpu.ops.transforms import TransformSpec, compile_transform, transform_out_width

# Trailing metadata columns of the staged input row: int32 LE record length,
# then 4 pad bytes (keeps the row 8-byte aligned for the host packer).
IN_META = 8
# Trailing metadata columns of the packed output row: int32 LE out_len,
# uint8 keep flag, 3 pad bytes.
OUT_META = 8


@functools.lru_cache(maxsize=16)
def make_batch_validator(r: int):
    """fn(rows uint8 [N, r], lens int32 [N], claimed uint32 [N]) -> ok bool [N]."""
    crc = make_crc_fn(r)

    @jax.jit
    def validate(rows, lens, claimed):
        got = crc(rows, lens)
        return (got == claimed) & (lens > 0)

    return validate


def _le32(cols):
    """uint8 [N, 4] little-endian columns -> int32 [N]."""
    c = cols.astype(jnp.int32)
    return c[:, 0] | (c[:, 1] << 8) | (c[:, 2] << 16) | (c[:, 3] << 24)


@functools.lru_cache(maxsize=64)
def _packed_pipeline_cached(spec_json: str, r_in: int):
    spec = TransformSpec.from_json(spec_json)
    tfn = compile_transform(spec, r_in)
    r_out = transform_out_width(spec, r_in)

    @jax.jit
    def run(staged):
        data = staged[:, :r_in]
        lens = _le32(staged[:, r_in : r_in + 4])
        out, out_len, keep = tfn(data, lens)
        masked = jnp.where(keep, out_len, 0).astype(jnp.int32)
        lenb = jnp.stack(
            [((masked >> (8 * k)) & 0xFF).astype(jnp.uint8) for k in range(4)], axis=1
        )
        keepb = keep.astype(jnp.uint8)[:, None]
        pad = jnp.zeros((out.shape[0], OUT_META - 5), dtype=jnp.uint8)
        return jnp.concatenate([out, lenb, keepb, pad], axis=1)

    return run, r_out


def make_packed_pipeline(spec: TransformSpec, r_in: int):
    """fn(staged uint8 [N, r_in+IN_META]) -> packed uint8 [N, r_out+OUT_META]."""
    return _packed_pipeline_cached(spec.to_json(), int(r_in))


@functools.lru_cache(maxsize=64)
def _record_pipeline_cached(spec_json: str, r_in: int):
    spec = TransformSpec.from_json(spec_json)
    tfn = compile_transform(spec, r_in)
    r_out = transform_out_width(spec, r_in)

    @jax.jit
    def run(data, lengths):
        out, out_len, keep = tfn(data, lengths)
        masked_len = jnp.where(keep, out_len, 0)
        return out, masked_len, keep

    return run, r_out


def make_record_pipeline(spec: TransformSpec, r_in: int):
    """fn(data uint8 [N, r_in], lens [N]) -> (out [N, r_out], out_len, keep).

    Unpacked variant for tests and the multichip dryrun; the engine's hot
    path uses make_packed_pipeline.
    """
    return _record_pipeline_cached(spec.to_json(), int(r_in))


def unpack_result(packed, r_out: int):
    """Split a fetched packed result (numpy uint8 [N, r_out+OUT_META]) into
    (out [N, r_out], out_len int32 [N], keep bool [N])."""
    import numpy as np

    out = packed[:, :r_out]
    out_len = packed[:, r_out : r_out + 4].copy().view(np.int32).reshape(-1)
    keep = packed[:, r_out + 4].astype(bool)
    return out, out_len, keep
