"""Device data plane: batched XLA/Pallas kernels over [partition, batch, record].

This is the TPU-first heart of the framework. The reference runs CRC32c,
(de)compression, and user transforms as scalar C++/JS per record batch; here
they are batched kernels over fixed-shape device arrays:

- ``packing``    — variable-length records <-> padded [P, B, R] staging arrays
- ``gf2``        — GF(2) linear algebra for carry-less CRC math (host precompute)
- ``crc32c_device`` — CRC-32C of N records as two MXU matmuls + an unwind
- ``transforms`` — the user map/filter transform DSL compiled to jitted fns
- ``pipeline``   — fused validate -> transform -> reseal coproc pipeline
"""

from redpanda_tpu.ops.packing import pack_rows, unpack_rows, pack_batches_prefixed
from redpanda_tpu.ops.crc32c_device import crc32c_device, make_crc_fn
from redpanda_tpu.ops.transforms import (
    TransformSpec,
    identity,
    filter_contains,
    filter_field_eq,
    map_project,
    compile_transform,
)

__all__ = [
    "pack_rows",
    "unpack_rows",
    "pack_batches_prefixed",
    "crc32c_device",
    "make_crc_fn",
    "TransformSpec",
    "identity",
    "filter_contains",
    "filter_field_eq",
    "map_project",
    "compile_transform",
]
