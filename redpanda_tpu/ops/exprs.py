"""Predicate-expression DSL v2: field-anchored transforms for the engine.

The reference accepts arbitrary user JS per record
(src/js/modules/public/SimpleTransform.ts:18, Coprocessor.apply()); v1 of our
DSL covered five fixed ops. v2 closes most of the expressiveness gap with a
composable expression tree over *parsed JSON fields*:

    spec = where(
        (field("meta.level") == "error") & (field("code") >= 500)
        | ~field("retriable").exists()
    ) | map_project(Int("code"), Str("msg", 64), Substr("msg", 4, 8))

Why expressions instead of raw-byte programs: the engine's link profile
(tools/link_probe.py, measured on the axon tunnel: H2D ~15-70 MB/s for
payload bytes, D2H ~3-14 MB/s) showed that shipping record payloads to the
device loses by an order of magnitude before any compute runs. A
field-anchored expression compiles into a *column plan*: the native
columnarizer (native/redpanda_native.cc rp_extract_*) extracts just the
referenced fields — a few bytes per record — the device evaluates the whole
predicate tree over those columns, and one bit per record comes back. This
is classic projection/predicate pushdown, applied at the host<->device
boundary instead of a storage boundary.

Comparison semantics (the host oracle `host_eval` is the normative spec and
the parity target for the device program; tests/test_exprs.py):

- All comparisons require field presence: a missing field makes any
  comparison False (including ``!=``). Use ``field(p).exists()`` to test
  presence.
- Nested paths are dot-separated object traversal; a path step through a
  non-object yields missing.
- String equality compares the *raw JSON bytes* of the value (no escape
  processing, mirroring v1's canonical-form matching); values longer than
  the compiled width compare unequal via their true length.
- Numeric comparisons: values that are integral and fit int32 compare
  exactly; everything else compares at float32 precision (documented TPU
  numeric: f64 is unavailable). Booleans compare as 1/0 only against
  boolean constants; null only matches ``== None``.
- ``str_contains`` scans the first ``w`` bytes of the value (default 64).

Every leaf is static-shape and branch-free on device; rows shard over the
mesh partition axis unchanged (redpanda_tpu.parallel).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

# Flag bits emitted by the numeric extractor (keep in sync with
# native/redpanda_native.cc rp_extract_num and tests/test_native.py).
F_PRESENT = 1
F_NUMBER = 2
F_INT_EXACT = 4
F_BOOL = 8
F_NULL = 16

_OPS = ("eq", "ne", "lt", "le", "gt", "ge")


class Expr:
    """Base predicate node. Combine with ``&``, ``|``, ``~``."""

    def __and__(self, other: "Expr") -> "Expr":
        return And(self, _as_expr(other))

    def __or__(self, other: "Expr") -> "Expr":
        return Or(self, _as_expr(other))

    def __invert__(self) -> "Expr":
        return Not(self)

    # serde ---------------------------------------------------------------
    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Expr":
        k = d["k"]
        if k == "cmp":
            v = d["v"]
            if d.get("vt") == "bytes":
                v = v.encode("latin1")
            return Cmp(d["p"], d["op"], v)
        if k == "exists":
            return Exists(d["p"])
        if k == "contains":
            return StrContains(d["p"], d["n"].encode("latin1"), d.get("w", 64))
        if k == "and":
            return And(Expr.from_dict(d["a"]), Expr.from_dict(d["b"]))
        if k == "or":
            return Or(Expr.from_dict(d["a"]), Expr.from_dict(d["b"]))
        if k == "not":
            return Not(Expr.from_dict(d["a"]))
        raise ValueError(f"unknown expr node {k!r}")


def _as_expr(x) -> Expr:
    if not isinstance(x, Expr):
        raise TypeError(f"expected Expr, got {type(x).__name__}")
    return x


@dataclass(frozen=True, eq=True)
class Cmp(Expr):
    path: str
    op: str  # eq ne lt le gt ge
    value: Any  # str | bytes | int | float | bool | None

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"bad op {self.op!r}")
        if isinstance(self.value, (str, bytes, bool)) or self.value is None:
            if self.op not in ("eq", "ne"):
                raise ValueError(f"op {self.op!r} needs a numeric constant")

    def to_dict(self) -> dict:
        v = self.value
        d = {"k": "cmp", "p": self.path, "op": self.op, "v": v}
        if isinstance(v, bytes):
            d["v"] = v.decode("latin1")
            d["vt"] = "bytes"
        return d


@dataclass(frozen=True, eq=True)
class Exists(Expr):
    path: str

    def to_dict(self) -> dict:
        return {"k": "exists", "p": self.path}


@dataclass(frozen=True, eq=True)
class StrContains(Expr):
    path: str
    needle: bytes
    window: int = 64  # scan width over the value's leading bytes

    def to_dict(self) -> dict:
        return {
            "k": "contains",
            "p": self.path,
            "n": self.needle.decode("latin1"),
            "w": self.window,
        }


@dataclass(frozen=True, eq=True)
class And(Expr):
    a: Expr
    b: Expr

    def to_dict(self) -> dict:
        return {"k": "and", "a": self.a.to_dict(), "b": self.b.to_dict()}


@dataclass(frozen=True, eq=True)
class Or(Expr):
    a: Expr
    b: Expr

    def to_dict(self) -> dict:
        return {"k": "or", "a": self.a.to_dict(), "b": self.b.to_dict()}


@dataclass(frozen=True, eq=True)
class Not(Expr):
    a: Expr

    def to_dict(self) -> dict:
        return {"k": "not", "a": self.a.to_dict()}


class FieldRef:
    """Comparison builder: ``field("a.b") >= 5`` -> :class:`Cmp`."""

    __slots__ = ("path",)

    def __init__(self, path: str):
        if not path or path.startswith(".") or path.endswith(".") or ".." in path:
            raise ValueError(f"bad field path {path!r}")
        self.path = path

    def __eq__(self, other):  # type: ignore[override]
        return Cmp(self.path, "eq", other)

    def __ne__(self, other):  # type: ignore[override]
        return Cmp(self.path, "ne", other)

    def __lt__(self, other):
        return Cmp(self.path, "lt", other)

    def __le__(self, other):
        return Cmp(self.path, "le", other)

    def __gt__(self, other):
        return Cmp(self.path, "gt", other)

    def __ge__(self, other):
        return Cmp(self.path, "ge", other)

    def __hash__(self):
        return hash(("fieldref", self.path))

    def exists(self) -> Exists:
        return Exists(self.path)

    def contains(self, needle: bytes | str, window: int = 64) -> StrContains:
        if isinstance(needle, str):
            needle = needle.encode()
        return StrContains(self.path, bytes(needle), window)


def field(path: str) -> FieldRef:
    return FieldRef(path)


# --------------------------------------------------------------------------
# Host oracle: the normative semantics, evaluated per record on raw bytes.
# Used by parity tests against the device program and as the engine's
# host-mode fallback evaluator. Mirrors the native extractor exactly
# (raw-bytes strings, f32/i32 numeric lattice).
# --------------------------------------------------------------------------


def _skip_ws(s: bytes, i: int, end: int) -> int:
    while i < end and s[i] in b" \t\n\r":
        i += 1
    return i


def _skip_string(s: bytes, i: int, end: int) -> int:
    """i points at the opening quote; returns index after the closing quote."""
    i += 1
    while i < end:
        c = s[i]
        if c == 0x5C:  # backslash
            i += 2
            continue
        if c == 0x22:  # quote
            return i + 1
        i += 1
    return end


def _skip_value(s: bytes, i: int, end: int) -> int:
    i = _skip_ws(s, i, end)
    if i >= end:
        return end
    c = s[i]
    if c == 0x22:
        return _skip_string(s, i, end)
    if c in (0x7B, 0x5B):  # { [
        depth = 0
        while i < end:
            c = s[i]
            if c == 0x22:
                i = _skip_string(s, i, end)
                continue
            if c in (0x7B, 0x5B):
                depth += 1
            elif c in (0x7D, 0x5D):
                depth -= 1
                if depth == 0:
                    return i + 1
            i += 1
        return end
    # number / literal
    while i < end and s[i] not in b",}] \t\n\r":
        i += 1
    return i


def json_find(s: bytes, path: str) -> tuple[int, int, int]:
    """Locate `path` in the JSON object `s`.

    Returns (type, value_start, value_end) where type is:
    0 missing, 1 string (extent excludes the quotes, raw escaped bytes),
    2 number, 3 true, 4 false, 5 null, 6 object, 7 array.
    Must match native rp_json_find (redpanda_native.cc) byte for byte.
    """
    segs = path.split(".")
    i, end = 0, len(s)
    for depth, seg in enumerate(segs):
        want = seg.encode()
        i = _skip_ws(s, i, end)
        if i >= end or s[i] != 0x7B:  # not an object
            return 0, 0, 0
        i += 1
        found = False
        while True:
            i = _skip_ws(s, i, end)
            if i >= end or s[i] == 0x7D:
                return 0, 0, 0
            if s[i] != 0x22:
                return 0, 0, 0  # malformed
            kstart = i + 1
            i = _skip_string(s, i, end)
            kend = i - 1
            i = _skip_ws(s, i, end)
            if i >= end or s[i] != 0x3A:  # ':'
                return 0, 0, 0
            i += 1
            i = _skip_ws(s, i, end)
            if s[kstart:kend] == want:
                found = True
                break
            i = _skip_value(s, i, end)
            i = _skip_ws(s, i, end)
            if i < end and s[i] == 0x2C:  # ','
                i += 1
        if not found:
            return 0, 0, 0
        if depth == len(segs) - 1:
            if i >= end:
                return 0, 0, 0
            c = s[i]
            if c == 0x22:
                j = _skip_string(s, i, end)
                return 1, i + 1, j - 1
            if c == 0x7B:
                return 6, i, _skip_value(s, i, end)
            if c == 0x5B:
                return 7, i, _skip_value(s, i, end)
            j = _skip_value(s, i, end)
            tok = s[i:j]
            if tok == b"true":
                return 3, i, j
            if tok == b"false":
                return 4, i, j
            if tok == b"null":
                return 5, i, j
            return 2, i, j
        # descend: value must be an object
        # (leave i at the value start; next loop iteration checks '{')
    return 0, 0, 0


def _num_lattice(tok: bytes) -> tuple[float, int, int]:
    """(f32val, i32val, flags) for a JSON number token; mirrors native
    rp_extract_num exactly (strtod-style: no '_' separators; a malformed
    token is PRESENT but not a NUMBER)."""
    import math

    import numpy as np

    try:
        # Native-parity grammar: decimal-number characters only (float()
        # would also take 'inf'/'nan'/'_', strtod would take hex — both are
        # PRESENT-only on both paths), and tokens too long for the native
        # 48-byte parse buffer stay PRESENT-only too.
        if len(tok) >= 48 or not tok or any(
            c not in b"0123456789-+.eE" for c in tok
        ):
            raise ValueError(tok)
        d = float(tok)
    except ValueError:
        return 0.0, 0, F_PRESENT
    flags = F_PRESENT | F_NUMBER
    i32 = 0
    if math.isfinite(d) and d == int(d) and -(2**31) <= int(d) <= 2**31 - 1:
        flags |= F_INT_EXACT
        i32 = int(d)
    with np.errstate(over="ignore"):  # |d| > f32 max -> inf, same as the C cast
        f32 = float(np.float32(d))
    return f32, i32, flags


def host_field(s: bytes, path: str) -> dict:
    """Extract one field the way the columnarizer does: raw bytes + lattice."""
    t, vs, ve = json_find(s, path)
    out = {"type": t, "raw": s[vs:ve] if t else b""}
    if t == 2:
        f32, i32, flags = _num_lattice(s[vs:ve])
        out.update(f32=f32, i32=i32, flags=flags)
    elif t == 3:
        out.update(f32=1.0, i32=1, flags=F_PRESENT | F_BOOL)
    elif t == 4:
        out.update(f32=0.0, i32=0, flags=F_PRESENT | F_BOOL)
    elif t == 5:
        out.update(f32=0.0, i32=0, flags=F_PRESENT | F_NULL)
    else:
        out.update(f32=0.0, i32=0, flags=F_PRESENT if t else 0)
    return out


def _cmp_num(op: str, a, b) -> bool:
    if op == "eq":
        return a == b
    if op == "ne":
        return a != b
    if op == "lt":
        return a < b
    if op == "le":
        return a <= b
    if op == "gt":
        return a > b
    return a >= b


def host_eval(expr: Expr, value: bytes) -> bool:
    """Evaluate `expr` against one record value (normative semantics)."""
    import numpy as np

    if isinstance(expr, And):
        return host_eval(expr.a, value) and host_eval(expr.b, value)
    if isinstance(expr, Or):
        return host_eval(expr.a, value) or host_eval(expr.b, value)
    if isinstance(expr, Not):
        return not host_eval(expr.a, value)
    if isinstance(expr, Exists):
        return json_find(value, expr.path)[0] != 0
    if isinstance(expr, StrContains):
        f = host_field(value, expr.path)
        if f["type"] != 1:
            return False
        return expr.needle in f["raw"][: expr.window]
    assert isinstance(expr, Cmp)
    f = host_field(value, expr.path)
    v = expr.value
    if f["type"] == 0:
        return False
    if isinstance(v, (str, bytes)):
        if f["type"] != 1:
            return False
        raw = v.encode() if isinstance(v, str) else bytes(v)
        eq = f["raw"] == raw
        return eq if expr.op == "eq" else not eq
    if isinstance(v, bool):
        if not (f["flags"] & F_BOOL):
            return False
        eq = f["i32"] == (1 if v else 0)
        return eq if expr.op == "eq" else not eq
    if v is None:
        isnull = bool(f["flags"] & F_NULL)
        return isnull if expr.op == "eq" else (f["type"] != 0 and not isnull)
    # numeric constant
    if not (f["flags"] & F_NUMBER) and not (f["flags"] & F_BOOL):
        return False
    if f["flags"] & F_BOOL:
        return False  # booleans only compare to booleans
    const_int = isinstance(v, int) or (float(v) == int(v) and -(2**31) <= int(v) <= 2**31 - 1)
    if const_int and not -(2**31) <= int(v) <= 2**31 - 1:
        const_int = False
    if const_int and (f["flags"] & F_INT_EXACT):
        return _cmp_num(expr.op, f["i32"], int(v))
    return _cmp_num(expr.op, np.float32(f["f32"]), np.float32(float(v)))


def expr_paths(expr: Expr) -> list[str]:
    """All field paths referenced by the tree (deduped, in first-use order)."""
    out: list[str] = []

    def walk(e: Expr):
        if isinstance(e, (And, Or)):
            walk(e.a)
            walk(e.b)
        elif isinstance(e, Not):
            walk(e.a)
        else:
            p = e.path  # type: ignore[attr-defined]
            if p not in out:
                out.append(p)

    walk(expr)
    return out
