"""Variable-length records <-> fixed-shape device staging arrays.

The device data plane operates on padded uint8 arrays of shape
``[partitions, batch, record_bytes]`` plus an int32 length array. Packing is
the host-side hot loop (native C when available, numpy fallback): scatter
record payloads into zero-padded rows; unpack gathers them back out.

``pack_batches_prefixed`` packs whole record batches as
``kafka_crc_prefix(40B) + payload`` rows so that a device CRC over the valid
prefix equals the batch's Kafka CRC-32C — the produce-path validation kernel
(the reference verifies this CRC per batch in kafka_batch_adapter.cc:93-121;
here it is one batched kernel over all partitions).
"""

from __future__ import annotations

import numpy as np

from redpanda_tpu.models.record import RecordBatch


def _native():
    try:
        from redpanda_tpu.native import lib

        return lib
    except Exception:
        return None


def pack_rows(payloads: list[bytes], row_stride: int) -> tuple[np.ndarray, np.ndarray]:
    """Pack N byte strings into a zero-padded uint8 [N, row_stride] + lengths.

    Oversized payloads are truncated (callers bucket by size to avoid this;
    the coproc frontend enforces max record size upstream).
    """
    n = len(payloads)
    lengths = np.array([min(len(p), row_stride) for p in payloads], dtype=np.int32)
    lib = _native()
    if lib is not None and n:
        src = b"".join(payloads)
        sizes = np.array([len(p) for p in payloads], dtype=np.int64)
        offsets = np.zeros(n, dtype=np.int64)
        offsets[1:] = np.cumsum(sizes[:-1])
        rows, _ = lib.pack_rows(src, offsets, sizes.astype(np.int32), row_stride)
        return rows, lengths
    rows = np.zeros((n, row_stride), dtype=np.uint8)
    for i, p in enumerate(payloads):
        m = min(len(p), row_stride)
        rows[i, :m] = np.frombuffer(p[:m], dtype=np.uint8)
    return rows, lengths


def unpack_rows(rows: np.ndarray, lengths: np.ndarray) -> list[bytes]:
    lib = _native()
    rows = np.asarray(rows, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int32)
    if lib is not None and len(lengths):
        blob = lib.unpack_rows(rows, lengths)
        out, pos = [], 0
        for n in lengths:
            n = int(min(max(n, 0), rows.shape[1]))
            out.append(blob[pos : pos + n])
            pos += n
        return out
    return [rows[i, : int(lengths[i])].tobytes() for i in range(len(lengths))]


def pack_batches_prefixed(
    batches: list[RecordBatch], row_stride: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack batches as (kafka-CRC-covered bytes) rows.

    Returns (rows uint8 [N, row_stride], lengths int32 [N], claimed_crcs
    uint32 [N]). crc32c_device(rows, lengths) == claimed_crcs iff every
    batch is intact.
    """
    payloads = [b.header.kafka_header_crc_prefix() + b.payload for b in batches]
    rows, lengths = pack_rows(payloads, row_stride)
    crcs = np.array([b.header.crc for b in batches], dtype=np.uint32)
    return rows, lengths, crcs
