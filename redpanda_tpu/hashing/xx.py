"""xxHash wrappers (parity with hashing/xx.h).

Used for: RPC method ids (xor of service/method name hashes), coproc script
checksums, RPC payload checksums.
"""

from __future__ import annotations

import xxhash as _xx


def xxhash64(data, seed: int = 0) -> int:
    if isinstance(data, str):
        data = data.encode()
    return _xx.xxh64_intdigest(bytes(data), seed)


def xxhash32(data, seed: int = 0) -> int:
    if isinstance(data, str):
        data = data.encode()
    return _xx.xxh32_intdigest(bytes(data), seed)
