from redpanda_tpu.hashing.crc32c import crc32c, crc32c_extend, Crc32c, crc32c_many
from redpanda_tpu.hashing.xx import xxhash64, xxhash32
from redpanda_tpu.hashing.jump import jump_consistent_hash

__all__ = [
    "crc32c",
    "crc32c_extend",
    "Crc32c",
    "crc32c_many",
    "xxhash64",
    "xxhash32",
    "jump_consistent_hash",
]
