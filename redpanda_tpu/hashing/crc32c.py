"""CRC-32C (Castagnoli) — host side.

Capability parity with the reference's ``hashing/crc32c.h`` (which wraps
google/crc32c): an incremental ``Crc32c`` object with ``extend`` over bytes
and fixed-width integers, plus a vectorized multi-record variant
(``crc32c_many``) that processes N equal-padded records in lockstep with
numpy — the host-side mirror of the TPU kernel in
``redpanda_tpu.ops.crc32c_device``.

Polynomial 0x1EDC6F41 (reflected 0x82F63B78), init 0xFFFFFFFF, xorout
0xFFFFFFFF. Golden vector: crc32c(b"123456789") == 0xE3069283 (RFC 3720).

If the native extension (native/libredpanda_native.so) is present it is used
for single-buffer CRC; the numpy path is the fallback and the oracle for
device-kernel tests.
"""

from __future__ import annotations

import struct

import numpy as np

_POLY = 0x82F63B78


def _make_table() -> np.ndarray:
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (_POLY if (c & 1) else 0)
        table[i] = c
    return table


TABLE = _make_table()

# Slicing-by-8 tables: TABLE8[k][b] = CRC update contribution of byte b seen
# k bytes before the end of an 8-byte group.
def _make_table8() -> np.ndarray:
    t8 = np.zeros((8, 256), dtype=np.uint32)
    t8[0] = TABLE
    for k in range(1, 8):
        t8[k] = TABLE[t8[k - 1] & 0xFF] ^ (t8[k - 1] >> 8)
    return t8


TABLE8 = _make_table8()

_native = None


def _load_native():
    global _native
    if _native is None:
        try:
            from redpanda_tpu.native import lib as _lib

            _native = _lib if _lib is not None else False
        except Exception:
            _native = False
    return _native


def crc32c_update(crc: int, data) -> int:
    """Core update: crc is the *internal* state (already inverted).

    Accepts any C-contiguous buffer (bytes, bytearray, memoryview): the
    produce path hands us zero-copy views of the network frame and must not
    pay a materialization per batch.
    """
    native = _load_native()
    if native:
        if not isinstance(data, bytes):
            data = bytes(data)  # ctypes c_char_p needs an owned contiguous blob
        return native.crc32c_update(crc, data)
    buf = np.frombuffer(data, dtype=np.uint8)
    c = np.uint32(crc)
    n = len(buf)
    # slicing-by-8 main loop
    i = 0
    t = TABLE8
    while n - i >= 8:
        b = buf[i : i + 8]
        c = np.uint32(c) ^ np.uint32(
            b[0] | (np.uint32(b[1]) << 8) | (np.uint32(b[2]) << 16) | (np.uint32(b[3]) << 24)
        )
        c = (
            t[7][c & 0xFF]
            ^ t[6][(c >> 8) & 0xFF]
            ^ t[5][(c >> 16) & 0xFF]
            ^ t[4][(c >> 24) & 0xFF]
            ^ t[3][b[4]]
            ^ t[2][b[5]]
            ^ t[1][b[6]]
            ^ t[0][b[7]]
        )
        i += 8
    while i < n:
        c = TABLE[(np.uint32(c) ^ buf[i]) & 0xFF] ^ (np.uint32(c) >> 8)
        i += 1
    return int(c)


def crc32c(data, value: int = 0) -> int:
    """CRC-32C of data, optionally continuing from a previous *final* value."""
    state = (value ^ 0xFFFFFFFF) & 0xFFFFFFFF
    state = crc32c_update(state, data)
    return state ^ 0xFFFFFFFF


def crc32c_extend(crc: int, data) -> int:
    return crc32c(data, crc)


class Crc32c:
    """Incremental CRC mirroring crc::crc32c (hashing/crc32c.h:19-40):
    extend() over raw bytes and over little/big-endian fixed-width ints."""

    __slots__ = ("_state",)

    def __init__(self) -> None:
        self._state = 0xFFFFFFFF

    def extend(self, data) -> "Crc32c":
        self._state = crc32c_update(self._state, data)  # pandalint: disable=RAC1101 -- Crc32c instances are per-call locals (built, extended, read, dropped inside one function); the multi-context affinity comes from callers in different contexts each using their OWN instance
        return self

    def extend_le(self, fmt: str, *values) -> "Crc32c":
        return self.extend(struct.pack("<" + fmt, *values))

    def extend_be(self, fmt: str, *values) -> "Crc32c":
        return self.extend(struct.pack(">" + fmt, *values))

    def value(self) -> int:
        return self._state ^ 0xFFFFFFFF


def crc32c_many(data: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """CRC-32C of N variable-length records in lockstep.

    data: uint8 [N, R] (zero-padded rows), lengths: int [N] actual sizes.
    Returns uint32 [N]. This is the numpy oracle for the device kernel: it
    walks byte positions once, updating all N states per step, freezing each
    record's state at its length.
    """
    data = np.ascontiguousarray(data, dtype=np.uint8)
    lengths = np.asarray(lengths)
    n, r = data.shape
    state = np.full(n, 0xFFFFFFFF, dtype=np.uint32)
    for j in range(r):
        active = j < lengths
        if not active.any():
            break
        nxt = TABLE[(state ^ data[:, j]) & 0xFF] ^ (state >> np.uint32(8))
        state = np.where(active, nxt, state)
    return state ^ np.uint32(0xFFFFFFFF)
