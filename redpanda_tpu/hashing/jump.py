"""Jump consistent hash (parity with hashing/jump_consistent_hash.h).

Used for shard assignment: partition -> shard, peer node -> owning shard of
its connection. Lamping & Veach's algorithm, 64-bit LCG.
"""

from __future__ import annotations

_MASK = (1 << 64) - 1


def jump_consistent_hash(key: int, num_buckets: int) -> int:
    if num_buckets <= 0:
        raise ValueError("num_buckets must be positive")
    key &= _MASK
    b, j = -1, 0
    while j < num_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK
        j = int((b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b
