"""Per-subsystem latency probes over the metrics registry.

Parity with the reference's probe-per-subsystem pattern (storage/probe.h,
raft/probe.cc, kafka/latency_probe.h): each hot path owns a histogram in
the process-wide registry, exported at /metrics. Unlike the tracer
(trace.py) these are ALWAYS on — a histogram record is a dict lookup plus
integer bucket math, the price the reference pays on every request too.

Naming convention (README "Observability"): ``<subsystem>_<stage>_latency_us``
for latency histograms, ``coproc_stage_latency_us{stage=...}`` for the
engine's per-stage breakdown, ``*_bytes_total`` for transfer counters.
"""

from __future__ import annotations

import threading
import time
import weakref

from redpanda_tpu.metrics import Counter, Histogram, registry

# ------------------------------------------------------------ broker path
storage_append_hist = registry.histogram(
    "storage_append_latency_us", "Storage log append latency (us)"
)
storage_housekeeping_hist = registry.histogram(
    "storage_housekeeping_latency_us",
    "One compaction/retention housekeeping pass over a log (us)",
)
raft_replicate_hist = registry.histogram(
    "raft_replicate_latency_us",
    "Raft replicate() to the requested consistency level (us)",
)
# Recorded at the kafka dispatch layer (server/protocol.py _dispatch), so
# one request is one sample — handler wrappers must NOT record these too.
kafka_produce_hist = registry.histogram(
    "kafka_produce_latency_us", "Produce handler latency (microseconds)"
)
kafka_fetch_hist = registry.histogram(
    "kafka_fetch_latency_us",
    "Fetch handler latency incl. long-poll wait (microseconds)",
)
rpc_request_hist = registry.histogram(
    "rpc_request_latency_us", "Internal RPC round-trip latency (us)"
)

# ------------------------------------------------------------ coproc engine
coproc_h2d_bytes = registry.counter(
    "coproc_device_transfer_bytes_total",
    "Bytes staged to / fetched from the device",
    direction="h2d",
)
coproc_d2h_bytes = registry.counter(
    "coproc_device_transfer_bytes_total",
    "Bytes staged to / fetched from the device",
    direction="d2h",
)
coproc_launch_rows_hist = registry.histogram(
    "coproc_launch_rows",
    "Records fused into one device launch (bucket size after shape rounding)",
)
coproc_shard_rows_hist = registry.histogram(
    "coproc_shard_rows",
    "Records per host-stage shard (coproc_host_workers fan-out)",
)
# Harvest framing path, per framing crossing (launch- or shard-level):
# gather = zero-copy framing straight from the joined blob's (offset, len)
# columns; padded = the row-matrix path (byte-mutating transforms).
coproc_harvest_gather = registry.counter(
    "coproc_harvest_path_total",
    "Harvest framing crossings by path",
    mode="gather",
)
coproc_harvest_padded = registry.counter(
    "coproc_harvest_path_total",
    "Harvest framing crossings by path",
    mode="padded",
)

# -------------------------------------------------------- coproc fault domains
# Classified failure counter, one series per (fault domain, exception kind):
# every formerly-silent except block in the engine reports here, so no
# degradation path is invisible on /metrics. Locked check-then-create for
# the same reason as coproc_stage_hist.
_failure_counters: dict[tuple[str, str], Counter] = {}
_failure_lock = threading.Lock()


def coproc_failure_counter(domain: str, kind: str) -> Counter:
    key = (domain, kind)
    c = _failure_counters.get(key)
    if c is None:
        with _failure_lock:
            c = _failure_counters.get(key)
            if c is None:
                c = registry.counter(
                    "coproc_failures_total",
                    "Classified coproc failures by fault domain",
                    domain=domain,
                    kind=kind,
                )
                _failure_counters[key] = c
    return c


coproc_breaker_trips = registry.counter(
    "coproc_breaker_trips_total",
    "Device circuit breaker transitions to open",
)
coproc_retries_total = registry.counter(
    "coproc_device_retries_total",
    "Device interaction retry attempts (deadline/launch failures)",
)
coproc_fallback_rows = registry.counter(
    "coproc_fallback_rows_total",
    "Records whose transform stages re-executed on the pure-host fallback",
)

# Breaker-state gauge: breakers are per-engine while the registry is
# process-wide, so the gauge follows the most recently constructed engine's
# breaker (the broker has exactly one; bench/test engines hand over on
# construction). Weakref: a dead bench engine must not pin its breaker.
_breaker_ref: "weakref.ref | None" = None


def register_breaker(breaker) -> None:
    global _breaker_ref
    _breaker_ref = weakref.ref(breaker)


def _breaker_state_value() -> float:
    b = _breaker_ref() if _breaker_ref is not None else None
    if b is None:
        return -1.0
    from redpanda_tpu.coproc.faults import STATE_NUM

    return STATE_NUM.get(b.state, -1.0)


coproc_breaker_state = registry.gauge(
    "coproc_breaker_state",
    _breaker_state_value,
    "Device circuit breaker state (0 closed, 1 open, 2 half_open, -1 none)",
)

# ------------------------------------------------------ host-stage pool
# Busy-worker gauge for the coproc host-stage pool (coproc/host_pool.py).
# The counter lives HERE, not on the pool: the gauge must be registered
# exactly once per process while pools are per-engine, and probes already
# owns the process-wide registry. inc/dec under a lock — += on an int is
# a read-modify-write and worker threads race it.
_host_pool_busy = 0
_host_pool_lock = threading.Lock()


def host_pool_task_started() -> None:
    global _host_pool_busy
    with _host_pool_lock:
        _host_pool_busy += 1


def host_pool_task_finished() -> None:
    global _host_pool_busy
    with _host_pool_lock:
        _host_pool_busy -= 1


coproc_host_pool_busy = registry.gauge(
    "coproc_host_pool_busy_workers",
    lambda: float(_host_pool_busy),
    "Host-stage pool workers currently running a shard task",
)

_coproc_stage: dict[str, Histogram] = {}
_coproc_stage_lock = threading.Lock()


def coproc_stage_hist(stage: str) -> Histogram:
    """Histogram for one engine stage (explode/pack/dispatch/fetch/...).

    Locked creation: harvests run on executor threads, and an unlocked
    check-then-create could register one Histogram in the registry while
    caching a twin here — the exported series would then stay frozen.
    Callers serialize record() themselves (the engine records under its
    _stats_lock; HdrHist's read-modify-write is not thread-safe)."""
    h = _coproc_stage.get(stage)
    if h is None:
        with _coproc_stage_lock:
            h = _coproc_stage.get(stage)
            if h is None:
                h = registry.histogram(
                    "coproc_stage_latency_us",
                    "TPU engine per-stage wall time (us)",
                    stage=stage,
                )
                _coproc_stage[stage] = h
    return h


def observe_us(hist: Histogram, t0: float) -> None:
    """Record elapsed-since-t0 (a perf_counter timestamp) in microseconds."""
    hist.record(int((time.perf_counter() - t0) * 1e6))


__all__ = [
    "Counter",
    "Histogram",
    "coproc_breaker_state",
    "coproc_breaker_trips",
    "coproc_d2h_bytes",
    "coproc_failure_counter",
    "coproc_fallback_rows",
    "coproc_h2d_bytes",
    "coproc_harvest_gather",
    "coproc_harvest_padded",
    "coproc_host_pool_busy",
    "coproc_launch_rows_hist",
    "coproc_retries_total",
    "coproc_shard_rows_hist",
    "coproc_stage_hist",
    "register_breaker",
    "host_pool_task_finished",
    "host_pool_task_started",
    "kafka_fetch_hist",
    "kafka_produce_hist",
    "observe_us",
    "raft_replicate_hist",
    "rpc_request_hist",
    "storage_append_hist",
    "storage_housekeeping_hist",
]
