"""Per-subsystem latency probes over the metrics registry.

Parity with the reference's probe-per-subsystem pattern (storage/probe.h,
raft/probe.cc, kafka/latency_probe.h): each hot path owns a histogram in
the process-wide registry, exported at /metrics. Unlike the tracer
(trace.py) these are ALWAYS on — a histogram record is a dict lookup plus
integer bucket math, the price the reference pays on every request too.

Naming convention (README "Observability"): ``<subsystem>_<stage>_latency_us``
for latency histograms, ``coproc_stage_latency_us{stage=...}`` for the
engine's per-stage breakdown, ``*_bytes_total`` for transfer counters.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref

from redpanda_tpu.metrics import Counter, Histogram, registry, series_key
from redpanda_tpu.observability.trace import tracer

# ------------------------------------------------------------ broker path
storage_append_hist = registry.histogram(
    "storage_append_latency_us", "Storage log append latency (us)"
)
storage_housekeeping_hist = registry.histogram(
    "storage_housekeeping_latency_us",
    "One compaction/retention housekeeping pass over a log (us)",
)
raft_replicate_hist = registry.histogram(
    "raft_replicate_latency_us",
    "Raft replicate() to the requested consistency level (us)",
)
# Recorded at the kafka dispatch layer (server/protocol.py _dispatch), so
# one request is one sample — handler wrappers must NOT record these too.
kafka_produce_hist = registry.histogram(
    "kafka_produce_latency_us", "Produce handler latency (microseconds)"
)
kafka_fetch_hist = registry.histogram(
    "kafka_fetch_latency_us",
    "Fetch handler latency incl. long-poll wait (microseconds)",
)
rpc_request_hist = registry.histogram(
    "rpc_request_latency_us", "Internal RPC round-trip latency (us)"
)

# ------------------------------------------------------------ coproc engine
coproc_h2d_bytes = registry.counter(
    "coproc_device_transfer_bytes_total",
    "Bytes staged to / fetched from the device",
    direction="h2d",
)
coproc_d2h_bytes = registry.counter(
    "coproc_device_transfer_bytes_total",
    "Bytes staged to / fetched from the device",
    direction="d2h",
)
coproc_launch_rows_hist = registry.histogram(
    "coproc_launch_rows",
    "Records fused into one device launch (bucket size after shape rounding)",
)
coproc_shard_rows_hist = registry.histogram(
    "coproc_shard_rows",
    "Records per host-stage shard (coproc_host_workers fan-out)",
)
# Harvest framing path, per framing crossing (launch- or shard-level):
# gather = zero-copy framing straight from the joined blob's (offset, len)
# columns; padded = the row-matrix path (byte-mutating transforms).
coproc_harvest_gather = registry.counter(
    "coproc_harvest_path_total",
    "Harvest framing crossings by path",
    mode="gather",
)
coproc_harvest_padded = registry.counter(
    "coproc_harvest_path_total",
    "Harvest framing crossings by path",
    mode="padded",
)
# Device-resident column cache (coproc/colcache.py): a hit means a launch
# skipped the whole host parse/extract ladder (and, on the device backend,
# the H2D replay of its predicate columns).
coproc_colcache_hits = registry.counter(
    "coproc_colcache_total",
    "Column-cache lookups by outcome",
    outcome="hit",
)
coproc_colcache_misses = registry.counter(
    "coproc_colcache_total",
    "Column-cache lookups by outcome",
    outcome="miss",
)

# ------------------------------------------------------ multi-chip meshrunner
# One sharded launch = one SPMD predicate program over the partition-axis
# device mesh (coproc/meshrunner.py). Demotions are launches the breaker or
# a failed mesh leg sent down the bit-identical single-device path.
coproc_mesh_launches = registry.counter(
    "coproc_mesh_launches_total",
    "Columnar launches dispatched SPMD over the device mesh",
)
coproc_mesh_demotions = registry.counter(
    "coproc_mesh_demotions_total",
    "Mesh-eligible launches demoted to the single-device path",
)
# per-device record counters, created lazily per mesh device index so the
# series set matches the mesh actually built (locked check-then-create,
# same rationale as coproc_failure_counter)
_mesh_device_rows: dict[int, Counter] = {}
_mesh_device_lock = threading.Lock()


def coproc_mesh_device_rows(device: int) -> Counter:
    c = _mesh_device_rows.get(device)
    if c is None:
        with _mesh_device_lock:
            c = _mesh_device_rows.get(device)
            if c is None:
                c = registry.counter(
                    "coproc_mesh_device_rows_total",
                    "Records dispatched to each mesh device shard",
                    device=str(device),
                )
                _mesh_device_rows[device] = c
    return c

# -------------------------------------------------------- coproc fault domains
# Classified failure counter, one series per (fault domain, exception kind):
# every formerly-silent except block in the engine reports here, so no
# degradation path is invisible on /metrics. Locked check-then-create for
# the same reason as coproc_stage_hist.
_failure_counters: dict[tuple[str, str], Counter] = {}
_failure_lock = threading.Lock()


def coproc_failure_counter(domain: str, kind: str) -> Counter:
    key = (domain, kind)
    c = _failure_counters.get(key)
    if c is None:
        with _failure_lock:
            c = _failure_counters.get(key)
            if c is None:
                c = registry.counter(
                    "coproc_failures_total",
                    "Classified coproc failures by fault domain",
                    domain=domain,
                    kind=kind,
                )
                _failure_counters[key] = c
    return c


coproc_breaker_trips = registry.counter(
    "coproc_breaker_trips_total",
    "Device circuit breaker transitions to open",
)
coproc_retries_total = registry.counter(
    "coproc_device_retries_total",
    "Device interaction retry attempts (deadline/launch failures)",
)
coproc_fallback_rows = registry.counter(
    "coproc_fallback_rows_total",
    "Records whose transform stages re-executed on the pure-host fallback",
)
coproc_lockwatch_edges = registry.counter(
    "coproc_lockwatch_edges_total",
    "Distinct lock-order edges observed by the coproc_lockwatch recorder",
)
coproc_leakwatch_imbalance = registry.counter(
    "coproc_leakwatch_imbalance_total",
    "Resource balances driven negative under the coproc_leakwatch recorder",
)

# Breaker-state gauges moved to the governor (coproc/governor.py): they
# are per-DOMAIN labeled series (coproc_breaker_state{domain=...}) owned by
# the engine's Governor via weakref — the old single weakref-to-latest-
# engine gauge reported a stale engine's breaker after restarts and in
# multi-engine tests.

# ------------------------------------------------------ host-stage pool
# Busy-worker gauge for the coproc host-stage pool (coproc/host_pool.py).
# The counter lives HERE, not on the pool: the gauge must be registered
# exactly once per process while pools are per-engine, and probes already
# owns the process-wide registry. inc/dec under a lock — += on an int is
# a read-modify-write and worker threads race it.
_host_pool_busy = 0
_host_pool_lock = threading.Lock()


def host_pool_task_started() -> None:
    global _host_pool_busy
    with _host_pool_lock:
        _host_pool_busy += 1


def host_pool_task_finished() -> None:
    global _host_pool_busy
    with _host_pool_lock:
        _host_pool_busy -= 1


coproc_host_pool_busy = registry.gauge(
    "coproc_host_pool_busy_workers",
    lambda: float(_host_pool_busy),
    "Host-stage pool workers currently running a shard task",
)

# Success-only device-leg latency per fault domain — THE adaptive-deadline
# source (governor.observe_leg records a sample only when a leg COMPLETES;
# abandoned/timed-out attempts contribute nothing, so timeout bursts can't
# inflate the tail the next deadline derives from the way the fetch-stage
# histogram could).
_device_leg: dict[str, Histogram] = {}
_device_leg_lock = threading.Lock()


def coproc_device_leg_hist(domain: str) -> Histogram:
    """Histogram for one fault domain's successful device legs. Locked
    check-then-create (same rationale as coproc_stage_hist); callers
    serialize record() themselves (the governor records under its own
    lock)."""
    h = _device_leg.get(domain)
    if h is None:
        with _device_leg_lock:
            h = _device_leg.get(domain)
            if h is None:
                h = registry.histogram(
                    "coproc_device_leg_latency_us",
                    "Successful device-leg wall time per fault domain "
                    "(adaptive-deadline source; success-only)",
                    domain=domain,
                )
                _device_leg[domain] = h
    return h


_coproc_stage: dict[str, Histogram] = {}
_coproc_stage_lock = threading.Lock()


def coproc_stage_hist(stage: str) -> Histogram:
    """Histogram for one engine stage (explode/pack/dispatch/fetch/...).

    Locked creation: harvests run on executor threads, and an unlocked
    check-then-create could register one Histogram in the registry while
    caching a twin here — the exported series would then stay frozen.
    Callers serialize record() themselves (the engine records under its
    _stats_lock; HdrHist's read-modify-write is not thread-safe)."""
    h = _coproc_stage.get(stage)
    if h is None:
        with _coproc_stage_lock:
            h = _coproc_stage.get(stage)
            if h is None:
                h = registry.histogram(
                    "coproc_stage_latency_us",
                    "TPU engine per-stage wall time (us)",
                    stage=stage,
                )
                _coproc_stage[stage] = h
    return h


# ------------------------------------------------------------ trace exemplars
# When a histogram observation lands over its breach threshold, the ambient
# trace id is recorded alongside the bucket it fell into, so an SLO breach
# on /v1/slo (and `rpk debug slo`) links straight to the matching
# /v1/trace/slow entry instead of leaving the operator to correlate by
# timestamp. Thresholds come from the armed SLO objectives
# (observability/slo.py arms threshold_ms per metric); a histogram with no
# armed objective falls back to the tracer's slow threshold. Exemplars
# only exist where a trace id does: with the tracer disabled the whole
# layer is one dict lookup + compare per observation (the
# slo_eval_overhead microbench gates that at <1% of a produce op).
_EXEMPLAR_CAP = 16  # newest-first ring per series

_exemplar_lock = threading.Lock()
# id(hist) -> threshold_us armed by an SLO objective (None = tracer default)
_exemplar_thresholds: dict[int, float] = {}
# series key -> deque of {"trace_id", "value_us", "bucket_us"}
_exemplars: dict[str, collections.deque] = {}


# ids that already have a deallocation finalizer registered: tracked
# SEPARATELY from the thresholds dict, because disarm/reset clear the
# thresholds while the finalizer lives as long as the histogram — keying
# "already registered" off the thresholds dict would register a fresh
# finalizer on every disarm/re-arm cycle of an immortal registry
# histogram (loadgen does one such cycle per scenario run).
_exemplar_finalized: set[int] = set()


def _drop_exemplar_threshold(key: int) -> None:
    with _exemplar_lock:
        _exemplar_thresholds.pop(key, None)
        # the object is being deallocated: a future histogram at this
        # address is a different object and deserves its own finalizer
        _exemplar_finalized.discard(key)


def arm_exemplar_threshold(hist: Histogram, threshold_us: float) -> None:
    """Arm a per-histogram breach threshold (an SLO objective's
    threshold_ms). Observations at or over it record the ambient trace id.

    The store is keyed by id(hist) for the hot-path lookup; the finalizer
    drops the entry when the histogram is collected (CPython runs it at
    deallocation, before the address can be reused), so a scratch
    histogram armed and dropped without a disarm can never bequeath its
    threshold to an unrelated histogram allocated at the same address."""
    with _exemplar_lock:
        # one finalizer per object LIFETIME, not per (re-)arm call:
        # evaluate() re-arms on every /v1/slo poll, and disarm/re-arm
        # cycles must not register duplicates either
        first = id(hist) not in _exemplar_finalized
        if first:
            _exemplar_finalized.add(id(hist))
        _exemplar_thresholds[id(hist)] = float(threshold_us)
    if first:
        weakref.finalize(hist, _drop_exemplar_threshold, id(hist))


def disarm_exemplar_threshold(hist: Histogram) -> None:
    with _exemplar_lock:
        _exemplar_thresholds.pop(id(hist), None)


def reset_exemplars() -> None:
    with _exemplar_lock:
        _exemplar_thresholds.clear()
        _exemplars.clear()


def exemplars_for(key: str) -> list[dict]:
    """Newest-first exemplars for one series key (metrics.series_key)."""
    with _exemplar_lock:
        ring = _exemplars.get(key)
        return list(ring)[::-1] if ring else []


def exemplars_snapshot() -> dict[str, list[dict]]:
    with _exemplar_lock:
        return {k: list(ring)[::-1] for k, ring in _exemplars.items() if ring}


def _note_exemplar(hist: Histogram, value_us: int, trace_id) -> None:
    """Slow path — only runs for an over-threshold observation."""
    if trace_id is None:
        trace_id = tracer.current_trace()
        if trace_id is None:
            return  # no trace to link: an exemplar would dangle
    from redpanda_tpu.utils.hdr import _bucket_of, _bucket_upper

    entry = {
        "trace_id": trace_id,
        "value_us": int(value_us),
        "bucket_us": _bucket_upper(_bucket_of(int(value_us))),
        # wall-clock stamp so a windowed SLO report can drop exemplars
        # recorded before its baseline mark (the ring outlives incidents)
        "ts": time.time(),
    }
    key = series_key(hist.name, hist.labels)
    with _exemplar_lock:
        ring = _exemplars.get(key)
        if ring is None:
            ring = _exemplars[key] = collections.deque(maxlen=_EXEMPLAR_CAP)
        ring.append(entry)


def record_us(hist: Histogram, value_us: int, trace_id=None) -> None:
    """Record a latency observation with exemplar capture. The always-on
    cost beyond hist.record is one dict lookup + compare; everything else
    only runs once the value crossed the breach threshold."""
    value_us = int(value_us)
    hist.record(value_us)
    thr = _exemplar_thresholds.get(id(hist))
    if thr is None:
        if not tracer.enabled:
            return
        thr = tracer.slow_threshold_us
    if value_us >= thr:
        _note_exemplar(hist, value_us, trace_id)


def observe_us(hist: Histogram, t0: float) -> None:
    """Record elapsed-since-t0 (a perf_counter timestamp) in microseconds."""
    record_us(hist, int((time.perf_counter() - t0) * 1e6))


__all__ = [
    "Counter",
    "Histogram",
    "arm_exemplar_threshold",
    "exemplars_for",
    "exemplars_snapshot",
    "record_us",
    "reset_exemplars",
    "coproc_breaker_trips",
    "coproc_d2h_bytes",
    "coproc_device_leg_hist",
    "coproc_failure_counter",
    "coproc_fallback_rows",
    "coproc_h2d_bytes",
    "coproc_harvest_gather",
    "coproc_harvest_padded",
    "coproc_host_pool_busy",
    "coproc_launch_rows_hist",
    "coproc_leakwatch_imbalance",
    "coproc_lockwatch_edges",
    "coproc_retries_total",
    "coproc_shard_rows_hist",
    "coproc_stage_hist",
    "host_pool_task_finished",
    "host_pool_task_started",
    "kafka_fetch_hist",
    "kafka_produce_hist",
    "observe_us",
    "raft_replicate_hist",
    "rpc_request_hist",
    "storage_append_hist",
    "storage_housekeeping_hist",
]
