"""pandaprobe span tracer: where does a record batch spend its time?

The reference answers "what is slow" with per-subsystem probes exported at
/metrics; it has no cross-subsystem *trace* because a seastar request never
leaves its shard. Our produce → raft → TPU-transform → fetch path crosses
an event loop, an executor pool AND the engine's harvester thread, so the
aggregate histograms (observability/probes.py) are paired with a span
tracer that stitches one batch's journey back together:

  with tracer.span("raft.replicate"):
      ...

* A span inherits the ambient trace id (a ``contextvars.ContextVar``, so it
  follows the asyncio task across awaits); ``root=True`` starts a fresh
  trace, and a mid-path span with NO ambient trace is a no-op (heartbeat /
  follower chatter must not mint orphan traces that evict real ones).
  Work hopping to another thread carries the id EXPLICITLY
  (``ProcessBatchRequest.trace_id`` → ``Ticket`` → ``_Launch`` → the
  harvester thread) because executor threads do not inherit task context.
* Completed spans land in a bounded ring (``collections.deque(maxlen=N)``)
  — tracing a busy broker must never grow memory; old traces fall off.
* Spans record wall time; stages that wait in a queue or block on the
  device attach ``queue_us`` / ``device_us`` extras (the harvester records
  device time AFTER the async D2H lands, i.e. post-``block_until_ready``
  semantics).
* Spans over ``slow_threshold_us`` additionally land in a slow-request
  ring and a WARNING log line — the "why was this one produce 2s" answer
  without trawling the full ring.

Cost discipline: a disabled tracer does ONE attribute check per span and
returns a shared no-op context manager — no clock read, no allocation, no
lock (tools/microbench.py --only tracer_overhead measures the delta).
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time
from contextvars import ContextVar

logger = logging.getLogger("rptpu.observability.trace")

# Ambient trace id for the current asyncio task / thread.
_current_trace: ContextVar[int | None] = ContextVar("rptpu_trace_id", default=None)

# Ambient NODE id: which broker's work this task is doing. Only entry-point
# spans set it (``span(..., node=N)``) — the kafka handlers, the rpc server's
# join span, the raft append_entries send — and child spans inherit it, so a
# single process hosting several in-process brokers (the loadgen cluster
# stack, the cluster test fixtures) still attributes each span to the right
# node. A real one-broker-per-process deployment falls back to the tracer's
# configured node id.
_current_node: ContextVar[int | None] = ContextVar("rptpu_trace_node", default=None)

_UNSET = object()


class _NoopSpan:
    """Shared do-nothing span: the entire cost of a disabled tracer."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, key: str, value) -> None:
        pass


_NOOP = _NoopSpan()

# Per-thread cached name: threading.current_thread().name walks the active
# registry on every call (~2x a plain local lookup); one enabled span pays
# it once per commit, and the propagation microbench prices that against
# the <1%-of-an-rpc budget. Thread names here never change after spawn.
_thread_name = threading.local()


def _current_thread_name() -> str:
    name = getattr(_thread_name, "v", None)
    if name is None:
        name = _thread_name.v = threading.current_thread().name
    return name


class _Detached:
    """Nulls the ambient trace id for the duration of the block."""

    __slots__ = ("_token",)

    def __enter__(self) -> "_Detached":
        self._token = _current_trace.set(None)
        return self

    def __exit__(self, *exc) -> bool:
        _current_trace.reset(self._token)
        return False


class _Span:
    __slots__ = ("_tracer", "name", "trace_id", "span_id", "_token", "_t0",
                 "extras", "_no_slow", "_node", "_ntoken")

    def __init__(
        self, tracer: "Tracer", name: str, trace_id: int, no_slow: bool,
        node: int | None = None,
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = tracer.new_span_id()
        self._token = None
        self._t0 = 0.0
        self.extras: dict | None = None
        self._no_slow = no_slow
        self._node = node
        self._ntoken = None

    def set(self, key: str, value) -> None:
        """Attach an extra (queue_us, device_us, bytes, ...) to this span."""
        if self.extras is None:
            self.extras = {}
        self.extras[key] = value

    def __enter__(self) -> "_Span":
        self._token = _current_trace.set(self.trace_id)
        if self._node is not None:
            # entry-point span: publish the node for every child span
            self._ntoken = _current_node.set(self._node)
        else:
            self._node = _current_node.get()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        _current_trace.reset(self._token)
        if self._ntoken is not None:
            _current_node.reset(self._ntoken)
            self._ntoken = None
        # positional call: one enabled span commits per sampled rpc, and
        # kwargs marshalling is measurable against the propagation budget
        self._tracer._commit(
            self.name,
            self.trace_id,
            self._t0,
            (t1 - self._t0) * 1e6,
            self.extras,
            self._no_slow,
            self.span_id,
            self._node,
        )
        return False


class Tracer:
    """Bounded, thread-safe span recorder. One process-wide instance
    (``tracer`` below), configured from broker config in app startup."""

    def __init__(
        self,
        *,
        enabled: bool = False,
        capacity: int = 2048,
        slow_capacity: int = 256,
        slow_threshold_ms: float = 500.0,
    ) -> None:
        self.enabled = enabled
        self.slow_threshold_us = float(slow_threshold_ms) * 1000.0
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._slow: collections.deque = collections.deque(maxlen=slow_capacity)
        # Committed-span sink (pandapulse flight recorder). One attribute
        # check per commit when unset; the sink itself must be cheap and
        # never raise (it runs inside every instrumented hot path).
        self._sink = None
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._span_ids = itertools.count(1)
        self._node_id: int | None = None
        # wall-clock anchor so start_us is meaningful across processes
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()
        self._recorded = 0

    # ------------------------------------------------------------ config
    def configure(
        self,
        *,
        enabled: bool | None = None,
        capacity: int | None = None,
        slow_threshold_ms: float | None = None,
        node_id: int | None = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = collections.deque(self._ring, maxlen=capacity)
            if slow_threshold_ms is not None:
                self.slow_threshold_us = float(slow_threshold_ms) * 1000.0
            if node_id is not None and node_id != self._node_id:
                # Namespace trace/span ids by node so a trace assembled
                # across broker processes never merges two nodes' unrelated
                # traces that happened to share a small counter value, and
                # salt the counter start with per-INCARNATION entropy: a
                # SIGKILLed-and-restarted broker seeding deterministically
                # would reuse its previous life's exact ids, and peers'
                # rings (which outlive the restart) would stitch both
                # incarnations into one bogus cluster trace. 36 random
                # bits leave 2^36+ spans of headroom inside the 40-bit
                # counter field before a wrap could touch the node bits.
                # The counters only ever RESEED on an actual node change —
                # reconfiguring other knobs must not rewind ids.
                self._node_id = int(node_id)
                base = ((self._node_id + 1) & 0xFFFF) << 40
                salt = int.from_bytes(os.urandom(5), "big") >> 4  # 36 bits
                self._ids = itertools.count(base | salt | 1)
                self._span_ids = itertools.count(base | salt | 1)
        if enabled is not None:
            self.enabled = enabled  # last: spans only start once ring is sized

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self._recorded = 0

    def set_sink(self, sink) -> None:
        """Install (or clear, with ``None``) the committed-span sink — the
        pandapulse flight recorder's feed. Exactly one sink: the recorder
        owns fan-out if it ever needs one."""
        self._sink = sink

    # ------------------------------------------------------------ ids
    def new_trace_id(self) -> int:
        return next(self._ids)

    def new_span_id(self) -> int:
        return next(self._span_ids)

    @property
    def node_id(self) -> int | None:
        return self._node_id

    def current_trace(self) -> int | None:
        """Ambient trace id (None when disabled or outside any span) —
        what cross-thread hops stamp onto their request objects."""
        if not self.enabled:
            return None
        return _current_trace.get()

    def current_node(self) -> int | None:
        """Ambient node id set by the nearest entry-point span, or the
        tracer's configured node (None when neither is known)."""
        n = _current_node.get()
        return n if n is not None else self._node_id

    @property
    def spans_recorded(self) -> int:
        return self._recorded

    @property
    def epoch_wall(self) -> float:
        """Wall-clock time perf-epoch 0 corresponds to — what lets the
        cluster assembler align span start_us across processes."""
        return self._epoch_wall

    @property
    def epoch_perf(self) -> float:
        return self._epoch_perf

    # ------------------------------------------------------------ spans
    def span(
        self, name: str, trace_id=_UNSET, *, root: bool = False,
        no_slow: bool = False, node: int | None = None,
    ):
        """Context manager timing one stage.

        - ``span(name)``: joins the ambient trace; NO-OP when there is
          none. Traces only ever originate at request entry points
          (``root=True``) — a mid-path span (storage.append on a follower,
          an rpc.send heartbeat) must not mint single-span orphan traces,
          or steady-state chatter evicts the end-to-end traces the ring
          exists for.
        - ``span(name, root=True)``: starts a fresh trace (request entry
          points: kafka produce/fetch, a coproc tick).
        - ``span(name, trace_id=tid)``: explicit id carried across a
          thread hop; ``tid=None`` means "caller had no trace" → no-op.
        - ``no_slow=True``: exempt from the slow-request log — for spans
          whose duration is INTENTIONAL waiting (a fetch long poll), which
          would otherwise bury real slow work.
        - ``node=N``: entry-point spans stamp which broker's work this is
          and publish it to child spans (see ``_current_node``); child
          spans inherit the ambient node automatically.
        """
        if not self.enabled:
            return _NOOP
        if root:
            tid = self.new_trace_id()
        elif trace_id is _UNSET:
            tid = _current_trace.get()
            if tid is None:
                return _NOOP
        elif trace_id is None:
            return _NOOP
        else:
            tid = trace_id
        return _Span(self, name, tid, no_slow, node=node)

    def detached(self):
        """Wrap creation of LONG-LIVED tasks (a replicate batcher's flush
        loop, follower recovery) in this: ``asyncio.create_task`` copies the
        caller's contextvars, so a task spawned inside a request span would
        otherwise attribute every span it ever records to that first
        request's trace — starving later traces of their legs and growing
        one ancient trace forever. Work the task does on behalf of many
        requests either carries ids explicitly or goes untraced."""
        return _Detached()

    def record(
        self,
        name: str,
        dur_us: float,
        trace_id: int | None = None,
        *,
        start_perf: float | None = None,
        **extras,
    ) -> None:
        """Manually record a completed stage (used where a context manager
        cannot wrap the work: harvester thread, pre-trace read phases)."""
        if not self.enabled or trace_id is None:
            return
        t0 = start_perf if start_perf is not None else time.perf_counter() - dur_us / 1e6
        self._commit(name, trace_id, t0, dur_us, extras or None)

    def _commit(
        self,
        name: str,
        trace_id: int,
        t0: float,
        dur_us: float,
        extras: dict | None,
        no_slow: bool = False,
        span_id: int | None = None,
        node: int | None = None,
    ) -> None:
        span = {
            "trace_id": trace_id,
            "name": name,
            "start_us": int((t0 - self._epoch_perf) * 1e6),
            "dur_us": int(dur_us),
            "thread": _current_thread_name(),
        }
        if span_id is None:
            span_id = self.new_span_id()  # manual record(): still unique
        span["span_id"] = span_id
        if node is None:
            # ambient first: tracer.record() calls inside an entry-point
            # span (pacemaker's back-dated read phase) belong to THAT
            # broker, not to whichever in-process app configured last
            node = _current_node.get()
            if node is None:
                node = self._node_id
        if node is not None:
            span["node"] = node
        if extras:
            span.update(extras)
        with self._lock:
            self._ring.append(span)
            self._recorded += 1
            if not no_slow and dur_us >= self.slow_threshold_us:
                self._slow.append(span)
                slow = True
            else:
                slow = False
        sink = self._sink
        if sink is not None:
            # outside the lock: the recorder has its own bounded ring and
            # must never serialize behind the tracer's
            sink(span)
        if slow:
            logger.warning(
                "slow span %s: %.1f ms (trace %d, thread %s)",
                name, dur_us / 1000.0, trace_id, span["thread"],
            )

    # ------------------------------------------------------------ queries
    def recent(self, limit: int = 20) -> list[dict]:
        """Newest-first traces: [{trace_id, wall_us, spans:[...]}, ...].

        Spans of one trace are grouped and time-ordered; a trace whose
        early spans already fell off the ring shows what survived.
        """
        with self._lock:
            spans = list(self._ring)
        by_trace: dict[int, list[dict]] = {}
        order: list[int] = []
        for s in spans:
            tid = s["trace_id"]
            if tid not in by_trace:
                by_trace[tid] = []
                order.append(tid)
            by_trace[tid].append(s)
        out = []
        for tid in reversed(order[-limit:] if limit else order):
            group = sorted(by_trace[tid], key=lambda s: s["start_us"])
            first = min(s["start_us"] for s in group)
            last = max(s["start_us"] + s["dur_us"] for s in group)
            out.append({
                "trace_id": tid,
                "epoch": self._epoch_wall,
                "wall_us": last - first,
                "spans": group,
            })
        return out

    def slow(self, limit: int = 50) -> list[dict]:
        """Newest-first spans that crossed the slow threshold."""
        with self._lock:
            return list(self._slow)[-limit:][::-1]

    def spans_for(self, trace_id: int) -> list[dict]:
        """Every surviving span of ONE trace, time-ordered — what the
        cluster-trace assembler (GET /v1/trace/id/<tid> per node, merged by
        admin fan-out) pulls. Ring and slow-ring hold the same dict objects,
        so the union dedupes by identity: a slow span whose trace fell off
        the main ring is still returned."""
        with self._lock:
            seen: dict[int, dict] = {}
            for s in list(self._ring) + list(self._slow):
                if s["trace_id"] == trace_id:
                    seen[id(s)] = s
        return sorted(seen.values(), key=lambda s: s["start_us"])


# Process-wide tracer, like the metrics registry singleton: subsystems
# import this instance; app startup flips it on from config.
tracer = Tracer()
