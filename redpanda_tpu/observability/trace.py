"""pandaprobe span tracer: where does a record batch spend its time?

The reference answers "what is slow" with per-subsystem probes exported at
/metrics; it has no cross-subsystem *trace* because a seastar request never
leaves its shard. Our produce → raft → TPU-transform → fetch path crosses
an event loop, an executor pool AND the engine's harvester thread, so the
aggregate histograms (observability/probes.py) are paired with a span
tracer that stitches one batch's journey back together:

  with tracer.span("raft.replicate"):
      ...

* A span inherits the ambient trace id (a ``contextvars.ContextVar``, so it
  follows the asyncio task across awaits); ``root=True`` starts a fresh
  trace, and a mid-path span with NO ambient trace is a no-op (heartbeat /
  follower chatter must not mint orphan traces that evict real ones).
  Work hopping to another thread carries the id EXPLICITLY
  (``ProcessBatchRequest.trace_id`` → ``Ticket`` → ``_Launch`` → the
  harvester thread) because executor threads do not inherit task context.
* Completed spans land in a bounded ring (``collections.deque(maxlen=N)``)
  — tracing a busy broker must never grow memory; old traces fall off.
* Spans record wall time; stages that wait in a queue or block on the
  device attach ``queue_us`` / ``device_us`` extras (the harvester records
  device time AFTER the async D2H lands, i.e. post-``block_until_ready``
  semantics).
* Spans over ``slow_threshold_us`` additionally land in a slow-request
  ring and a WARNING log line — the "why was this one produce 2s" answer
  without trawling the full ring.

Cost discipline: a disabled tracer does ONE attribute check per span and
returns a shared no-op context manager — no clock read, no allocation, no
lock (tools/microbench.py --only tracer_overhead measures the delta).
"""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import time
from contextvars import ContextVar

logger = logging.getLogger("rptpu.observability.trace")

# Ambient trace id for the current asyncio task / thread.
_current_trace: ContextVar[int | None] = ContextVar("rptpu_trace_id", default=None)

_UNSET = object()


class _NoopSpan:
    """Shared do-nothing span: the entire cost of a disabled tracer."""

    __slots__ = ()
    trace_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, key: str, value) -> None:
        pass


_NOOP = _NoopSpan()


class _Detached:
    """Nulls the ambient trace id for the duration of the block."""

    __slots__ = ("_token",)

    def __enter__(self) -> "_Detached":
        self._token = _current_trace.set(None)
        return self

    def __exit__(self, *exc) -> bool:
        _current_trace.reset(self._token)
        return False


class _Span:
    __slots__ = ("_tracer", "name", "trace_id", "_token", "_t0", "extras",
                 "_no_slow")

    def __init__(
        self, tracer: "Tracer", name: str, trace_id: int, no_slow: bool
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self._token = None
        self._t0 = 0.0
        self.extras: dict | None = None
        self._no_slow = no_slow

    def set(self, key: str, value) -> None:
        """Attach an extra (queue_us, device_us, bytes, ...) to this span."""
        if self.extras is None:
            self.extras = {}
        self.extras[key] = value

    def __enter__(self) -> "_Span":
        self._token = _current_trace.set(self.trace_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        _current_trace.reset(self._token)
        self._tracer._commit(
            self.name,
            self.trace_id,
            self._t0,
            (t1 - self._t0) * 1e6,
            self.extras,
            no_slow=self._no_slow,
        )
        return False


class Tracer:
    """Bounded, thread-safe span recorder. One process-wide instance
    (``tracer`` below), configured from broker config in app startup."""

    def __init__(
        self,
        *,
        enabled: bool = False,
        capacity: int = 2048,
        slow_capacity: int = 256,
        slow_threshold_ms: float = 500.0,
    ) -> None:
        self.enabled = enabled
        self.slow_threshold_us = float(slow_threshold_ms) * 1000.0
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._slow: collections.deque = collections.deque(maxlen=slow_capacity)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._recorded = 0
        # wall-clock anchor so start_us is meaningful across processes
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()

    # ------------------------------------------------------------ config
    def configure(
        self,
        *,
        enabled: bool | None = None,
        capacity: int | None = None,
        slow_threshold_ms: float | None = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = collections.deque(self._ring, maxlen=capacity)
            if slow_threshold_ms is not None:
                self.slow_threshold_us = float(slow_threshold_ms) * 1000.0
        if enabled is not None:
            self.enabled = enabled  # last: spans only start once ring is sized

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self._recorded = 0

    # ------------------------------------------------------------ ids
    def new_trace_id(self) -> int:
        return next(self._ids)

    def current_trace(self) -> int | None:
        """Ambient trace id (None when disabled or outside any span) —
        what cross-thread hops stamp onto their request objects."""
        if not self.enabled:
            return None
        return _current_trace.get()

    @property
    def spans_recorded(self) -> int:
        return self._recorded

    # ------------------------------------------------------------ spans
    def span(
        self, name: str, trace_id=_UNSET, *, root: bool = False,
        no_slow: bool = False,
    ):
        """Context manager timing one stage.

        - ``span(name)``: joins the ambient trace; NO-OP when there is
          none. Traces only ever originate at request entry points
          (``root=True``) — a mid-path span (storage.append on a follower,
          an rpc.send heartbeat) must not mint single-span orphan traces,
          or steady-state chatter evicts the end-to-end traces the ring
          exists for.
        - ``span(name, root=True)``: starts a fresh trace (request entry
          points: kafka produce/fetch, a coproc tick).
        - ``span(name, trace_id=tid)``: explicit id carried across a
          thread hop; ``tid=None`` means "caller had no trace" → no-op.
        - ``no_slow=True``: exempt from the slow-request log — for spans
          whose duration is INTENTIONAL waiting (a fetch long poll), which
          would otherwise bury real slow work.
        """
        if not self.enabled:
            return _NOOP
        if root:
            tid = self.new_trace_id()
        elif trace_id is _UNSET:
            tid = _current_trace.get()
            if tid is None:
                return _NOOP
        elif trace_id is None:
            return _NOOP
        else:
            tid = trace_id
        return _Span(self, name, tid, no_slow)

    def detached(self):
        """Wrap creation of LONG-LIVED tasks (a replicate batcher's flush
        loop, follower recovery) in this: ``asyncio.create_task`` copies the
        caller's contextvars, so a task spawned inside a request span would
        otherwise attribute every span it ever records to that first
        request's trace — starving later traces of their legs and growing
        one ancient trace forever. Work the task does on behalf of many
        requests either carries ids explicitly or goes untraced."""
        return _Detached()

    def record(
        self,
        name: str,
        dur_us: float,
        trace_id: int | None = None,
        *,
        start_perf: float | None = None,
        **extras,
    ) -> None:
        """Manually record a completed stage (used where a context manager
        cannot wrap the work: harvester thread, pre-trace read phases)."""
        if not self.enabled or trace_id is None:
            return
        t0 = start_perf if start_perf is not None else time.perf_counter() - dur_us / 1e6
        self._commit(name, trace_id, t0, dur_us, extras or None)

    def _commit(
        self,
        name: str,
        trace_id: int,
        t0: float,
        dur_us: float,
        extras: dict | None,
        *,
        no_slow: bool = False,
    ) -> None:
        span = {
            "trace_id": trace_id,
            "name": name,
            "start_us": int((t0 - self._epoch_perf) * 1e6),
            "dur_us": int(dur_us),
            "thread": threading.current_thread().name,
        }
        if extras:
            span.update(extras)
        with self._lock:
            self._ring.append(span)
            self._recorded += 1
            if not no_slow and dur_us >= self.slow_threshold_us:
                self._slow.append(span)
                slow = True
            else:
                slow = False
        if slow:
            logger.warning(
                "slow span %s: %.1f ms (trace %d, thread %s)",
                name, dur_us / 1000.0, trace_id, span["thread"],
            )

    # ------------------------------------------------------------ queries
    def recent(self, limit: int = 20) -> list[dict]:
        """Newest-first traces: [{trace_id, wall_us, spans:[...]}, ...].

        Spans of one trace are grouped and time-ordered; a trace whose
        early spans already fell off the ring shows what survived.
        """
        with self._lock:
            spans = list(self._ring)
        by_trace: dict[int, list[dict]] = {}
        order: list[int] = []
        for s in spans:
            tid = s["trace_id"]
            if tid not in by_trace:
                by_trace[tid] = []
                order.append(tid)
            by_trace[tid].append(s)
        out = []
        for tid in reversed(order[-limit:] if limit else order):
            group = sorted(by_trace[tid], key=lambda s: s["start_us"])
            first = min(s["start_us"] for s in group)
            last = max(s["start_us"] + s["dur_us"] for s in group)
            out.append({
                "trace_id": tid,
                "epoch": self._epoch_wall,
                "wall_us": last - first,
                "spans": group,
            })
        return out

    def slow(self, limit: int = 50) -> list[dict]:
        """Newest-first spans that crossed the slow threshold."""
        with self._lock:
            return list(self._slow)[-limit:][::-1]


# Process-wide tracer, like the metrics registry singleton: subsystems
# import this instance; app startup flips it on from config.
tracer = Tracer()
