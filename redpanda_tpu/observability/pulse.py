"""pandapulse: the always-on flight recorder + continuous wall profiler.

Every perf PR since BENCH_r06 has been steered by coarse ``t_*`` stage
sums and one-off microbenches — nobody could *see* a launch's lifecycle
(queue wait vs H2D vs device vs harvest vs seal, across pool shards and
mesh devices) on a time axis. This module turns the instrumentation the
repo already has into timelines, at near-zero marginal cost:

* **Flight recorder** (``FlightRecorder``) — a bounded ring of committed
  span dicts fed straight off the tracer's commit path
  (``Tracer.set_sink``). NO new clocks anywhere: the engine's stage
  timers (``TpuEngine._stat_stage``, ``_Launch._stat``), the pacemaker's
  tick spans and the harvester's queue/device extras are the only time
  sources; the recorder just retains and *assembles* them into per-launch
  lifecycle groups, with queue-wait gaps made explicit from the
  ``queue_us`` extras the harvester already records.
* **Wall profiler** (``WallProfiler``) — a low-frequency sampling thread
  (``sys._current_frames``, config ``profile_hz``, default off; ~19 Hz is
  the recommended on-value: prime, aliases with nothing periodic). Samples
  fold into per-thread flamegraph stacks tagged with the executor-affinity
  names pandalint's concurrency analysis already knows (loop / executor /
  pool_worker / daemon). Profiler off = NO sampler thread and zero code on
  any hot path — the engine never calls into this module.
* **Chrome trace export** (``Pulse.timeline``) — Perfetto-loadable
  trace-event JSON: launch slices as complete ("X") events on per-thread
  tracks, governor journal verdicts and admission-shed episodes injected
  as instant ("i") events on the same clock, so a breaker trip or an
  autotune move is visible in the timeline right next to the launches it
  affected. ``GET /v1/profile/timeline`` serves it; the federated variant
  (observability/federation.py ``assemble_cluster_timeline``) merges every
  node's events into one cluster timeline like ``/v1/trace/cluster``.

Clock contract: span ``start_us`` is perf-counter-relative to the
tracer's epoch (``tracer.epoch_perf``), whose wall anchor is
``tracer.epoch_wall``; journal entries carry wall ``ts``, so instant
events land on the span clock via ``(ts - epoch_wall) * 1e6``. Cross-node
assembly re-anchors on each node's epoch exactly like cluster traces.

Cost discipline: the recorder rides spans that are already being paid for
(``trace_enabled`` gates the whole plane — the pandascope rollout-flag
posture); with the sink installed the extra cost per committed span is one
bounded-deque append, and with pulse disabled it is one attribute check
inside ``Tracer._commit``. ``tools/microbench.py pulse_overhead`` prices
the recorder against a real columnar launch (``--assert-pulse-overhead``).
"""

from __future__ import annotations

import collections
import copy
import itertools
import os
import sys
import threading
import time

from redpanda_tpu.observability.trace import tracer

# Span names that mark a trace as a LAUNCH lifecycle group (a coproc tick
# or a bare-engine submit both qualify; produce/fetch traces with no
# coproc leg are not launches and stay out of the launch timeline).
_LAUNCH_MARKERS = ("coproc.tick", "coproc.dispatch", "coproc.harvest")

# thread-name prefix -> pandalint executor-affinity context name
# (tools/pandalint/affinity.py seeds: loop / executor / pool_worker /
# daemon / device_mesh / finalizer). The profiler and the timeline tag
# every thread track with these so a flamegraph reads in the same
# vocabulary the static race analysis uses.
_AFFINITY_PREFIXES = (
    ("MainThread", "loop"),
    ("rptpu-coproc-tick", "executor"),
    ("rptpu-host-stage", "pool_worker"),
    ("rptpu-mask-harvester", "daemon"),
    ("rptpu-fault-fetch", "daemon"),
    ("rptpu-pulse-profiler", "daemon"),
    ("asyncio_", "executor"),
    ("ThreadPoolExecutor", "executor"),
)


def thread_affinity(thread_name: str) -> str:
    """Executor-affinity context for a thread name (pandalint vocabulary);
    unknown threads read as plain ``thread``."""
    for prefix, ctx in _AFFINITY_PREFIXES:
        if thread_name.startswith(prefix):
            return ctx
    return "thread"


# ================================================================ recorder
class FlightRecorder:
    """Bounded ring of committed spans + launch-lifecycle assembly.

    ``record`` is the tracer sink: it must stay allocation-light and can
    never raise (deque.append on a bounded deque is atomic under the GIL,
    so no lock on the write path; readers take a snapshot copy)."""

    def __init__(self, capacity: int = 8192) -> None:
        self._ring: collections.deque = collections.deque(
            maxlen=max(16, int(capacity))
        )
        # GIL-atomic C-level counter: += on an int is a read-modify-write
        # racing across commit threads (the lost-update class PR 9 fixed
        # in metrics.Counter), and a lock here would double the per-span
        # sink cost the pulse_overhead gate prices. itertools.count is
        # consumed to count and copy-peeked to read.
        self._ids = itertools.count(1)

    # ------------------------------------------------------------ feed
    def record(self, span: dict) -> None:
        # the span dict is the tracer's own committed object; the recorder
        # treats it as immutable and shares it (no copy per span)
        self._ring.append(span)
        next(self._ids)

    # ------------------------------------------------------------ config
    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def configure(self, capacity: int) -> None:
        capacity = max(16, int(capacity))
        if capacity != self._ring.maxlen:
            self._ring = collections.deque(self._ring, maxlen=capacity)

    def reset(self) -> None:
        self._ring.clear()
        self._ids = itertools.count(1)

    @property
    def spans_recorded(self) -> int:
        # non-consuming read: a copy of the counter yields the next value
        return next(copy.copy(self._ids)) - 1

    def spans(self) -> list[dict]:
        return list(self._ring)

    # ------------------------------------------------------------ assembly
    def launches(self, limit: int = 0) -> list[dict]:
        """Newest-first launch lifecycle groups assembled from the ring.

        A group is every surviving span of one trace that contains at
        least one launch marker (a coproc tick / dispatch / harvest leg),
        with derived ``*.queue_wait`` slices made explicit from the
        ``queue_us`` extras the harvester records — the gap between a mask
        being enqueued and the harvester picking it up becomes a visible
        slice instead of dead air."""
        spans = self.spans()
        by_trace: dict[int, list[dict]] = {}
        order: list[int] = []
        launchy: set[int] = set()
        for s in spans:
            tid = s["trace_id"]
            if tid not in by_trace:
                by_trace[tid] = []
                order.append(tid)
            by_trace[tid].append(s)
            if s["name"].startswith(_LAUNCH_MARKERS):
                launchy.add(tid)
        out: list[dict] = []
        for tid in reversed(order):
            if tid not in launchy:
                continue
            group = sorted(by_trace[tid], key=lambda s: s["start_us"])
            slices = []
            for s in group:
                slices.append(s)
                q_us = s.get("queue_us")
                if q_us:
                    # derived, not measured twice: the harvester computed
                    # queue_us off timestamps it already took
                    slices.append({
                        "trace_id": tid,
                        "name": s["name"] + ".queue_wait",
                        "start_us": s["start_us"] - int(q_us),
                        "dur_us": int(q_us),
                        "thread": s.get("thread", "?"),
                        "node": s.get("node"),
                        "derived": True,
                    })
            first = min(s["start_us"] for s in group)
            last = max(s["start_us"] + s["dur_us"] for s in group)
            out.append({
                "trace_id": tid,
                "start_us": first,
                "wall_us": last - first,
                "slices": slices,
            })
            if limit and len(out) >= limit:
                break
        return out

    def stage_totals(self) -> dict[str, float]:
        """Per-span-name summed seconds over every span in the ring — the
        recorder-side twin of the engine's ``stats()`` ``t_*`` splits
        (``coproc.stage.explode_find2`` sums against ``t_explode_find2``;
        the parity test pins them within integer-microsecond truncation
        per slice)."""
        totals: dict[str, float] = {}
        for s in self.spans():
            if s.get("derived"):
                continue
            totals[s["name"]] = totals.get(s["name"], 0.0) + s["dur_us"] / 1e6
        return totals

    def summary(self) -> dict:
        spans = self.spans()
        return {
            "capacity": self.capacity,
            "spans": len(spans),
            "spans_recorded": self.spans_recorded,
            "launches": len(self.launches()),
        }


# ================================================================ profiler
class WallProfiler:
    """Low-frequency wall-clock sampling profiler over every live thread.

    ``sys._current_frames()`` is a point-in-time snapshot of each thread's
    Python frame; at ~19 Hz the sampler costs microseconds per tick and
    nothing at all on the sampled threads (no tracing hooks, no
    sys.setprofile — the threads never know). Stacks fold into
    ``(thread_name, frame-tuple) -> count``, the flamegraph form."""

    MAX_DEPTH = 64
    MAX_STACKS = 4096  # distinct (thread, stack) keys retained

    def __init__(self) -> None:
        self.hz = 0.0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._stacks: dict[tuple, int] = {}
        self._samples = 0
        self._dropped = 0
        self._started_ts: float | None = None

    # ------------------------------------------------------------ control
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def configure(self, hz: float | None) -> None:
        """``hz > 0`` starts (or retunes) the sampler; ``hz <= 0`` stops
        it. Idempotent either way."""
        if hz is None:
            return
        hz = float(hz)
        if hz <= 0:
            self.stop()
            return
        self.hz = hz
        if not self.running:
            self._stop.clear()
            self._started_ts = time.time()
            self._thread = threading.Thread(
                target=self._loop, name="rptpu-pulse-profiler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        t, self._thread = self._thread, None
        self.hz = 0.0
        if t is not None and t.is_alive():
            self._stop.set()
            t.join(timeout=2.0)

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._samples = 0
            self._dropped = 0

    # ------------------------------------------------------------ sampling
    def _loop(self) -> None:
        while True:
            hz = self.hz
            if hz <= 0 or self._stop.wait(1.0 / hz):
                return
            try:
                self._sample()
            except Exception:  # noqa: BLE001 - the sampler must never die
                self._dropped += 1

    def _sample(self) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        me = threading.get_ident()
        folded: list[tuple[tuple, int]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue  # the sampler observing itself is pure noise
            stack: list[str] = []
            f = frame
            while f is not None and len(stack) < self.MAX_DEPTH:
                co = f.f_code
                stack.append(
                    f"{os.path.basename(co.co_filename)}:{co.co_name}"
                )
                f = f.f_back
            stack.reverse()  # root-first, the folded-stack convention
            folded.append(((names.get(ident, f"tid-{ident}"), tuple(stack)), 1))
        with self._lock:
            self._samples += 1
            for key, n in folded:
                if key not in self._stacks and len(self._stacks) >= self.MAX_STACKS:
                    self._dropped += 1
                    continue
                self._stacks[key] = self._stacks.get(key, 0) + n

    # ------------------------------------------------------------ queries
    @property
    def samples(self) -> int:
        return self._samples

    def stacks(self, limit: int = 0) -> list[dict]:
        """Folded stacks, heaviest-first: [{thread, affinity, stack,
        count}]."""
        with self._lock:
            items = sorted(
                self._stacks.items(), key=lambda kv: kv[1], reverse=True
            )
        out = [
            {
                "thread": thread,
                "affinity": thread_affinity(thread),
                "stack": list(stack),
                "count": count,
            }
            for (thread, stack), count in items
        ]
        return out[:limit] if limit else out

    def top(self, limit: int = 20) -> list[dict]:
        """Leaf-frame self-time attribution per thread: where the samples
        actually landed — the ``rpk debug profile --top`` table."""
        agg: dict[tuple[str, str], int] = {}
        with self._lock:
            for (thread, stack), count in self._stacks.items():
                leaf = stack[-1] if stack else "<no python frame>"
                k = (thread, leaf)
                agg[k] = agg.get(k, 0) + count
        rows = [
            {
                "thread": thread,
                "affinity": thread_affinity(thread),
                "frame": leaf,
                "samples": count,
            }
            for (thread, leaf), count in agg.items()
        ]
        rows.sort(key=lambda r: r["samples"], reverse=True)
        return rows[:limit] if limit else rows

    def folded(self) -> list[str]:
        """flamegraph.pl folded-stack lines: ``thread;root;...;leaf N``."""
        return [
            ";".join([s["thread"], *s["stack"]]) + f" {s['count']}"
            for s in self.stacks()
        ]

    def summary(self) -> dict:
        with self._lock:
            n_stacks = len(self._stacks)
        return {
            "running": self.running,
            "hz": self.hz,
            "samples": self._samples,
            "distinct_stacks": n_stacks,
            "dropped": self._dropped,
            "started_ts": self._started_ts,
        }


# ================================================================ pulse
class Pulse:
    """The process-wide pandapulse facade: flight recorder + wall
    profiler + Chrome trace export. One instance (``pulse`` below),
    configured from broker config at app start."""

    def __init__(self) -> None:
        self.recorder = FlightRecorder()
        self.profiler = WallProfiler()
        self._installed = False

    # ------------------------------------------------------------ config
    @property
    def enabled(self) -> bool:
        return self._installed

    def configure(
        self,
        *,
        enabled: bool | None = None,
        ring_capacity: int | None = None,
        profile_hz: float | None = None,
    ) -> None:
        if ring_capacity is not None:
            self.recorder.configure(ring_capacity)
        if enabled is not None:
            if enabled and not self._installed:
                tracer.set_sink(self.recorder.record)
                self._installed = True
            elif not enabled and self._installed:
                tracer.set_sink(None)
                self._installed = False
        self.profiler.configure(profile_hz)

    def reset(self) -> None:
        self.recorder.reset()
        self.profiler.reset()

    # ------------------------------------------------------------ surfaces
    def snapshot(self, top: int = 20) -> dict:
        """The ``GET /v1/profile`` body."""
        return {
            "enabled": self._installed,
            "tracing": tracer.enabled,
            "recorder": self.recorder.summary(),
            "profiler": self.profiler.summary(),
            "stage_totals_s": {
                k: round(v, 6)
                for k, v in sorted(self.recorder.stage_totals().items())
            },
            "top": self.profiler.top(top),
        }

    def timeline(
        self,
        launches: int = 0,
        journal_entries: list[dict] | None = None,
        journal_margin_s: float = 2.0,
    ) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable) for the newest
        ``launches`` launch groups (0 = every launch in the ring), with
        governor verdicts and admission-shed episodes as instant events on
        the same clock. ``journal_entries=None`` pulls the live process
        decision journal."""
        groups = self.recorder.launches(limit=launches)
        if journal_entries is None:
            # lazy: observability must stay importable without coproc
            from redpanda_tpu.coproc.governor import journal

            journal_entries = journal.entries()
        node = tracer.node_id
        pid_default = node if node is not None else 0
        events: list[dict] = []
        tids: dict[tuple[int, str], int] = {}
        pids_seen: set[int] = set()

        def tid_of(pid: int, thread: str) -> int:
            key = (pid, thread)
            t = tids.get(key)
            if t is None:
                t = tids[key] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid, "tid": t,
                    "args": {
                        "name": f"{thread} [{thread_affinity(thread)}]"
                    },
                })
            if pid not in pids_seen:
                pids_seen.add(pid)
                events.append({
                    "ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "args": {"name": f"broker node {pid}"},
                })
            return t

        t_min = None
        t_max = None
        for g in groups:
            for s in g["slices"]:
                pid = s.get("node")
                pid = pid_default if pid is None else pid
                ev = {
                    "name": s["name"],
                    "ph": "X",
                    "ts": s["start_us"],
                    "dur": max(int(s["dur_us"]), 1),
                    "pid": pid,
                    "tid": tid_of(pid, s.get("thread", "?")),
                    "cat": "derived" if s.get("derived") else "span",
                    "args": {
                        "trace_id": s["trace_id"],
                        # span_id stays: the cluster-timeline assembler
                        # dedupes by it (in-process stacks share one
                        # recorder, so every node's fetch returns the
                        # same spans)
                        **{
                            k: v for k, v in s.items()
                            if k not in (
                                "trace_id", "name", "start_us", "dur_us",
                                "thread", "node", "derived",
                            )
                        },
                    },
                }
                events.append(ev)
                t_min = ev["ts"] if t_min is None else min(t_min, ev["ts"])
                end = ev["ts"] + ev["dur"]
                t_max = end if t_max is None else max(t_max, end)
        # journal entries ride the same clock: wall ts re-anchored on the
        # tracer's (epoch_wall, epoch_perf) pair. With launches in view,
        # only entries inside the window (+/- margin) inject — a 256-deep
        # journal must not bury a 10-launch timeline; with none, the
        # newest entries still render so `rpk debug profile --perfetto` on
        # an idle broker shows the decision history.
        margin_us = journal_margin_s * 1e6
        injected = 0
        for e in journal_entries:
            ts_us = (e["ts"] - tracer.epoch_wall) * 1e6
            if t_min is not None and not (
                t_min - margin_us <= ts_us <= t_max + margin_us
            ):
                continue
            pid = pid_default
            ev = {
                "name": f"{e['domain']}:{e['verdict']}",
                "ph": "i",
                "s": "p",  # process-scoped instant: a governor decision
                "ts": max(ts_us, 0.0),
                "pid": pid,
                "tid": tid_of(pid, "governor"),
                "cat": "governor",
                "args": {
                    "seq": e.get("seq"),
                    "reason": e.get("reason"),
                    "inputs": e.get("inputs"),
                },
            }
            events.append(ev)
            injected += 1
        # pandatrend counter tracks (ROADMAP 7c): the metrics-history
        # ring's derived series as ph:"C" events on the SAME span clock —
        # occupancy, pressure, shed rate, launch knobs, colcache, inflight
        # gate render as counter lanes under the launch slices. Window
        # filtering matches the journal instants: with launches in view
        # only in-window samples (± margin) emit; an idle broker's
        # timeline still shows its whole retained trend.
        from redpanda_tpu.observability.history import history

        counter_events = history.counter_tracks(
            pid=pid_default,
            t_min_us=t_min,
            t_max_us=t_max,
            margin_us=margin_us,
        )
        events.extend(counter_events)
        return {
            "displayTimeUnit": "ms",
            "traceEvents": events,
            "node": node,
            "epoch": tracer.epoch_wall,
            "launches": len(groups),
            "journal_events": injected,
            "counter_events": len(counter_events),
        }


# Process-wide instance, like tracer/registry/slo: subsystems import this;
# app startup configures it from broker config.
pulse = Pulse()

__all__ = [
    "FlightRecorder",
    "Pulse",
    "WallProfiler",
    "pulse",
    "thread_affinity",
]
