"""pandatrend: the always-on, bounded metrics-history ring.

ROADMAP 7c/7d's missing substrate: every signal the repo already emits
(registry counters/gauges/histograms, budget-plane occupancy, governor
knobs, colcache hits) is point-in-time — a scrape says where the broker
IS, never where it has BEEN. This module keeps a short, byte-bounded ring
of time-bucketed DELTA windows over the whole registry so that:

- ``GET /v1/history`` / ``rpk debug trend`` answer "what changed in the
  last N minutes" without an external prometheus;
- ``Pulse.timeline()`` renders the windows as Perfetto counter tracks
  (``ph:"C"``) on the SAME clock as launch slices (ROADMAP 7c);
- EWMA-band breaches (tail latency, shed rate, occupancy, colcache hit
  rate) journal into the governor's ``trend`` domain — a regression is an
  incident entry with measured inputs, not folklore.

Sampling discipline mirrors the pulse ring: ``history_interval_s=0``
means OFF and spawns NO thread (pinned by the ``history_overhead``
microbench); the recorder thread holds no lock while snapshotting (the
registry's snapshot paths are GIL-atomic materializations, PR-6 round 4
discipline), and the ring is bounded BOTH by window count and by an
estimated byte budget — a label-cardinality explosion evicts history, it
never grows the process.

Derivations reuse the SLO engine's machinery verbatim: histogram windows
are ``slo._hist_window`` snapshots diffed with ``slo.window_delta`` and
quantile-interpolated with ``slo.interpolate_quantile(hdr_layout=True)``
— one bucket-math implementation across SLO verdicts, federation merges
and trend windows.
"""

from __future__ import annotations

import math
import threading
import time

from redpanda_tpu.metrics import _labelstr
from redpanda_tpu.metrics import registry as default_registry

DEFAULT_INTERVAL_S = 5.0
DEFAULT_WINDOWS = 240            # 20 min at the 5s default cadence
DEFAULT_MAX_BYTES = 4 * 1024 * 1024

# EWMA band parameters (trend breach detection). Warmup gates the band:
# the first few windows of a fresh process are all "anomalous" relative
# to nothing; a band needs history before it may accuse.
EWMA_ALPHA = 0.3
EWMA_BAND_SIGMA = 3.0
EWMA_WARMUP_WINDOWS = 8

_SHED_SUFFIX = "_admission_shed_total"


def _estimate_bytes(win: dict) -> int:
    """Cheap, stable size estimate for the byte budget: key lengths plus
    a flat per-entry cost. json.dumps-per-window would dominate the very
    overhead this recorder is gated on."""
    n = 64
    for section in ("counters", "gauges", "hists", "tracks"):
        for k, v in win.get(section, {}).items():
            n += len(k) + 16
            if isinstance(v, dict):
                n += 16 * len(v)
    return n


class HistoryRecorder:
    """Bounded ring of per-interval registry delta windows.

    One instance per process (``history`` below), configured from broker
    config at app start. Tests and the microbench drive private
    instances; ``sample_once()`` is the whole hot path."""

    def __init__(self, registry=None) -> None:
        self.registry = registry if registry is not None else default_registry
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._ring_bytes = 0
        self._interval_s = 0.0
        self._max_windows = DEFAULT_WINDOWS
        self._max_bytes = DEFAULT_MAX_BYTES
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._stop = False
        # previous cumulative snapshots (recorder-thread-private in the
        # steady state; guarded by _lock for reset()/sample_once races)
        self._prev_counters: dict[str, float] | None = None
        self._prev_hists: dict[str, dict] | None = None
        self._prev_ts: float | None = None
        # EWMA state per watched series: {name: (mean, var, n, breached)}
        self._ewma: dict[str, list] = {}
        self._samples_total = 0
        self._breaches_total = 0
        self._evicted_total = 0

    # ------------------------------------------------------------ config
    @property
    def interval_s(self) -> float:
        return self._interval_s

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def breaches_total(self) -> int:
        return self._breaches_total

    @property
    def samples_total(self) -> int:
        return self._samples_total

    def configure(
        self,
        *,
        interval_s: float | None = None,
        windows: int | None = None,
        max_bytes: int | None = None,
    ) -> None:
        """Apply knobs; start/stop the recorder thread to match.
        ``interval_s=0`` is the documented OFF posture: no thread exists
        afterwards (not a parked one — NONE, the pulse profiler_hz=0
        contract)."""
        if windows is not None:
            self._max_windows = max(1, int(windows))
        if max_bytes is not None:
            self._max_bytes = max(1024, int(max_bytes))
        if interval_s is not None:
            self._interval_s = max(0.0, float(interval_s))
        with self._lock:
            self._trim_locked()
        want_thread = self._interval_s > 0
        if want_thread and not self.running:
            self._stop = False
            self._wake.clear()
            self._thread = threading.Thread(
                target=self._loop, name="rptpu-history-recorder", daemon=True
            )
            self._thread.start()
        elif not want_thread and self.running:
            self.stop()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop = True
        self._wake.set()
        t.join(timeout=5.0)
        self._thread = None

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._ring_bytes = 0
            self._prev_counters = None
            self._prev_hists = None
            self._prev_ts = None
            self._ewma.clear()

    # ------------------------------------------------------------ sampling
    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self._interval_s or DEFAULT_INTERVAL_S)
            if self._stop:
                return
            try:
                self.sample_once()
            except Exception:
                # the recorder must outlive any single bad scrape; a
                # throwing gauge fn or a mid-registration race costs one
                # window, never the thread
                pass

    def _cumulative(self) -> tuple[dict, dict, dict]:
        """(counters, gauges, hist_windows) cumulative snapshot.

        GIL-atomic discipline (PR-6 round 4): materialize the registry
        dicts with one C-level ``list()`` call each, then iterate the
        private lists — the live dicts keep growing under load and a
        plain ``.values()`` walk races registration with
        "dict changed size during iteration"."""
        from redpanda_tpu.observability.slo import _hist_window

        reg = self.registry
        counters: dict[str, float] = {}
        for c in list(reg._counters.values()):
            counters[f"{c.name}{_labelstr(c.labels)}"] = float(c.value)
        gauges: dict[str, float] = {}
        for g in list(reg._gauges.values()):
            try:
                v = g.fn()
            except Exception:
                # gauge fns are caller-supplied closures; render_prometheus
                # makes the same trade (NaN, not a dead scrape)
                v = None
            if isinstance(v, (int, float)) and math.isfinite(v):
                gauges[f"{g.name}{_labelstr(g.labels)}"] = float(v)
        hists: dict[str, dict] = {}
        for h in list(reg._hists.values()):
            hists[f"{h.name}{_labelstr(h.labels)}"] = _hist_window(h)
        return counters, gauges, hists

    def sample_once(self) -> dict | None:
        """Take one delta window NOW and append it to the ring. Returns
        the stored window (None for the very first call, which only
        anchors the cumulative baseline)."""
        from redpanda_tpu.observability.slo import (
            interpolate_quantile, window_delta,
        )

        now = time.time()
        counters, gauges, hists = self._cumulative()
        with self._lock:
            prev_c, prev_h, prev_ts = (
                self._prev_counters, self._prev_hists, self._prev_ts,
            )
            self._prev_counters, self._prev_hists = counters, hists
            self._prev_ts = now
            self._samples_total += 1
        if prev_ts is None:
            return None
        dt = max(now - prev_ts, 1e-9)
        win: dict = {"ts": now, "dur_s": round(dt, 3)}
        wc: dict[str, dict] = {}
        for key, val in counters.items():
            delta = val - (prev_c or {}).get(key, 0.0)
            if delta:
                wc[key] = {"delta": delta, "rate": round(delta / dt, 3)}
        wh: dict[str, dict] = {}
        for key, after in hists.items():
            before = (prev_h or {}).get(key)
            d = window_delta(after, before)
            if d["count"] <= 0:
                continue
            row = {"count": d["count"], "rate": round(d["count"] / dt, 3)}
            for q, label in ((50.0, "p50"), (99.0, "p99"), (99.9, "p999")):
                v = interpolate_quantile(
                    d["buckets"], d["count"], q,
                    observed_max=d["max"], hdr_layout=True,
                )
                if v is not None:
                    row[label] = round(v, 1)
            row["max"] = d["max"]
            wh[key] = row
        win["counters"] = wc
        win["gauges"] = gauges
        win["hists"] = wh
        win["tracks"] = self._derive_tracks(wc, gauges, wh, dt)
        win["bytes"] = _estimate_bytes(win)
        with self._lock:
            self._ring.append(win)
            self._ring_bytes += win["bytes"]
            self._trim_locked()
        self._judge_window(win)
        return win

    def _trim_locked(self) -> None:
        evicted = 0
        while self._ring and (
            len(self._ring) > self._max_windows
            or self._ring_bytes > self._max_bytes
        ):
            old = self._ring.pop(0)
            self._ring_bytes -= old.get("bytes", 0)
            evicted += 1
        if not self._ring:
            self._ring_bytes = 0
        self._evicted_total += evicted

    # ------------------------------------------------------------ derived tracks
    def _derive_tracks(
        self, wc: dict, gauges: dict, wh: dict, dt: float
    ) -> dict[str, float]:
        """The named trend series: what the EWMA judge watches and what
        the timeline renders as counter tracks. Derived from whole-window
        deltas, so one slow scrape can't alias a rate."""
        tracks: dict[str, float] = {}
        # per-account occupancy off the budget-plane held/limit gauges
        for key, held in gauges.items():
            if not key.startswith("resource_account_held_bytes{"):
                continue
            acct = key.split('account="', 1)[-1].split('"', 1)[0]
            limit = gauges.get(
                f'resource_account_limit_bytes{{account="{acct}"}}', 0.0
            )
            if limit and limit > 0:
                tracks[f"occupancy:{acct}"] = round(held / limit, 4)
        if "resource_pressure_state" in gauges:
            tracks["pressure"] = gauges["resource_pressure_state"]
        # shed rate per subsystem + aggregate
        shed_total = 0.0
        for key, row in wc.items():
            name = key.split("{", 1)[0]
            if name.endswith(_SHED_SUFFIX):
                sub = name[: -len(_SHED_SUFFIX)]
                tracks[f"shed_rate:{sub}"] = row["rate"]
                shed_total += row["rate"]
        tracks["shed_rate"] = round(shed_total, 3)
        # colcache hit rate over THIS window's delta, not the lifetime
        hits = wc.get('coproc_colcache_total{outcome="hit"}', {}).get("delta", 0.0)
        miss = wc.get('coproc_colcache_total{outcome="miss"}', {}).get("delta", 0.0)
        if hits + miss > 0:
            tracks["colcache_hit_rate"] = round(hits / (hits + miss), 4)
            tracks["colcache_hits_per_s"] = round(hits / dt, 3)
        # governor launch knobs + the rpc inflight gate (live gauges)
        for key, val in gauges.items():
            if key.startswith("coproc_autotune_knob{"):
                knob = key.split('knob="', 1)[-1].split('"', 1)[0]
                tracks[f"knob:{knob}"] = val
            elif key.startswith("rpc_inflight_requests"):
                tracks["inflight:rpc"] = val
        # tail latency per histogram family (EWMA watch input)
        for key, row in wh.items():
            if "p999" in row:
                name = key.split("{", 1)[0]
                prev = tracks.get(f"p999_us:{name}")
                v = float(row["p999"])
                tracks[f"p999_us:{name}"] = max(prev, v) if prev else v
        return tracks

    # ------------------------------------------------------------ EWMA judge
    # direction per watched-series prefix: +1 = breach when ABOVE band
    # (latency, sheds, occupancy, pressure), -1 = breach when BELOW
    # (hit rates — a cold cache is the regression)
    _WATCH_DIRECTION = (
        ("p999_us:", +1), ("shed_rate", +1), ("occupancy:", +1),
        ("pressure", +1), ("colcache_hit_rate", -1),
    )

    def _judge_window(self, win: dict) -> None:
        """EWMA band check over the derived tracks; breaches journal into
        the governor's TREND domain once per excursion (episode posture —
        re-arms when the series returns inside the band)."""
        for name, value in win["tracks"].items():
            direction = 0
            for prefix, d in self._WATCH_DIRECTION:
                if name.startswith(prefix):
                    direction = d
                    break
            if direction == 0:
                continue
            with self._lock:
                st = self._ewma.get(name)
                if st is None:
                    st = self._ewma[name] = [float(value), 0.0, 1, False]
                    continue
                mean, var, n, breached = st
                band = EWMA_BAND_SIGMA * math.sqrt(max(var, 0.0))
                dev = (value - mean) * direction
                is_breach = (
                    n >= EWMA_WARMUP_WINDOWS
                    and dev > band
                    and dev > abs(mean) * 0.05 + 1e-9
                )
                fire = is_breach and not breached
                # breach windows do NOT update the band: an excursion must
                # not teach the band that the excursion is normal
                if not is_breach:
                    delta = value - mean
                    st[0] = mean + EWMA_ALPHA * delta
                    st[1] = (1 - EWMA_ALPHA) * (var + EWMA_ALPHA * delta * delta)
                st[2] = n + 1
                st[3] = is_breach
                if fire:
                    self._breaches_total += 1
            if fire:
                self._journal_breach(name, value, mean, band, win)

    def _journal_breach(
        self, name: str, value: float, mean: float, band: float, win: dict
    ) -> None:
        # lazy: observability must stay importable without coproc
        from redpanda_tpu.coproc.governor import TREND, journal_record

        journal_record(
            TREND, "breach",
            f"{name} left its EWMA band: {value:.4g} vs mean "
            f"{mean:.4g} ± {band:.4g} ({EWMA_BAND_SIGMA}σ)",
            inputs={
                "series": name, "value": value,
                "ewma_mean": round(mean, 4), "band": round(band, 4),
                "window_ts": win["ts"], "window_dur_s": win["dur_s"],
            },
            config={
                "interval_s": self._interval_s,
                "alpha": EWMA_ALPHA, "sigma": EWMA_BAND_SIGMA,
            },
        )

    # ------------------------------------------------------------ views
    def windows(self, limit: int = 0) -> list[dict]:
        """Newest-last windows (chronological — the timeline order)."""
        with self._lock:
            items = list(self._ring)
        return items[-limit:] if limit else items

    def snapshot(self, series: str | None = None, limit: int = 0) -> dict:
        """The ``GET /v1/history`` body. ``series`` substring-filters
        every per-series section (counters/gauges/hists/tracks) so a
        narrow question doesn't ship the whole registry history."""
        wins = self.windows(limit)
        if series:
            needle = series
            filtered = []
            for w in wins:
                fw = {"ts": w["ts"], "dur_s": w["dur_s"]}
                for section in ("counters", "gauges", "hists", "tracks"):
                    fw[section] = {
                        k: v for k, v in w.get(section, {}).items()
                        if needle in k
                    }
                filtered.append(fw)
            wins = filtered
        with self._lock:
            meta = {
                "interval_s": self._interval_s,
                "recorder_running": self.running,
                "windows_retained": len(self._ring),
                "windows_max": self._max_windows,
                "bytes": self._ring_bytes,
                "bytes_max": self._max_bytes,
                "samples_total": self._samples_total,
                "breaches_total": self._breaches_total,
                "evicted_total": self._evicted_total,
                "ewma": {
                    name: {
                        "mean": round(st[0], 4),
                        "band": round(
                            EWMA_BAND_SIGMA * math.sqrt(max(st[1], 0.0)), 4
                        ),
                        "n": st[2],
                        "breached": st[3],
                    }
                    for name, st in sorted(self._ewma.items())
                },
            }
        meta["windows"] = wins
        if series:
            meta["series_filter"] = series
        return meta

    def counter_tracks(
        self,
        pid: int,
        tid: int = 0,
        t_min_us: float | None = None,
        t_max_us: float | None = None,
        margin_us: float = 2e6,
    ) -> list[dict]:
        """Perfetto ``ph:"C"`` counter events for every derived track,
        re-anchored on the span clock (wall ts minus the tracer's wall
        epoch — the exact journal-instant math in ``Pulse.timeline``).
        With a launch window in view only in-window samples (± margin)
        emit; without one the whole ring renders (ROADMAP 7c: an idle
        broker's timeline still shows its recent trend)."""
        from redpanda_tpu.observability.trace import tracer

        events: list[dict] = []
        for w in self.windows():
            ts_us = (w["ts"] - tracer.epoch_wall) * 1e6
            if t_min_us is not None and not (
                t_min_us - margin_us <= ts_us <= (t_max_us or ts_us) + margin_us
            ):
                continue
            for name, value in sorted(w.get("tracks", {}).items()):
                events.append({
                    "name": f"trend:{name}",
                    "ph": "C",
                    "ts": max(ts_us, 0.0),
                    "pid": pid,
                    "tid": tid,
                    "cat": "trend",
                    "args": {"value": value},
                })
        return events


# Process-wide instance, like tracer/registry/slo/pulse: subsystems import
# this; app startup configures it from broker config.
history = HistoryRecorder()

__all__ = ["HistoryRecorder", "history"]
