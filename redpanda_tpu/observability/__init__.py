"""pandaprobe: end-to-end span tracing + per-subsystem latency probes.

Two complementary layers:

* ``probes`` — always-on prometheus histograms/counters per subsystem,
  exported at ``/metrics`` (the reference's probe.h pattern).
* ``tracer`` — an opt-in span tracer (``trace_enabled`` config) that
  stitches one batch's produce → raft → TPU-transform → fetch journey into
  a single trace retrievable at ``/v1/trace/recent`` and renderable with
  ``tools/traceview.py`` (or ``rpk debug trace``).
"""

from redpanda_tpu.observability import probes
from redpanda_tpu.observability.trace import Tracer, tracer

__all__ = ["Tracer", "probes", "tracer"]

# pandapulse (observability/pulse.py) is imported lazily by its consumers
# (admin, cli, engine tests): importing it here would make every probes
# user pay its module load, and the flight recorder only matters where it
# is explicitly configured.
